"""Trainer→server promotion: snapshot → canary reload → fleet rollout.

``Promoter`` drives a servable export (a ``ZooModel.save_model`` dir)
onto an ordered set of serving instances: the designated **canary**
first, then the rest of the fleet one by one.  Each instance's reload
runs the full canary machinery already inside
:meth:`ClusterServing.reload_model` (load + prewarm + synthetic-batch
predict off the serve path), and the promoter then verifies the
instance *reports* the new version live via ``health_snapshot()`` —
the stamp only lands on a successful swap, so a lying rollout is
impossible.

The rollback state machine is two-phase and exception-driven:

    PROMOTING(inst_i)  --ok-->  PROMOTING(inst_{i+1})  --all ok-->  LANDED
         |failure
         v
    ROLLING_BACK: every already-promoted instance reloads its prior
    (path, version), newest-first; then PromotionError raises.

A failure at the canary therefore touches nothing else; a failure
mid-rollout restores the fleet to a single consistent prior version.
Instances keep serving throughout — ``reload_model`` swaps atomically
and never drops a request — so a mid-rollout chaos kill costs zero
terminals.  Fault-injectable at ``online.promote`` (fires per-instance,
before that instance's reload) on top of the existing
``serving.reload`` site inside the reload itself.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common import faults
from ..common import metrics as zoo_metrics
from ..common.config import global_config
from ..ops import events as ops_events

logger = logging.getLogger(__name__)

_E_PROMOTION = ops_events.event_type(
    "online.promotion",
    "Rolling promotion terminal (outcome=landed|rolled_back, version).")

_M_PROMOTIONS = zoo_metrics.counter(
    "online.promotions_total",
    "Promotion attempts by terminal outcome (landed / rolled_back).",
    labels=("outcome",))
_M_PROMOTE_S = zoo_metrics.histogram(
    "online.promote_seconds",
    "Wall time from promotion start to the new version live fleet-wide "
    "(or to rollback complete on failure).")


def export_servable(zoo_model, estimator, path: str) -> str:
    """Materialize the trainer's live params as a servable ZooModel
    export (``zoo_model.json`` + ``weights/``) at ``path``, whose
    basename becomes the promotion version label.

    The export *unshards*: serving replicas do whole-table dense
    lookups, so sharded embedding tables drop their mesh-padding rows
    and the exported config pins ``shard_embeddings=False``.  Non-param
    model state (e.g. batchnorm statistics) is carried over where the
    unsharded twin has a same-shaped slot; the sharded engine's
    exchange-blob stash is not a servable artifact and is left behind.
    """
    import jax
    import numpy as np

    config = dict(zoo_model.get_config())
    if "shard_embeddings" in config:
        config["shard_embeddings"] = False
    serve = type(zoo_model)(**config)
    serve._ensure_built()
    if not hasattr(serve.model, "loss_fn"):
        serve.default_compile()
    params0, state0 = serve.model.build(jax.random.PRNGKey(0))

    def _fit(ref_tree, trained_tree, strict):
        out = {}
        for lname, group in ref_tree.items():
            src = (trained_tree or {}).get(lname, {})
            out[lname] = {}
            for k, ref in group.items():
                ref = np.asarray(ref)
                w = src.get(k)
                w = None if w is None else np.asarray(w)
                if w is not None and w.ndim == ref.ndim \
                        and w.shape[1:] == ref.shape[1:] \
                        and w.shape[0] >= ref.shape[0]:
                    w = w[:ref.shape[0]]  # drop mesh-padding rows
                if w is None or w.shape != ref.shape:
                    if strict:
                        raise ValueError(
                            f"cannot export {lname}/{k}: trained shape "
                            f"{None if w is None else w.shape} does not "
                            f"map onto servable shape {ref.shape}")
                    w = ref  # derived state: fall back to fresh init
                out[lname][k] = w
        return out

    trained = jax.device_get(estimator.params)
    trained_state = jax.device_get(estimator.model_state)
    est_s = serve.model.get_estimator()
    est_s.set_params(_fit(params0, trained, strict=True))
    est_s.set_model_state(_fit(state0 or {}, trained_state, strict=False))
    serve.save_model(path)
    return path


class PromotionError(RuntimeError):
    """A rollout failed; the fleet was rolled back to the prior version."""


class RollbackError(PromotionError):
    """A rollout failed AND rolling an instance back also failed — the
    fleet may be version-split and needs operator attention."""


class Promoter:
    """Canary-first rollout coordinator over serving handles.

    ``servers`` is an ordered ``{name: server}`` mapping; each server
    exposes ``reload_model(path, model_type=..., version=...)``,
    ``health_snapshot()`` and a ``config`` with ``model_path`` —
    :class:`~analytics_zoo_tpu.serving.server.ClusterServing` qualifies
    directly, in-process or driven over its queue.  ``canary`` names
    the instance that takes the new version first (default: the first
    mapping entry)."""

    def __init__(self, servers: Dict[str, Any],
                 canary: Optional[str] = None,
                 model_type: str = "zoo",
                 verify_timeout_s: Optional[float] = None):
        if not servers:
            raise ValueError("Promoter needs at least one server")
        self.servers = dict(servers)
        self.canary = canary if canary is not None else next(iter(servers))
        if self.canary not in self.servers:
            raise ValueError(f"canary {self.canary!r} not in servers")
        self.model_type = model_type
        cfg = global_config()
        self.verify_timeout_s = float(
            verify_timeout_s if verify_timeout_s is not None
            else cfg.get("online.rollout_verify_timeout_s"))

    # -- internals ------------------------------------------------------------

    def _rollout_order(self) -> List[str]:
        rest = [n for n in self.servers if n != self.canary]
        return [self.canary] + rest

    def _reload_one(self, name: str, path: Optional[str], version: str,
                    model_type: Optional[str]) -> None:
        """The single fault-injectable promotion step (one call site for
        the ``online.promote`` chaos schedule: arm ``at=k`` — 1-based —
        to kill the rollout at the k-th instance, canary being the 1st)."""
        faults.inject("online.promote")
        srv = self.servers[name]
        srv.reload_model(path, model_type=model_type, version=version)

    def _verify_live(self, name: str, version: str) -> None:
        srv = self.servers[name]
        deadline = time.monotonic() + self.verify_timeout_s
        live = None
        while True:
            live = srv.health_snapshot().get("model_version")
            if live == version:
                return
            if time.monotonic() >= deadline:
                raise PromotionError(
                    f"instance {name!r} reports model_version={live!r} "
                    f"after reload, expected {version!r}")
            time.sleep(0.01)

    def _rollback(self, done: List[str], prior: Dict[str, Any]) -> None:
        failures = []
        for name in reversed(done):
            path, version, model = prior[name]
            try:
                srv = self.servers[name]
                if path:
                    srv.reload_model(path, model_type=self.model_type,
                                     version=version)
                else:
                    # instance was born with an inline model object —
                    # swap the retained object back in (and undo the
                    # model_path stamp the forward reload left behind)
                    srv.reload_model(model=model, version=version)
                    srv.config.model_path = ""
                self._verify_live(name, version)
            except Exception as e:  # keep unwinding; report at the end
                logger.exception("rollback of %s to %r failed", name,
                                 version)
                failures.append((name, e))
        if failures:
            raise RollbackError(
                "rollback failed on %s — fleet may be version-split" %
                ", ".join(f"{n} ({e!r})" for n, e in failures))

    # -- API ------------------------------------------------------------------

    def promote(self, model_path: str, version: Optional[str] = None,
                model_type: Optional[str] = None) -> str:
        """Roll ``model_path`` across the fleet, canary first.  Returns
        the landed version label.  On any failure the already-promoted
        instances are rolled back to their prior (path, version) and
        :class:`PromotionError` raises — the fleet never stays split."""
        import os
        version = version or (os.path.basename(
            str(model_path).rstrip("/")) or "unversioned")
        model_type = model_type or self.model_type
        # retain (path, version, live model object) per instance so a
        # rollback works even for instances born with inline models
        prior = {n: (getattr(s.config, "model_path", None) or None,
                     getattr(s, "model_version", "inline-0"),
                     getattr(s, "model", None))
                 for n, s in self.servers.items()}
        t0 = time.monotonic()
        done: List[str] = []
        try:
            for name in self._rollout_order():
                self._reload_one(name, model_path, version, model_type)
                self._verify_live(name, version)
                done.append(name)
                logger.info("promotion %s live on %s%s", version, name,
                            " (canary)" if name == self.canary else "")
        except Exception as e:
            try:
                self._rollback(done, prior)
            finally:
                _M_PROMOTIONS.labels(outcome="rolled_back").inc()
                _E_PROMOTION.emit(outcome="rolled_back", version=version)
                _M_PROMOTE_S.observe(time.monotonic() - t0)
            if isinstance(e, PromotionError):
                raise
            raise PromotionError(
                f"promotion of {version!r} failed at instance "
                f"{self._rollout_order()[len(done)]!r} ({e!r}); fleet "
                f"rolled back to prior versions") from e
        _M_PROMOTIONS.labels(outcome="landed").inc()
        _E_PROMOTION.emit(outcome="landed", version=version)
        _M_PROMOTE_S.observe(time.monotonic() - t0)
        return version
