from .time_sequence import TimeSequenceFeatureTransformer  # noqa: F401
