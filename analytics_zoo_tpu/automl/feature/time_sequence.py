"""Time-sequence feature engineering (reference
``automl/feature/time_sequence.py:30``: datetime features + standard scaling
+ rolling windows; save/restore of scaler state)."""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

_DT_FEATURES = ["hour", "day", "weekday", "month", "is_weekend"]


class TimeSequenceFeatureTransformer:
    def __init__(self, future_seq_len: int = 1, dt_col: str = "datetime",
                 target_col: str = "value",
                 extra_features_col: Optional[Sequence[str]] = None,
                 drop_missing: bool = True):
        self.future_seq_len = future_seq_len
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = list(extra_features_col or [])
        self.drop_missing = drop_missing
        self.past_seq_len: Optional[int] = None
        self.selected_features: List[str] = []
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None

    # -- feature list (datetime-derived + extras) -----------------------------

    def get_feature_list(self, input_df=None) -> List[str]:
        return _DT_FEATURES + list(self.extra_features_col)

    def _feature_matrix(self, df) -> np.ndarray:
        import pandas as pd
        dt = pd.to_datetime(df[self.dt_col])
        cols = {
            "hour": dt.dt.hour.to_numpy(np.float32),
            "day": dt.dt.day.to_numpy(np.float32),
            "weekday": dt.dt.weekday.to_numpy(np.float32),
            "month": dt.dt.month.to_numpy(np.float32),
            "is_weekend": (dt.dt.weekday >= 5).to_numpy(np.float32),
        }
        feats = [df[self.target_col].to_numpy(np.float32)[:, None]]
        for name in self.selected_features:
            if name in cols:
                feats.append(cols[name][:, None])
            elif name in df.columns:
                feats.append(df[name].to_numpy(np.float32)[:, None])
            else:
                raise ValueError(f"unknown feature '{name}'")
        return np.concatenate(feats, axis=1)

    # -- scaling --------------------------------------------------------------

    def _fit_scaler(self, data: np.ndarray) -> None:
        self._mean = data.mean(axis=0)
        self._std = np.maximum(data.std(axis=0), 1e-8)

    def _scale(self, data: np.ndarray) -> np.ndarray:
        return (data - self._mean) / self._std

    def _unscale_target(self, y: np.ndarray) -> np.ndarray:
        return y * self._std[0] + self._mean[0]

    # -- rolling --------------------------------------------------------------

    def _roll(self, data: np.ndarray, past: int, future: int
              ) -> Tuple[np.ndarray, np.ndarray]:
        n = len(data) - past - future + 1
        if n <= 0:
            raise ValueError(f"series of {len(data)} rows too short for "
                             f"past={past} future={future}")
        idx = np.arange(past)[None, :] + np.arange(n)[:, None]
        x = data[idx]
        yidx = np.arange(future)[None, :] + np.arange(n)[:, None] + past
        y = data[yidx][:, :, 0]  # target is column 0
        return x.astype(np.float32), y.astype(np.float32)

    # -- the fit/transform contract -------------------------------------------

    def fit_transform(self, input_df, **config) -> Tuple[np.ndarray, np.ndarray]:
        self.past_seq_len = int(config.get("past_seq_len", 2))
        self.selected_features = list(config.get("selected_features", []))
        dfs = input_df if isinstance(input_df, list) else [input_df]
        xs, ys = [], []
        fitted = False
        for df in dfs:
            df = self._clean(df)
            data = self._feature_matrix(df)
            if not fitted:
                self._fit_scaler(data)
                fitted = True
            x, y = self._roll(self._scale(data), self.past_seq_len,
                              self.future_seq_len)
            xs.append(x)
            ys.append(y)
        return np.concatenate(xs), np.concatenate(ys)

    def transform(self, input_df, is_train: bool = True):
        if self.past_seq_len is None:
            raise RuntimeError("fit_transform first")
        df = self._clean(input_df)
        data = self._scale(self._feature_matrix(df))
        if is_train:
            return self._roll(data, self.past_seq_len, self.future_seq_len)
        # test mode: rolling windows only, no labels
        n = len(data) - self.past_seq_len + 1
        idx = np.arange(self.past_seq_len)[None, :] + np.arange(n)[:, None]
        return data[idx].astype(np.float32)

    def post_processing(self, input_df, y_pred, is_train: bool):
        """Unscale predictions back to the target's units."""
        return self._unscale_target(np.asarray(y_pred))

    def _clean(self, df):
        if df[self.target_col].isnull().any():
            if not self.drop_missing:
                raise ValueError("missing values in target column")
            df = df.dropna(subset=[self.target_col])
        return df

    # -- persistence ----------------------------------------------------------

    def save(self, file_path: str) -> None:
        state = {
            "future_seq_len": self.future_seq_len,
            "dt_col": self.dt_col, "target_col": self.target_col,
            "extra_features_col": self.extra_features_col,
            "past_seq_len": self.past_seq_len,
            "selected_features": self.selected_features,
            "mean": None if self._mean is None else self._mean.tolist(),
            "std": None if self._std is None else self._std.tolist(),
        }
        with open(file_path, "w") as f:
            json.dump(state, f)

    def restore(self, file_path: str) -> "TimeSequenceFeatureTransformer":
        with open(file_path) as f:
            state = json.load(f)
        self.future_seq_len = state["future_seq_len"]
        self.dt_col = state["dt_col"]
        self.target_col = state["target_col"]
        self.extra_features_col = state["extra_features_col"]
        self.past_seq_len = state["past_seq_len"]
        self.selected_features = state["selected_features"]
        self._mean = None if state["mean"] is None else np.asarray(state["mean"])
        self._std = None if state["std"] is None else np.asarray(state["std"])
        return self
