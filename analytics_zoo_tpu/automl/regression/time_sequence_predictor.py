"""TimeSequencePredictor (reference
``automl/regression/time_sequence_predictor.py:37``): hyper-parameter search
over (feature transform × model config), returning the fitted
``TimeSequencePipeline``."""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..common.metrics import Evaluator
from ..config.recipe import Recipe, SmokeRecipe
from ..feature.time_sequence import TimeSequenceFeatureTransformer
from ..model import MODEL_REGISTRY
from ..pipeline.time_sequence import TimeSequencePipeline
from ..search.local_search import LocalSearchEngine
from ..search.parallel_search import ParallelSearchEngine


class TimeSequencePredictor:
    def __init__(self, name: str = "automl", future_seq_len: int = 1,
                 dt_col: str = "datetime", target_col: str = "value",
                 extra_features_col: Optional[Sequence[str]] = None,
                 drop_missing: bool = True):
        self.name = name
        self.future_seq_len = future_seq_len
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = extra_features_col
        self.drop_missing = drop_missing
        self.pipeline: Optional[TimeSequencePipeline] = None

    def _trial(self, config: Dict[str, Any], data) -> float:
        train_df, val_df, metric = data
        ft = TimeSequenceFeatureTransformer(
            self.future_seq_len, self.dt_col, self.target_col,
            self.extra_features_col, self.drop_missing)
        model_cls = MODEL_REGISTRY[config.get("model", "LSTM")]
        model = model_cls()
        if hasattr(model, "required_past_seq_len"):
            config = dict(config,
                          past_seq_len=model.required_past_seq_len(config))
        x, y = ft.fit_transform(train_df, **config)
        val = None
        if val_df is not None:
            vx, vy = ft.transform(val_df, is_train=True)
            val = (vx, vy)
        score = model.fit_eval((x, y), validation_data=val, metric=metric,
                               **config)
        self._last = (ft, model)  # engine runs trials sequentially
        if self._best_score is None or self._is_better(score):
            self._best_score = score
            self._best = (ft, model, dict(config))
        return score

    def _is_better(self, score: float) -> bool:
        if self._mode == "max":
            return score > self._best_score
        return score < self._best_score

    def fit(self, input_df, validation_df=None,
            recipe: Optional[Recipe] = None, metric: str = "mse",
            search_engine: str = "local", num_workers: Optional[int] = None,
            search_timeout: Optional[float] = None,
            ) -> TimeSequencePipeline:
        """``search_engine="parallel"`` runs trials in spawned worker
        processes on this host; ``"pod"`` strides them across PodLauncher
        worker processes (the cluster-scale RayTune role), killed after
        ``search_timeout`` seconds (None = wait indefinitely; only the pod
        engine supports a timeout). The winning config is then re-fit
        in-process to build the returned pipeline."""
        recipe = recipe or SmokeRecipe()
        self._best = None
        self._best_score = None
        self._mode = Evaluator.get_metric_mode(metric)
        if search_engine == "parallel":
            if search_timeout is not None:
                raise ValueError(
                    "search_timeout is only supported by the pod engine")
            engine = ParallelSearchEngine(num_workers=num_workers)
        elif search_engine == "pod":
            from ..search.pod_search import PodSearchEngine
            engine = PodSearchEngine(num_workers=num_workers or 2,
                                     timeout=search_timeout)
        elif search_engine == "local":
            if search_timeout is not None:
                raise ValueError(
                    "search_timeout is only supported by the pod engine")
            engine = LocalSearchEngine()
        else:
            raise ValueError(f"search_engine must be local/parallel/pod, "
                             f"got {search_engine!r}")
        ft_probe = TimeSequenceFeatureTransformer(
            self.future_seq_len, self.dt_col, self.target_col,
            self.extra_features_col)
        engine.compile(data=(input_df, validation_df, metric),
                       model_create_fn=None, recipe=recipe, metric=metric,
                       feature_cols=ft_probe.get_feature_list(),
                       fit_fn=self._trial)
        engine.run()
        if self._best is None:
            # parallel engines ran trials in worker processes, so the
            # in-process best tracker never fired: re-fit the winning config
            best_trials = engine.get_best_trials(1)
            if not best_trials:
                raise RuntimeError("no successful trials")
            self._trial(best_trials[0].config,
                        (input_df, validation_df, metric))
        ft, model, config = self._best
        self.pipeline = TimeSequencePipeline(ft, model, config,
                                             name=self.name)
        return self.pipeline
