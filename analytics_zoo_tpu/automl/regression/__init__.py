from .time_sequence_predictor import TimeSequencePredictor  # noqa: F401
