"""Search-space DSL (the ray.tune sampling vocabulary the reference recipes
are written in: ``RandomSample``/``GridSearch`` wrappers in
``automl/search/RayTuneSearchEngine.py``)."""
from __future__ import annotations

import random
from typing import Any, Callable, List, Sequence


class Sampler:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Choice(Sampler):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class Uniform(Sampler):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Sampler):
    def __init__(self, low: float, high: float):
        import math
        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Sampler):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randint(self.low, self.high)


class Grid:
    """Exhaustive axis: the cross product of all Grid axes is enumerated,
    random axes are re-sampled per point (reference GridSearch)."""

    def __init__(self, options: Sequence[Any]):
        self.options = list(options)


class Func(Sampler):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


def choice(options):
    return Choice(options)


def uniform(low, high):
    return Uniform(low, high)


def loguniform(low, high):
    return LogUniform(low, high)


def randint(low, high):
    return RandInt(low, high)


def grid_search(options):
    return Grid(options)


def sample_from(fn):
    return Func(fn)
