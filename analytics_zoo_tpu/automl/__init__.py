"""AutoML (reference ``pyzoo/zoo/automl/**``, SURVEY §2.7): search-engine
abstraction + recipes (search-space DSL) + time-sequence feature engineering
+ built-in TS models + ``TimeSequencePredictor`` → ``TimeSequencePipeline``.

TPU shape: trials train through the shared Estimator on-device loop; the
search engine itself is host-side Python. The reference's RayTune engine maps
to :class:`~analytics_zoo_tpu.automl.search.LocalSearchEngine` (sequential /
thread-parallel trials; a Ray engine can plug into the same ``SearchEngine``
contract when ray is present)."""
from . import hp  # noqa: F401
from .common.metrics import Evaluator  # noqa: F401
from .config.recipe import (  # noqa: F401
    BayesRecipe, GridRandomRecipe, LSTMGridRandomRecipe, MTNetGridRandomRecipe,
    RandomRecipe, Recipe, SmokeRecipe)
from .regression.time_sequence_predictor import TimeSequencePredictor  # noqa: F401
from .pipeline.time_sequence import TimeSequencePipeline  # noqa: F401
