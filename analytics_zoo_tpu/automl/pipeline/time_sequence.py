"""TimeSequencePipeline (reference ``automl/pipeline/time_sequence.py:28``):
the fitted feature-transform + model bundle with evaluate/predict/
save/load and incremental fit."""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..common.metrics import Evaluator
from ..feature.time_sequence import TimeSequenceFeatureTransformer


class TimeSequencePipeline:
    def __init__(self, feature_transformer: TimeSequenceFeatureTransformer,
                 model, config: Dict[str, Any], name: str = "automl"):
        self.ft = feature_transformer
        self.model = model
        self.config = dict(config)
        self.name = name

    def predict(self, input_df) -> np.ndarray:
        x = self.ft.transform(input_df, is_train=False)
        y = self.model.predict(x)
        return self.ft.post_processing(input_df, y, is_train=False)

    def evaluate(self, input_df, metrics: Sequence[str] = ("mse",)
                 ) -> Dict[str, float]:
        x, y = self.ft.transform(input_df, is_train=True)
        pred = self.model.predict(x)
        y_true = self.ft.post_processing(input_df, y, is_train=False)
        y_pred = self.ft.post_processing(input_df, pred, is_train=False)
        return {m: Evaluator.evaluate(m, y_true, y_pred) for m in metrics}

    def fit(self, input_df, validation_df=None, epoch_num: int = 1) -> float:
        """Incremental fit on new data with the fitted config (reference
        ``TimeSequencePipeline.fit``)."""
        x, y = self.ft.transform(input_df, is_train=True)
        config = dict(self.config, epochs=epoch_num)
        val = None
        if validation_df is not None:
            val = self.ft.transform(validation_df, is_train=True)
        return self.model.fit_eval((x, y), validation_data=val, **config)

    # -- persistence ----------------------------------------------------------

    def save(self, pipeline_path: str) -> None:
        os.makedirs(pipeline_path, exist_ok=True)
        self.ft.save(os.path.join(pipeline_path, "feature_transformer.json"))
        self.model.save(os.path.join(pipeline_path, "model"))
        meta = {"name": self.name,
                "model_class": type(self.model).__name__,
                "config": {k: v for k, v in self.config.items()
                           if isinstance(v, (int, float, str, bool, list))}}
        with open(os.path.join(pipeline_path, "pipeline.json"), "w") as f:
            json.dump(meta, f)

    @staticmethod
    def load(pipeline_path: str) -> "TimeSequencePipeline":
        from ..model import MODEL_REGISTRY, MTNet, TimeSeq2Seq, VanillaLSTM
        with open(os.path.join(pipeline_path, "pipeline.json")) as f:
            meta = json.load(f)
        ft = TimeSequenceFeatureTransformer().restore(
            os.path.join(pipeline_path, "feature_transformer.json"))
        classes = {"VanillaLSTM": VanillaLSTM, "MTNet": MTNet,
                   "TimeSeq2Seq": TimeSeq2Seq}
        model = classes[meta["model_class"]]()
        config = dict(meta["config"])
        config.setdefault("future_seq_len", ft.future_seq_len)
        config.setdefault("past_seq_len", ft.past_seq_len)
        config.setdefault("input_dim", 1 + len(ft.selected_features))
        model.restore(os.path.join(pipeline_path, "model"), **config)
        return TimeSequencePipeline(ft, model, config, name=meta["name"])
