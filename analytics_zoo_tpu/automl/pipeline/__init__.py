from .time_sequence import TimeSequencePipeline  # noqa: F401
