"""VanillaLSTM trainable (reference ``automl/model/VanillaLSTM.py``:
LSTM→Dropout→LSTM→Dropout→Dense over rolled windows; the search engine's
``fit_eval`` contract)."""
from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ...keras import Sequential
from ...keras.layers import Dense, Dropout, LSTM
from ...keras.optimizers import Adam
from ..common.metrics import Evaluator


class VanillaLSTM:
    def __init__(self, check_optional_config: bool = False):
        self.model: Optional[Sequential] = None
        self.config: Dict[str, Any] = {}

    def _build(self, output_dim: int, config: Dict[str, Any]) -> Sequential:
        model = Sequential(name="vanilla_lstm")
        model.add(LSTM(int(config.get("lstm_1_units", 32)),
                       return_sequences=True))
        model.add(Dropout(float(config.get("dropout_1", 0.2))))
        model.add(LSTM(int(config.get("lstm_2_units", 32))))
        model.add(Dropout(float(config.get("dropout_2", 0.2))))
        model.add(Dense(output_dim))
        model.compile(Adam(float(config.get("lr", 1e-3))), "mse")
        return model

    def fit_eval(self, data: Tuple, validation_data: Optional[Tuple] = None,
                 metric: str = "mse", **config) -> float:
        """``data`` = (x [n, past, d], y [n, future]); returns the validation
        metric (train-set metric when no validation split given)."""
        x, y = data
        y = np.asarray(y)
        if y.ndim == 1:
            y = y[:, None]
        self.config = dict(config)
        self.model = self._build(y.shape[-1], config)
        batch = int(config.get("batch_size", 32))
        batch = min(batch, len(x))
        self.model.fit(np.asarray(x, np.float32), y.astype(np.float32),
                       batch_size=batch,
                       nb_epoch=int(config.get("epochs", 1)))
        vx, vy = validation_data if validation_data is not None else (x, y)
        pred = self.predict(vx)
        return Evaluator.evaluate(metric, np.asarray(vy), pred)

    def predict(self, x) -> np.ndarray:
        preds = self.model.predict(np.asarray(x, np.float32), batch_size=128)
        return np.asarray(preds)

    def evaluate(self, x, y, metrics=("mse",)) -> Dict[str, float]:
        pred = self.predict(x)
        return {m: Evaluator.evaluate(m, np.asarray(y), pred)
                for m in metrics}

    def save(self, model_path: str, config_path: Optional[str] = None) -> None:
        self.model.save_model(model_path)
        if config_path:
            import json
            with open(config_path, "w") as f:
                json.dump({k: v for k, v in self.config.items()
                           if isinstance(v, (int, float, str, list, bool))}, f)

    def restore(self, model_path: str, **config) -> None:
        x_dim = config.get("input_dim")
        future = int(config.get("future_seq_len", 1))
        self.config = dict(config)
        self.model = self._build(future, config)
        # materialize params with a dummy batch before loading weights
        past = int(config.get("past_seq_len", 2))
        dummy = np.zeros((1, past, int(x_dim or 1)), np.float32)
        est = self.model.get_estimator()
        est._ensure_initialized(dummy)
        self.model.load_weights(model_path)
