from .vanilla_lstm import VanillaLSTM  # noqa: F401
from .mtnet import MTNet  # noqa: F401
from .time_seq2seq import TimeSeq2Seq  # noqa: F401

MODEL_REGISTRY = {"LSTM": VanillaLSTM, "MTNet": MTNet,
                  "Seq2Seq": TimeSeq2Seq}
