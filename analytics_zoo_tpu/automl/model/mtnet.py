"""MTNet trainable (reference ``automl/model/MTNet_keras.py`` — the
memory-network time-series model: long-term history encoded as ``long_num``
CNN+attention memory blocks, a short-term CNN query block, attention over
memory, plus an autoregressive linear highway).

TPU notes: all blocks are encoded in one batched conv (blocks folded into
the batch axis — one MXU-friendly conv instead of ``long_num`` small ones);
attention is a single einsum."""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...keras import Sequential
from ...keras.engine import Layer
from ...keras.layers import Dense
from ...keras.optimizers import Adam
from ..common.metrics import Evaluator


class _MTNetCore(Layer):
    def __init__(self, time_step: int, long_num: int, cnn_height: int,
                 cnn_hid_size: int, ar_window: int, output_dim: int,
                 dropout: float, name=None):
        super().__init__(name)
        self.time_step = time_step
        self.long_num = long_num
        self.cnn_height = min(cnn_height, time_step)
        self.cnn_hid = cnn_hid_size
        self.ar_window = min(ar_window, time_step)
        self.output_dim = output_dim
        self.dropout = dropout

    def build(self, rng, input_shape):
        d = input_shape[-1]
        k = jax.random.split(rng, 6)
        hid = self.cnn_hid
        conv_rows = self.time_step - self.cnn_height + 1
        params = {
            # one conv filter bank shared by memory and query encoders
            "conv": jax.random.normal(
                k[0], (self.cnn_height, d, hid)) * (1.0 / np.sqrt(
                    self.cnn_height * d)),
            "conv_b": jnp.zeros((hid,)),
            "attn": jax.random.normal(k[1], (hid, hid)) * (1.0 / np.sqrt(hid)),
            "out_w": jax.random.normal(
                k[2], (2 * hid * conv_rows, self.output_dim)) * 0.05,
            "out_b": jnp.zeros((self.output_dim,)),
            "ar_w": jax.random.normal(
                k[3], (self.ar_window, self.output_dim)) * 0.05,
            "ar_b": jnp.zeros((self.output_dim,)),
        }
        return params, {}

    def _encode(self, params, x):
        """[b, time_step, d] → [b, conv_rows*hid] via valid 1D conv + relu."""
        y = jax.lax.conv_general_dilated(
            x, params["conv"], window_strides=(1,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        y = jax.nn.relu(y + params["conv_b"])
        return y.reshape(y.shape[0], -1), y

    def call(self, params, state, inputs, *, training=False, rng=None):
        b = inputs.shape[0]
        n, T = self.long_num, self.time_step
        d = inputs.shape[-1]
        mem = inputs[:, :n * T].reshape(b * n, T, d)  # fold blocks into batch
        query = inputs[:, n * T:n * T + T]

        mem_flat, _ = self._encode(params, mem)      # [b*n, rows*hid]
        q_flat, _ = self._encode(params, query)      # [b, rows*hid]
        rows_hid = mem_flat.shape[-1]
        hid = self.cnn_hid
        mem_blocks = mem_flat.reshape(b, n, rows_hid)

        # attention of query over memory blocks (dot in conv-feature space)
        scores = jnp.einsum("bnf,bf->bn", mem_blocks, q_flat) / np.sqrt(
            rows_hid)
        attn = jax.nn.softmax(scores, axis=-1)
        context = jnp.einsum("bn,bnf->bf", attn, mem_blocks)

        feats = jnp.concatenate([context, q_flat], axis=-1)
        if training and self.dropout > 0.0 and rng is not None:
            keep = 1.0 - self.dropout
            mask = jax.random.bernoulli(rng, keep, feats.shape)
            feats = jnp.where(mask, feats / keep, 0.0)
        nonlinear = feats @ params["out_w"] + params["out_b"]

        # autoregressive highway over the raw target (column 0)
        ar_in = inputs[:, -self.ar_window:, 0]
        linear = ar_in @ params["ar_w"] + params["ar_b"]
        return nonlinear + linear, state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.output_dim)


class MTNet:
    def __init__(self, check_optional_config: bool = False):
        self.model: Optional[Sequential] = None
        self.config: Dict[str, Any] = {}

    def _build(self, output_dim: int, config: Dict[str, Any]) -> Sequential:
        core = _MTNetCore(
            time_step=int(config.get("time_step", 4)),
            long_num=int(config.get("long_num", 3)),
            cnn_height=int(config.get("cnn_height", 2)),
            cnn_hid_size=int(config.get("cnn_hid_size", 16)),
            ar_window=int(config.get("ar_window", 2)),
            output_dim=output_dim,
            dropout=float(config.get("dropout", 0.0)),
            name="mtnet_core")
        model = Sequential([core], name="mtnet")
        model.compile(Adam(float(config.get("lr", 1e-3))), "mse")
        return model

    def required_past_seq_len(self, config: Dict[str, Any]) -> int:
        return (int(config.get("long_num", 3)) + 1) * \
            int(config.get("time_step", 4))

    def fit_eval(self, data: Tuple, validation_data: Optional[Tuple] = None,
                 metric: str = "mse", **config) -> float:
        x, y = data
        y = np.asarray(y)
        if y.ndim == 1:
            y = y[:, None]
        need = self.required_past_seq_len(config)
        if x.shape[1] < need:
            raise ValueError(
                f"MTNet needs past_seq_len >= (long_num+1)*time_step = "
                f"{need}, got {x.shape[1]}")
        x = x[:, -need:]  # trailing window
        self.config = dict(config)
        self.model = self._build(y.shape[-1], config)
        batch = min(int(config.get("batch_size", 32)), len(x))
        self.model.fit(np.asarray(x, np.float32), y.astype(np.float32),
                       batch_size=batch,
                       nb_epoch=int(config.get("epochs", 1)))
        vx, vy = validation_data if validation_data is not None else (x, y)
        pred = self.predict(vx)
        return Evaluator.evaluate(metric, np.asarray(vy), pred)

    def predict(self, x) -> np.ndarray:
        need = self.required_past_seq_len(self.config)
        x = np.asarray(x, np.float32)[:, -need:]
        return np.asarray(self.model.predict(x, batch_size=128))

    def evaluate(self, x, y, metrics=("mse",)) -> Dict[str, float]:
        pred = self.predict(x)
        return {m: Evaluator.evaluate(m, np.asarray(y), pred)
                for m in metrics}

    def save(self, model_path: str, config_path: Optional[str] = None) -> None:
        self.model.save_model(model_path)

    def restore(self, model_path: str, **config) -> None:
        self.config = dict(config)
        future = int(config.get("future_seq_len", 1))
        self.model = self._build(future, config)
        need = self.required_past_seq_len(config)
        dummy = np.zeros((1, need, int(config.get("input_dim", 1))),
                         np.float32)
        self.model.get_estimator()._ensure_initialized(dummy)
        self.model.load_weights(model_path)
