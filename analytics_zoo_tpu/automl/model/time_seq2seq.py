"""Seq2Seq trainable for time series (reference ``automl/model/Seq2Seq.py``:
LSTM encoder/decoder forecaster with teacher forcing)."""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ...models.seq2seq import Seq2seq
from ..common.metrics import Evaluator


class TimeSeq2Seq:
    def __init__(self, check_optional_config: bool = False):
        self.zoo: Optional[Seq2seq] = None
        self.config: Dict[str, Any] = {}
        self.future_seq_len = 1

    def _decoder_inputs(self, x: np.ndarray, future: int) -> np.ndarray:
        """Teacher-forcing decoder input: last encoder target step repeated
        (inference uses the same scheme, so train/test match)."""
        last = x[:, -1:, 0:1]
        return np.repeat(last, future, axis=1).astype(np.float32)

    def fit_eval(self, data: Tuple, validation_data: Optional[Tuple] = None,
                 metric: str = "mse", **config) -> float:
        x, y = data
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        self.future_seq_len = y.shape[1]
        self.config = dict(config)
        self.zoo = Seq2seq(rnn_type="lstm",
                           num_layers=int(config.get("num_layers", 1)),
                           hidden_size=int(config.get("latent_dim", 32)),
                           bridge="passthrough", generator_dim=1)
        self.zoo.default_compile()
        dec = self._decoder_inputs(x, self.future_seq_len)
        target = y[:, :, None]
        batch = min(int(config.get("batch_size", 32)), len(x))
        self.zoo.fit([np.asarray(x, np.float32), dec], target,
                     batch_size=batch,
                     nb_epoch=int(config.get("epochs", 1)))
        vx, vy = validation_data if validation_data is not None else (x, y)
        pred = self.predict(vx)
        return Evaluator.evaluate(metric, np.asarray(vy), pred)

    def predict(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        dec = self._decoder_inputs(x, self.future_seq_len)
        out = np.asarray(self.zoo.predict([x, dec], batch_size=128))
        return out[:, :, 0]

    def evaluate(self, x, y, metrics=("mse",)) -> Dict[str, float]:
        pred = self.predict(x)
        return {m: Evaluator.evaluate(m, np.asarray(y), pred)
                for m in metrics}

    def save(self, model_path: str, config_path: Optional[str] = None) -> None:
        self.zoo.save_model(model_path)

    def restore(self, model_path: str, **config) -> None:
        from ...models.common import ZooModel
        self.config = dict(config)
        self.future_seq_len = int(config.get("future_seq_len", 1))
        self.zoo = ZooModel.load_model(model_path)
