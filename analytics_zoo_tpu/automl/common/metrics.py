"""Evaluation metrics (reference ``automl/common/metrics.py``: Evaluator +
MSE/RMSE/MAE/sMAPE/MAPE/R2... with multioutput handling)."""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np


def _flat(y_true, y_pred):
    y_true = np.asarray(y_true, np.float64)
    y_pred = np.asarray(y_pred, np.float64)
    if y_true.shape != y_pred.shape:
        y_pred = y_pred.reshape(y_true.shape)
    return y_true.reshape(len(y_true), -1), y_pred.reshape(len(y_pred), -1)


def MSE(y_true, y_pred):
    t, p = _flat(y_true, y_pred)
    return float(np.mean((t - p) ** 2))


def RMSE(y_true, y_pred):
    return float(np.sqrt(MSE(y_true, y_pred)))


def MAE(y_true, y_pred):
    t, p = _flat(y_true, y_pred)
    return float(np.mean(np.abs(t - p)))


def sMAPE(y_true, y_pred):
    t, p = _flat(y_true, y_pred)
    return float(100.0 * np.mean(np.abs(t - p) /
                                 np.maximum(np.abs(t) + np.abs(p), 1e-8) * 2))


def MAPE(y_true, y_pred):
    t, p = _flat(y_true, y_pred)
    return float(100.0 * np.mean(np.abs((t - p) / np.maximum(np.abs(t), 1e-8))))


def MPE(y_true, y_pred):
    t, p = _flat(y_true, y_pred)
    return float(100.0 * np.mean((t - p) / np.maximum(np.abs(t), 1e-8)))


def ME(y_true, y_pred):
    t, p = _flat(y_true, y_pred)
    return float(np.mean(t - p))


def R2(y_true, y_pred):
    t, p = _flat(y_true, y_pred)
    ss_res = np.sum((t - p) ** 2)
    ss_tot = np.sum((t - t.mean(axis=0)) ** 2)
    return float(1.0 - ss_res / max(ss_tot, 1e-12))


_METRICS: Dict[str, Callable] = {
    "mse": MSE, "rmse": RMSE, "mae": MAE, "smape": sMAPE, "mape": MAPE,
    "mpe": MPE, "me": ME, "r2": R2, "r_squared": R2,
}

# metrics where bigger is better (everything else minimizes)
MAXIMIZE = {"r2", "r_squared"}


class Evaluator:
    @staticmethod
    def evaluate(metric: str, y_true, y_pred) -> float:
        key = metric.lower()
        if key not in _METRICS:
            raise ValueError(f"unknown metric '{metric}'; have "
                             f"{sorted(_METRICS)}")
        return _METRICS[key](y_true, y_pred)

    @staticmethod
    def get_metric_mode(metric: str) -> str:
        return "max" if metric.lower() in MAXIMIZE else "min"
