"""SearchEngine abstraction (reference ``automl/search/abstract.py``:
``SearchEngine.compile/run/get_best_trials`` + ``TrialOutput``)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List


@dataclass
class TrialOutput:
    config: Dict[str, Any]
    metric: float
    model_path: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)


class SearchEngine:
    def compile(self, data, model_create_fn, recipe, metric: str = "mse",
                **kwargs) -> None:
        raise NotImplementedError

    def run(self) -> List[TrialOutput]:
        raise NotImplementedError

    def get_best_trials(self, k: int = 1) -> List[TrialOutput]:
        raise NotImplementedError
