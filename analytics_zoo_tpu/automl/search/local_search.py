"""LocalSearchEngine — the RayTuneSearchEngine role
(``automl/search/RayTuneSearchEngine.py:28``) without a Ray dependency:
trial configs are generated from the recipe's space (grid cross-product ×
random samples, or a GP-surrogate Bayes loop), each trial calls the
user-provided trainable and the engine ranks results. Trials run
sequentially by default: one TPU, one process — the accelerator is already
saturated by a single trial's batched training."""
from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import hp
from ..common.metrics import Evaluator
from ..config.recipe import Recipe
from .abstract import SearchEngine, TrialOutput


def _expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    grid_keys = [k for k, v in space.items() if isinstance(v, hp.Grid)]
    if not grid_keys:
        return [dict(space)]
    combos = itertools.product(*[space[k].options for k in grid_keys])
    out = []
    for combo in combos:
        point = dict(space)
        point.update(dict(zip(grid_keys, combo)))
        out.append(point)
    return out


def _materialize(point: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    return {k: (v.sample(rng) if isinstance(v, hp.Sampler) else v)
            for k, v in point.items()}


class LocalSearchEngine(SearchEngine):
    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.trials: List[TrialOutput] = []
        self._compiled = False

    def compile(self, data, model_create_fn: Callable[[], Any],
                recipe: Recipe, metric: str = "mse",
                feature_cols: Optional[Sequence[str]] = None,
                fit_fn: Optional[Callable] = None) -> None:
        """``model_create_fn() -> model`` with the trainable contract
        ``model.fit_eval(data, validation_data, metric, **config) -> float``;
        or pass ``fit_fn(config, data) -> float`` directly."""
        self.data = data
        self.model_create_fn = model_create_fn
        self.recipe = recipe
        self.metric = metric
        self.mode = Evaluator.get_metric_mode(metric)
        self.space = recipe.search_space(feature_cols)
        self.fit_fn = fit_fn
        self._compiled = True

    def _run_trial(self, config: Dict[str, Any]) -> TrialOutput:
        if self.fit_fn is not None:
            score = self.fit_fn(config, self.data)
        else:
            model = self.model_create_fn()
            score = model.fit_eval(self.data, metric=self.metric, **config)
        return TrialOutput(config=config, metric=float(score))

    def run(self) -> List[TrialOutput]:
        if not self._compiled:
            raise RuntimeError("compile first")
        if self.recipe.search_algorithm() == "bayes":
            self.trials = self._run_bayes()
            return self.trials
        points = _expand_grid(self.space)
        n_samples = max(1, self.recipe.runtime_params()["num_samples"])
        for point in points:
            for _ in range(n_samples):
                config = _materialize(point, self.rng)
                self.trials.append(self._run_trial(config))
        return self.trials

    # -- GP-surrogate bayes loop (the BayesOpt role) --------------------------

    def _numeric_keys(self) -> List[str]:
        keys = []
        for k, v in self.space.items():
            if isinstance(v, (hp.Uniform, hp.LogUniform, hp.RandInt)):
                keys.append(k)
            elif isinstance(v, hp.Choice) and all(
                    isinstance(o, (int, float)) for o in v.options):
                keys.append(k)
        return keys

    def _run_bayes(self, n_init: int = 3) -> List[TrialOutput]:
        from sklearn.gaussian_process import GaussianProcessRegressor
        num_keys = self._numeric_keys()
        n_total = max(n_init + 1,
                      self.recipe.runtime_params()["num_samples"])
        trials: List[TrialOutput] = []
        configs: List[Dict[str, Any]] = []
        for i in range(n_total):
            if i < n_init or not num_keys:
                config = _materialize(self.space, self.rng)
            else:
                # fit GP on numeric projection; pick best of random candidates
                X = np.asarray([[float(c[k]) for k in num_keys]
                                for c in configs])
                y = np.asarray([t.metric for t in trials])
                if self.mode == "max":
                    y = -y
                gp = GaussianProcessRegressor(normalize_y=True).fit(X, y)
                cands = [_materialize(self.space, self.rng)
                         for _ in range(32)]
                Xc = np.asarray([[float(c[k]) for k in num_keys]
                                 for c in cands])
                mu, sigma = gp.predict(Xc, return_std=True)
                best = float(y.min())
                ei = (best - mu) + 1.0 * sigma  # exploration bonus
                config = cands[int(np.argmax(ei))]
            out = self._run_trial(config)
            trials.append(out)
            configs.append(config)
        return trials

    def get_best_trials(self, k: int = 1) -> List[TrialOutput]:
        reverse = self.mode == "max"
        return sorted(self.trials, key=lambda t: t.metric,
                      reverse=reverse)[:k]
