"""ParallelSearchEngine — distributed trial execution, the role the
reference fills with Ray Tune over a Ray cluster
(``pyzoo/zoo/automl/search/RayTuneSearchEngine.py:28``).

Trials run in spawned worker PROCESSES, each pinned to the CPU backend (a
hyperparameter sweep must not fight the training job for the TPU; the
winning config then trains on the accelerator). Configs are generated
exactly as the sequential engine does, so results are seed-compatible —
only wall-clock changes.

The trainable must be picklable (module-level function / class), the same
contract Ray Tune imposes via cloudpickle — and, as with any library that
spawns worker processes, a driving SCRIPT must guard its entry point with
``if __name__ == "__main__":`` (spawned children re-import the main module).
"""
from __future__ import annotations

import os
from concurrent.futures import ProcessPoolExecutor
from multiprocessing import get_context
from typing import Any, Callable, Dict, List, Optional, Sequence

from .abstract import TrialOutput
from .local_search import LocalSearchEngine, _expand_grid, _materialize


# per-worker trainable context, installed once by the pool initializer so
# the (potentially large) dataset is pickled once per WORKER, not per trial
_worker_ctx: Dict[str, Any] = {}


def _worker_init(fit_fn, model_create_fn, data, metric):
    # the worker interpreter may have pre-imported jax (sitecustomize) with
    # the hardware platform pinned; re-assert CPU before any backend starts
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    _worker_ctx.update(fit_fn=fit_fn, model_create_fn=model_create_fn,
                       data=data, metric=metric)


def _run_one(config) -> Dict[str, Any]:
    fit_fn = _worker_ctx["fit_fn"]
    if fit_fn is not None:
        score = fit_fn(config, _worker_ctx["data"])
    else:
        model = _worker_ctx["model_create_fn"]()
        score = model.fit_eval(_worker_ctx["data"],
                               metric=_worker_ctx["metric"], **config)
    return {"config": config, "metric": float(score)}


class ParallelSearchEngine(LocalSearchEngine):
    """Drop-in for :class:`LocalSearchEngine` with process-parallel trials.

    ``num_workers`` caps concurrent trials (defaults to the host CPU count,
    at most 8 — search trials are small by construction). Bayes search stays
    sequential (each step conditions on all previous results) — the engine
    falls back with a log note rather than silently changing the algorithm.
    """

    def __init__(self, num_workers: Optional[int] = None, seed: int = 0):
        super().__init__(seed=seed)
        self.num_workers = num_workers or min(8, os.cpu_count() or 2)

    def run(self) -> List[TrialOutput]:
        if not self._compiled:
            raise RuntimeError("compile first")
        if self.recipe.search_algorithm() == "bayes":
            import logging
            logging.getLogger("analytics_zoo_tpu").info(
                "bayes search is sequential by construction; running trials "
                "in-process")
            self.trials = self._run_bayes()
            return self.trials
        points = _expand_grid(self.space)
        n_samples = max(1, self.recipe.runtime_params()["num_samples"])
        configs = [_materialize(point, self.rng)
                   for point in points for _ in range(n_samples)]
        ctx_args = (self.fit_fn, self.model_create_fn, self.data, self.metric)
        # validate picklability UP FRONT, so a genuine trial exception later
        # propagates as itself instead of being misdiagnosed
        import pickle
        try:
            pickle.dumps(ctx_args)
        except Exception as e:
            raise ValueError(
                "ParallelSearchEngine needs a picklable trainable "
                "(module-level fit_fn / model_create_fn); use "
                f"LocalSearchEngine for closures. Underlying error: {e!r}")
        with ProcessPoolExecutor(
                max_workers=min(self.num_workers, len(configs)),
                mp_context=get_context("spawn"),
                initializer=_worker_init, initargs=ctx_args) as pool:
            results = list(pool.map(_run_one, configs))
        self.trials = [TrialOutput(config=r["config"], metric=r["metric"])
                       for r in results]
        return self.trials
