from .abstract import SearchEngine, TrialOutput  # noqa: F401
from .local_search import LocalSearchEngine  # noqa: F401
