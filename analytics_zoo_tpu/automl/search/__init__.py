from .abstract import SearchEngine, TrialOutput  # noqa: F401
from .local_search import LocalSearchEngine  # noqa: F401
from .parallel_search import ParallelSearchEngine  # noqa: F401
from .pod_search import PodSearchEngine  # noqa: F401
