"""PodSearchEngine — AutoML trials distributed over PodLauncher workers.

The reference distributes hyperparameter trials across the cluster with Ray
Tune (``pyzoo/zoo/automl/search/RayTuneSearchEngine.py:28``: one Ray actor
per trial, results gathered on the driver). The TPU-native equivalent reuses
the framework's own pod orchestration (``cluster/launcher.py`` PodLauncher):
the driver expands the full deterministic trial list, spools the trainable +
data ONCE via pickle, launches N workers that each run the
``rank::num_workers`` stride of trials on the CPU backend, and merges the
per-worker result files rank-0-style. Config generation is identical to the
sequential engine (same seed → same trials → same best config); only the
placement changes.
"""
from __future__ import annotations

import os
import pickle
import tempfile

from typing import Any, Dict, List, Optional

from ...common import pickling
from ...common.pickling import pickler as _pickler
from .abstract import TrialOutput
from .local_search import LocalSearchEngine, _expand_grid, _materialize


def _pod_worker(spool_dir: str) -> int:
    """Worker target (runs under ``cluster.bootstrap``): execute this rank's
    stride of trials and write ``results_{rank}.pkl``."""
    rank = int(os.environ["ZOO_TPU_PROC_ID"])
    nprocs = int(os.environ["ZOO_TPU_NPROCS"])
    with open(os.path.join(spool_dir, "payload.pkl"), "rb") as f:
        payload = pickle.load(f)
    fit_fn = payload["fit_fn"]
    model_create_fn = payload["model_create_fn"]
    data, metric = payload["data"], payload["metric"]
    results: List[Dict[str, Any]] = []
    for idx in range(rank, len(payload["configs"]), nprocs):
        config = payload["configs"][idx]
        if fit_fn is not None:
            score = fit_fn(config, data)
        else:
            model = model_create_fn()
            score = model.fit_eval(data, metric=metric, **config)
        results.append({"index": idx, "config": config,
                        "metric": float(score)})
    tmp = os.path.join(spool_dir, f".results_{rank}.pkl")
    with open(tmp, "wb") as f:
        pickle.dump(results, f)
    os.replace(tmp, os.path.join(spool_dir, f"results_{rank}.pkl"))
    return 0


class PodSearchEngine(LocalSearchEngine):
    """Cluster-wide trial execution over PodLauncher worker processes.

    Differences from :class:`ParallelSearchEngine` (one-host process pool):
    workers are full pod workers — parent-death guarded, per-worker log
    files, fail-fast reaping — the same machinery that runs distributed
    training, so a search can span every host a pod spans. Bayes search
    stays sequential (each step conditions on all previous results).
    """

    def __init__(self, num_workers: int = 2, seed: int = 0,
                 timeout: Optional[float] = None):
        super().__init__(seed=seed)
        self.num_workers = num_workers
        self.timeout = timeout

    def run(self) -> List[TrialOutput]:
        if not self._compiled:
            raise RuntimeError("compile first")
        if self.recipe.search_algorithm() == "bayes":
            import logging
            logging.getLogger("analytics_zoo_tpu").info(
                "bayes search is sequential by construction; running trials "
                "in-process")
            self.trials = self._run_bayes()
            return self.trials
        points = _expand_grid(self.space)
        n_samples = max(1, self.recipe.runtime_params()["num_samples"])
        configs = [_materialize(point, self.rng)
                   for point in points for _ in range(n_samples)]
        payload = {"fit_fn": self.fit_fn,
                   "model_create_fn": self.model_create_fn,
                   "data": self.data, "metric": self.metric,
                   "configs": configs}
        try:
            blob = _pickler.dumps(payload)
        except Exception as e:
            raise ValueError(
                "PodSearchEngine needs a serializable trainable and data "
                f"({pickling.capability_note()}); underlying error: {e!r}")
        # the spool holds a full copy of the training data — always removed,
        # success or failure (long-lived AutoML hosts must not fill /tmp)
        spool = tempfile.mkdtemp(prefix="zoo_pod_search_")
        try:
            with open(os.path.join(spool, "payload.pkl"), "wb") as f:
                f.write(blob)
            from ...cluster.launcher import run_pod
            nprocs = min(self.num_workers, len(configs))
            run_pod("analytics_zoo_tpu.automl.search.pod_search:_pod_worker",
                    nprocs, args=[spool], platform="cpu",
                    timeout=self.timeout)
            merged: List[Dict[str, Any]] = []
            for rank in range(nprocs):
                path = os.path.join(spool, f"results_{rank}.pkl")
                if not os.path.exists(path):
                    raise RuntimeError(
                        f"search worker {rank} exited OK but wrote no "
                        f"results file — {path} missing")
                with open(path, "rb") as f:
                    merged.extend(pickle.load(f))
        finally:
            import shutil
            shutil.rmtree(spool, ignore_errors=True)
        # submission order == the sequential engine's trial order, so the
        # seed-compatibility contract (identical best config) holds
        merged.sort(key=lambda r: r["index"])
        self.trials = [TrialOutput(config=r["config"], metric=r["metric"])
                       for r in merged]
        return self.trials
