"""Recipes — named search-space presets (reference ``automl/config/recipe.py``:
SmokeRecipe, GridRandomRecipe, LSTMGridRandomRecipe, MTNetGridRandomRecipe,
RandomRecipe, BayesRecipe)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from .. import hp


class Recipe:
    num_samples: int = 1
    training_iteration: int = 10

    def search_space(self, all_available_features: Optional[Sequence[str]]
                     ) -> Dict[str, Any]:
        raise NotImplementedError

    def runtime_params(self) -> Dict[str, Any]:
        return {"training_iteration": self.training_iteration,
                "num_samples": self.num_samples}

    def fixed_params(self) -> Dict[str, Any]:
        return {}

    def search_algorithm(self) -> str:
        return "random"


class _FeatureSubset(hp.Sampler):
    """Random feature subset drawn from the engine's seeded rng (keeps
    searches reproducible under ``LocalSearchEngine(seed)``)."""

    def __init__(self, features: Sequence[str]):
        self.features = list(features)

    def sample(self, rng):
        k = rng.randint(0, len(self.features))
        return list(rng.sample(self.features, k))


def _feature_subset(features: Optional[Sequence[str]]):
    if not features:
        return hp.choice([[]])
    return _FeatureSubset(features)


class SmokeRecipe(Recipe):
    """Tiny sanity sweep (reference SmokeRecipe)."""
    num_samples = 1
    training_iteration = 1

    def search_space(self, all_available_features):
        return {
            "selected_features": hp.choice(
                [list(all_available_features or [])]),
            "model": "LSTM",
            "lstm_1_units": hp.choice([16]),
            "lstm_2_units": hp.choice([16]),
            "dropout_1": 0.2,
            "dropout_2": 0.2,
            "lr": 0.001,
            "batch_size": 32,
            "epochs": 1,
            "past_seq_len": 2,
        }


class GridRandomRecipe(Recipe):
    """Grid over structure × random over the rest (reference
    GridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2,
                 epochs: int = 5):
        self.num_samples = num_rand_samples
        self.training_iteration = epochs
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": _feature_subset(all_available_features),
            "model": "LSTM",
            "lstm_1_units": hp.grid_search([16, 32]),
            "lstm_2_units": hp.grid_search([16, 32]),
            "dropout_1": hp.uniform(0.1, 0.3),
            "dropout_2": hp.uniform(0.1, 0.3),
            "lr": hp.loguniform(1e-4, 1e-2),
            "batch_size": hp.choice([32, 64]),
            "epochs": self.training_iteration,
            "past_seq_len": self.look_back,
        }


class LSTMGridRandomRecipe(GridRandomRecipe):
    """LSTM-specific structure sweep (reference LSTMGridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 lstm_1_units: Sequence[int] = (16, 32, 64),
                 lstm_2_units: Sequence[int] = (16, 32, 64),
                 batch_size: Sequence[int] = (32, 64),
                 look_back: int = 2):
        super().__init__(num_rand_samples, look_back, epochs)
        self.lstm_1_units = list(lstm_1_units)
        self.lstm_2_units = list(lstm_2_units)
        self.batch_size = list(batch_size)

    def search_space(self, all_available_features):
        space = super().search_space(all_available_features)
        space.update({
            "lstm_1_units": hp.grid_search(self.lstm_1_units),
            "lstm_2_units": hp.grid_search(self.lstm_2_units),
            "batch_size": hp.choice(self.batch_size),
        })
        return space


class MTNetGridRandomRecipe(Recipe):
    """MTNet structure sweep (reference MTNetGridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 time_step: Sequence[int] = (4,),
                 long_num: Sequence[int] = (3, 4),
                 cnn_height: Sequence[int] = (2, 3),
                 cnn_hid_size: Sequence[int] = (16, 32),
                 batch_size: Sequence[int] = (32, 64)):
        self.num_samples = num_rand_samples
        self.training_iteration = epochs
        self.time_step = list(time_step)
        self.long_num = list(long_num)
        self.cnn_height = list(cnn_height)
        self.cnn_hid_size = list(cnn_hid_size)
        self.batch_size = list(batch_size)

    def search_space(self, all_available_features):
        return {
            "selected_features": _feature_subset(all_available_features),
            "model": "MTNet",
            "time_step": hp.grid_search(self.time_step),
            "long_num": hp.grid_search(self.long_num),
            "cnn_height": hp.choice(self.cnn_height),
            "cnn_hid_size": hp.choice(self.cnn_hid_size),
            "dropout": hp.uniform(0.0, 0.2),
            "lr": hp.loguniform(1e-4, 1e-2),
            "batch_size": hp.choice(self.batch_size),
            "epochs": self.training_iteration,
        }


class RandomRecipe(Recipe):
    """Pure random search (reference RandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2,
                 epochs: int = 5):
        self.num_samples = num_rand_samples
        self.training_iteration = epochs
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": _feature_subset(all_available_features),
            "model": "LSTM",
            "lstm_1_units": hp.choice([8, 16, 32, 64]),
            "lstm_2_units": hp.choice([8, 16, 32, 64]),
            "dropout_1": hp.uniform(0.1, 0.5),
            "dropout_2": hp.uniform(0.1, 0.5),
            "lr": hp.loguniform(1e-4, 1e-2),
            "batch_size": hp.choice([32, 64, 128]),
            "epochs": self.training_iteration,
            "past_seq_len": self.look_back,
        }


class BayesRecipe(RandomRecipe):
    """Bayesian-optimization search over the random space (reference
    BayesRecipe backed by BayesOpt; here a GP surrogate from sklearn drives
    the proposal loop in the search engine)."""

    def __init__(self, num_samples: int = 10, look_back: int = 2,
                 epochs: int = 5):
        super().__init__(num_samples, look_back, epochs)

    def search_algorithm(self) -> str:
        return "bayes"
