// Native TFRecord reader: mmap + CRC32C + record index.
//
// The reference's data plane leans on JVM-native readers (Hadoop input
// formats / TFRecordInputFormat) so the hot ingest path never touches
// per-record interpreted code; this plays the same role for the TPU host
// pipeline. Python asks for an index once (offsets/lengths validated by
// CRC32C), then slices records straight out of the mapped file with zero
// copies in the common case.
//
// Format (tensorflow/core/lib/io/record_writer.h):
//   uint64 length | uint32 masked_crc32c(length) | data | uint32 masked_crc32c(data)
//
// Build: g++ -O3 -shared -fPIC -o libzoo_tfrecord.so tfrecord_reader.cpp

#include <cstdint>
#include <cstring>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ---- CRC32C (Castagnoli), slice-by-8 table driven ----
uint32_t kTable[8][256];
bool kTableInit = false;

void init_table() {
  if (kTableInit) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t n = 0; n < 256; n++) {
    uint32_t crc = n;
    for (int k = 0; k < 8; k++) crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
    kTable[0][n] = crc;
  }
  for (uint32_t n = 0; n < 256; n++) {
    uint32_t crc = kTable[0][n];
    for (int s = 1; s < 8; s++) {
      crc = kTable[0][crc & 0xFF] ^ (crc >> 8);
      kTable[s][n] = crc;
    }
  }
  kTableInit = true;
}

uint32_t crc32c(const uint8_t* data, size_t n, uint32_t crc = 0) {
  crc ^= 0xFFFFFFFFu;
  // 8 bytes at a time through the sliced tables
  while (n >= 8) {
    uint64_t word;
    std::memcpy(&word, data, 8);
    word ^= crc;
    crc = kTable[7][word & 0xFF] ^ kTable[6][(word >> 8) & 0xFF] ^
          kTable[5][(word >> 16) & 0xFF] ^ kTable[4][(word >> 24) & 0xFF] ^
          kTable[3][(word >> 32) & 0xFF] ^ kTable[2][(word >> 40) & 0xFF] ^
          kTable[1][(word >> 48) & 0xFF] ^ kTable[0][(word >> 56) & 0xFF];
    data += 8;
    n -= 8;
  }
  while (n--) crc = kTable[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

uint32_t masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

struct Reader {
  uint8_t* base = nullptr;
  size_t size = 0;
  std::vector<uint64_t> offsets;  // of record payload
  std::vector<uint64_t> lengths;
  int error = 0;  // 0 ok, 1 truncated, 2 crc mismatch
};

}  // namespace

extern "C" {

// Open + index a TFRecord file. verify: 0 none, 1 header crc, 2 +payload crc.
void* ztr_open(const char* path, int verify) {
  init_table();
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { ::close(fd); return nullptr; }
  auto* r = new Reader();
  r->size = static_cast<size_t>(st.st_size);
  if (r->size > 0) {
    r->base = static_cast<uint8_t*>(
        mmap(nullptr, r->size, PROT_READ, MAP_PRIVATE, fd, 0));
    if (r->base == MAP_FAILED) { ::close(fd); delete r; return nullptr; }
    madvise(r->base, r->size, MADV_SEQUENTIAL);
  }
  ::close(fd);

  size_t pos = 0;
  while (pos + 12 <= r->size) {
    uint64_t len;
    std::memcpy(&len, r->base + pos, 8);
    if (verify >= 1) {
      uint32_t hcrc;
      std::memcpy(&hcrc, r->base + pos + 8, 4);
      if (hcrc != masked_crc(r->base + pos, 8)) { r->error = 2; break; }
    }
    // overflow-safe bounds check: a crafted length near 2^64 must not wrap
    // `pos + 12 + len + 4` past the mmap (CRC32C is not a MAC)
    uint64_t avail = r->size - pos - 12;
    if (len > avail || avail - len < 4) { r->error = 1; break; }
    if (verify >= 2) {
      uint32_t dcrc;
      std::memcpy(&dcrc, r->base + pos + 12 + len, 4);
      if (dcrc != masked_crc(r->base + pos + 12, len)) { r->error = 2; break; }
    }
    r->offsets.push_back(pos + 12);
    r->lengths.push_back(len);
    pos += 12 + len + 4;
  }
  return r;
}

long ztr_count(void* h) { return static_cast<Reader*>(h)->offsets.size(); }
int ztr_error(void* h) { return static_cast<Reader*>(h)->error; }

long ztr_record_len(void* h, long i) {
  auto* r = static_cast<Reader*>(h);
  if (i < 0 || static_cast<size_t>(i) >= r->lengths.size()) return -1;
  return static_cast<long>(r->lengths[i]);
}

// Copy record i into buf (caller sized it via ztr_record_len).
int ztr_read(void* h, long i, uint8_t* buf) {
  auto* r = static_cast<Reader*>(h);
  if (i < 0 || static_cast<size_t>(i) >= r->offsets.size()) return -1;
  std::memcpy(buf, r->base + r->offsets[i], r->lengths[i]);
  return 0;
}

// Bulk: copy records [start, start+n) back-to-back into buf and write each
// length into lens. Python then splits with numpy — one ctypes call per batch.
int ztr_read_batch(void* h, long start, long n, uint8_t* buf, int64_t* lens) {
  auto* r = static_cast<Reader*>(h);
  if (start < 0 || start + n > static_cast<long>(r->offsets.size())) return -1;
  uint8_t* out = buf;
  for (long i = 0; i < n; i++) {
    uint64_t len = r->lengths[start + i];
    std::memcpy(out, r->base + r->offsets[start + i], len);
    lens[i] = static_cast<int64_t>(len);
    out += len;
  }
  return 0;
}

int64_t ztr_total_bytes(void* h, long start, long n) {
  auto* r = static_cast<Reader*>(h);
  if (start < 0 || start + n > static_cast<long>(r->offsets.size())) return -1;
  int64_t total = 0;
  for (long i = 0; i < n; i++) total += r->lengths[start + i];
  return total;
}

void ztr_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  if (r->base && r->size) munmap(r->base, r->size);
  delete r;
}

}  // extern "C"

// ---- writer: buffered framed-record output (CRC32C in native code) ----

#include <cstdio>

namespace {
struct Writer {
  FILE* f = nullptr;
};
}  // namespace

extern "C" {

void* ztw_open(const char* path) {
  init_table();
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  setvbuf(f, nullptr, _IOFBF, 1 << 20);  // 1MB buffered
  return w;
}

// Frame one record: u64 len | masked_crc(len) | data | masked_crc(data).
int ztw_write(void* h, const uint8_t* data, uint64_t len) {
  auto* w = static_cast<Writer*>(h);
  uint8_t header[8];
  std::memcpy(header, &len, 8);
  uint32_t hcrc = masked_crc(header, 8);
  uint32_t dcrc = masked_crc(data, len);
  if (std::fwrite(header, 1, 8, w->f) != 8) return -1;
  if (std::fwrite(&hcrc, 1, 4, w->f) != 4) return -1;
  if (len && std::fwrite(data, 1, len, w->f) != len) return -1;
  if (std::fwrite(&dcrc, 1, 4, w->f) != 4) return -1;
  return 0;
}

int ztw_flush(void* h) {
  return std::fflush(static_cast<Writer*>(h)->f);
}

// Returns 0 on success; nonzero if the final flush/close failed (ENOSPC
// etc.) — callers must surface this, a truncated file must not look ok.
int ztw_close(void* h) {
  auto* w = static_cast<Writer*>(h);
  int rc = 0;
  if (w->f) rc = std::fclose(w->f);
  delete w;
  return rc;
}

}  // extern "C"
