"""Native (C++) runtime components, built on demand with the system
toolchain and loaded over ctypes. Python fallbacks exist for every native
path — the framework works without a compiler, just slower."""
from .build import load_library  # noqa: F401
