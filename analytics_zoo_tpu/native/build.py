"""On-demand compilation of the native components.

No build step at install time: the first import compiles the .so next to the
source with the system ``g++`` (cached by mtime), the way JAX itself JITs its
kernels. Failure to build is non-fatal — callers fall back to pure Python.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

logger = logging.getLogger("analytics_zoo_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_cache = {}


def _build(src: str, out: str) -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", out, src]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build unavailable (%s); using Python fallback", e)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed; using Python fallback:\n%s",
                       proc.stderr[-2000:])
        return False
    return True


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Load (building if stale/missing) ``native/<name>.cpp`` as a CDLL.
    Returns None when no compiler is available — callers must fall back."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        out = os.path.join(_DIR, f"lib{name}.so")
        if not os.path.exists(src):
            raise FileNotFoundError(src)
        ok = True
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            # build into the package dir when writable, else a temp dir
            target = out
            if not os.access(_DIR, os.W_OK):
                target = os.path.join(tempfile.gettempdir(),
                                      f"zoo_native_lib{name}.so")
            ok = _build(src, target)
            out = target
        lib = None
        if ok:
            try:
                lib = ctypes.CDLL(out)
            except OSError as e:
                logger.warning("could not load %s (%s); Python fallback", out, e)
        _cache[name] = lib
        return lib
