"""Attention / Transformer / BERT Keras-style layers.

Capability parity with the reference's ``TransformerLayer.scala:1`` (GPT-style
self-attention stack over [tokens, positions]) and ``BERT.scala:66`` (inputs
[token ids, token type ids, position ids, attention mask]; outputs block
states + pooled first-token output). The compute path is TPU-native: heads
are one batched ``[b, h, s, d]`` tensor driving the fused attention kernels
in ``ops/attention.py`` (pallas flash kernel on TPU), bf16-friendly, no
per-head Python loops.
"""
from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..engine import Layer
from ...ops.attention import dot_product_attention, flash_attention


def _dense_params(rng, d_in, d_out, init_range):
    wkey, _ = jax.random.split(rng)
    return {"kernel": jax.random.normal(wkey, (d_in, d_out)) * init_range,
            "bias": jnp.zeros((d_out,))}


def _dense(p, x):
    return x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)


def _layer_norm_params(dim):
    return {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}


def _layer_norm(p, x, eps=1e-5):
    x32 = x.astype(jnp.float32)  # stable moments in bf16 pipelines
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return y.astype(x.dtype)


def _dropout(x, rate, rng, training):
    if not training or rate <= 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


class MultiHeadAttention(Layer):
    """Batched multi-head self/cross attention.

    ``call`` input: one tensor [b, s, hidden] (self-attention) or a list
    [query, key_value]. ``mask``: [b, kv_len] 1/0 valid mask folded into an
    additive bias.
    """

    def __init__(self, n_head: int, hidden_size: Optional[int] = None,
                 attn_drop: float = 0.0, output_drop: float = 0.0,
                 causal: bool = False, init_range: float = 0.02,
                 use_flash: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.n_head = n_head
        self.hidden_size = hidden_size
        self.attn_drop = attn_drop
        self.output_drop = output_drop
        self.causal = causal
        self.init_range = init_range
        self.use_flash = use_flash

    def build(self, rng, input_shape):
        shape = input_shape[0] if isinstance(input_shape, list) else input_shape
        hidden = self.hidden_size or shape[-1]
        if hidden % self.n_head:
            raise ValueError(f"hidden {hidden} % n_head {self.n_head} != 0")
        self.hidden_size = hidden
        keys = jax.random.split(rng, 4)
        params = {
            "q": _dense_params(keys[0], shape[-1], hidden, self.init_range),
            "k": _dense_params(keys[1], shape[-1], hidden, self.init_range),
            "v": _dense_params(keys[2], shape[-1], hidden, self.init_range),
            "o": _dense_params(keys[3], hidden, hidden, self.init_range),
        }
        return params, {}

    def compute_output_shape(self, input_shape):
        shape = input_shape[0] if isinstance(input_shape, list) else input_shape
        return tuple(shape[:-1]) + (self.hidden_size or shape[-1],)

    def attend(self, params, x_q, x_kv, mask=None, *, training=False,
               rng=None):
        b, sq, _ = x_q.shape
        h, dh = self.n_head, self.hidden_size // self.n_head
        q = _dense(params["q"], x_q).reshape(b, sq, h, dh).transpose(0, 2, 1, 3)
        k = _dense(params["k"], x_kv).reshape(
            b, x_kv.shape[1], h, dh).transpose(0, 2, 1, 3)
        v = _dense(params["v"], x_kv).reshape(
            b, x_kv.shape[1], h, dh).transpose(0, 2, 1, 3)
        bias = None
        if mask is not None:
            bias = ((1.0 - mask[:, None, None, :].astype(jnp.float32))
                    * -1e9).astype(x_q.dtype)
        drop_rng = None
        if training and self.attn_drop > 0.0 and rng is not None:
            rng, drop_rng = jax.random.split(rng)
        from ...ops.attention import (
            FUSED_SHORT_MAX_SEQ, fused_short_applicable,
            fused_short_attention)
        if (self.use_flash
                and fused_short_applicable(q.shape[-2], k.shape[-2],
                                           self.causal)):
            # short sequences on TPU: single-kernel exact attention — the
            # probability matrix never touches HBM in either direction, and
            # attention dropout runs on the in-kernel PRNG (the BERT-base
            # step is HBM-bound; this path cuts its biggest traffic source)
            key_bias = None if mask is None else bias[:, 0, 0, :]
            ctx = fused_short_attention(
                q, k, v, key_bias=key_bias,
                dropout_rate=self.attn_drop if drop_rng is not None else 0.0,
                dropout_rng=drop_rng, causal=self.causal)
        elif drop_rng is not None:
            # short sequences: the materialized prob matrix is small and the
            # fused-softmax path wins; long ones: streaming + per-block
            # dropout (measured cutover ~512 on v5e)
            if self.use_flash and k.shape[-2] > FUSED_SHORT_MAX_SEQ:
                # streaming attention with per-block dropout: never
                # materializes the [q, kv] probability matrix (equals
                # post-softmax dropout exactly — see blockwise_attention)
                from ...ops.attention import blockwise_attention
                ctx = blockwise_attention(
                    q, k, v, bias=bias, causal=self.causal,
                    dropout_rate=self.attn_drop, dropout_rng=drop_rng)
            else:
                ctx = dot_product_attention(
                    q, k, v, bias=bias, causal=self.causal,
                    dropout_rate=self.attn_drop, dropout_rng=drop_rng)
        elif self.use_flash and k.shape[-2] > FUSED_SHORT_MAX_SEQ:
            # one shared cutover constant: at or below it the fused short
            # kernel (or, when inapplicable, XLA's fused softmax chain —
            # measured 0.9ms vs 1.5ms fwd+bwd per call at the BERT-base
            # shape) beats the streaming flash kernels
            ctx = flash_attention(q, k, v, bias=bias, causal=self.causal)
        else:
            ctx = dot_product_attention(q, k, v, bias=bias, causal=self.causal)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, sq, self.hidden_size)
        out = _dense(params["o"], ctx)
        return _dropout(out, self.output_drop, rng, training)

    def call(self, params, state, inputs, *, training=False, rng=None):
        """Inputs: one tensor (self-attention), [q, kv], or [q, kv, mask]."""
        mask = None
        if isinstance(inputs, (list, tuple)):
            x_q, x_kv = inputs[0], inputs[1]
            if len(inputs) > 2:
                mask = inputs[2]
        else:
            x_q = x_kv = inputs
        return self.attend(params, x_q, x_kv, mask, training=training,
                           rng=rng), state


class _TransformerBase(Layer):
    """Shared transformer encoder stack machinery."""

    def __init__(self, n_block: int, n_head: int, hidden_size: int,
                 intermediate_size: int, hidden_drop: float, attn_drop: float,
                 init_range: float, causal: bool, output_all_block: bool,
                 use_flash: bool = True, compute_dtype=None,
                 name: Optional[str] = None):
        super().__init__(name)
        # mixed precision: embeddings cast to this dtype so every block's
        # matmuls hit the MXU in bf16; layer norms still reduce in f32
        self.compute_dtype = compute_dtype
        self.n_block = n_block
        self.n_head = n_head
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.hidden_drop = hidden_drop
        self.attn_drop = attn_drop
        self.init_range = init_range
        self.causal = causal
        self.output_all_block = output_all_block
        self.use_flash = use_flash
        self.attn = MultiHeadAttention(
            n_head, hidden_size, attn_drop, hidden_drop, causal=causal,
            init_range=init_range, use_flash=use_flash,
            name=f"{self.name}_attn")

    def _block_params(self, rng):
        keys = jax.random.split(rng, 3)
        attn_p, _ = self.attn.build(
            keys[0], (None, None, self.hidden_size))
        return {
            "attn": attn_p,
            "ln1": _layer_norm_params(self.hidden_size),
            "ffn_in": _dense_params(keys[1], self.hidden_size,
                                    self.intermediate_size, self.init_range),
            "ffn_out": _dense_params(keys[2], self.intermediate_size,
                                     self.hidden_size, self.init_range),
            "ln2": _layer_norm_params(self.hidden_size),
        }

    def _run_block(self, p, x, mask, training, rng):
        r1, r2 = (jax.random.split(rng) if rng is not None else (None, None))
        a = self.attn.attend(p["attn"], x, x, mask, training=training, rng=r1)
        x = _layer_norm(p["ln1"], x + a)
        hmid = jax.nn.gelu(_dense(p["ffn_in"], x))
        h = _dropout(_dense(p["ffn_out"], hmid), self.hidden_drop, r2, training)
        return _layer_norm(p["ln2"], x + h)

    def _pooler_params(self, rng):
        return _dense_params(rng, self.hidden_size, self.hidden_size,
                             self.init_range)

    def _pool(self, p, states):
        return jnp.tanh(_dense(p, states[:, 0]))

    def _stack_output_shape(self, seq):
        states = (None, seq, self.hidden_size)
        pooled = (None, self.hidden_size)
        if self.output_all_block:
            return [states] * self.n_block + [pooled]
        return [states, pooled]


class TransformerLayer(_TransformerBase):
    """GPT-style stack (reference ``TransformerLayer.scala``): inputs
    [token ids [b, s], position ids [b, s]]; outputs block state(s) + pooled.
    """

    def __init__(self, vocab: int, hidden_size: int = 768, n_block: int = 12,
                 n_head: int = 12, seq_len: int = 512,
                 intermediate_size: int = 0, hidden_p_drop: float = 0.1,
                 attn_p_drop: float = 0.1, initializer_range: float = 0.02,
                 bidirectional: bool = False, output_all_block: bool = True,
                 use_flash: bool = True, compute_dtype=None,
                 name: Optional[str] = None):
        super().__init__(n_block, n_head, hidden_size, intermediate_size,
                         hidden_p_drop, attn_p_drop, initializer_range,
                         causal=not bidirectional,
                         output_all_block=output_all_block,
                         use_flash=use_flash, compute_dtype=compute_dtype,
                         name=name)
        self.vocab = vocab
        self.seq_len = seq_len

    def build(self, rng, input_shape):
        keys = jax.random.split(rng, self.n_block + 3)
        params = {
            "word_emb": jax.random.normal(
                keys[0], (self.vocab, self.hidden_size)) * self.init_range,
            "pos_emb": jax.random.normal(
                keys[1], (self.seq_len, self.hidden_size)) * self.init_range,
            "pooler": self._pooler_params(keys[2]),
        }
        for i in range(self.n_block):
            params[f"block_{i}"] = self._block_params(keys[3 + i])
        return params, {}

    def compute_output_shape(self, input_shape):
        shape = input_shape[0] if isinstance(input_shape, list) else input_shape
        return self._stack_output_shape(shape[1])

    def call(self, params, state, inputs, *, training=False, rng=None):
        if isinstance(inputs, (list, tuple)):
            tokens, positions = inputs[0], inputs[1]
        else:
            tokens = inputs
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape)
        tokens = tokens.astype(jnp.int32)
        positions = positions.astype(jnp.int32)
        x = params["word_emb"][tokens] + params["pos_emb"][positions]
        if self.compute_dtype is not None:
            x = x.astype(self.compute_dtype)
        all_states = []
        for i in range(self.n_block):
            sub = None
            if rng is not None:
                rng, sub = jax.random.split(rng)
            x = self._run_block(params[f"block_{i}"], x, None, training, sub)
            all_states.append(x)
        pooled = self._pool(params["pooler"], x)
        outs = (all_states if self.output_all_block else [x]) + [pooled]
        return outs, state


class BERT(_TransformerBase):
    """BERT encoder (reference ``BERT.scala:66``): inputs [token ids,
    token type ids, position ids, attention mask]; outputs block state(s) +
    pooled first-token output."""

    def __init__(self, vocab: int = 40990, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12,
                 max_position_len: int = 512, intermediate_size: int = 3072,
                 hidden_p_drop: float = 0.1, attn_p_drop: float = 0.1,
                 initializer_range: float = 0.02,
                 output_all_block: bool = True, use_flash: bool = True,
                 compute_dtype=None, name: Optional[str] = None):
        super().__init__(n_block, n_head, hidden_size, intermediate_size,
                         hidden_p_drop, attn_p_drop, initializer_range,
                         causal=False, output_all_block=output_all_block,
                         use_flash=use_flash, compute_dtype=compute_dtype,
                         name=name)
        self.vocab = vocab
        self.max_position_len = max_position_len

    def build(self, rng, input_shape):
        keys = jax.random.split(rng, self.n_block + 4)
        params = {
            "word_emb": jax.random.normal(
                keys[0], (self.vocab, self.hidden_size)) * self.init_range,
            "pos_emb": jax.random.normal(
                keys[1], (self.max_position_len,
                          self.hidden_size)) * self.init_range,
            "type_emb": jax.random.normal(
                keys[2], (2, self.hidden_size)) * self.init_range,
            "emb_ln": _layer_norm_params(self.hidden_size),
            "pooler": self._pooler_params(keys[3]),
        }
        for i in range(self.n_block):
            params[f"block_{i}"] = self._block_params(keys[4 + i])
        return params, {}

    def compute_output_shape(self, input_shape):
        shape = input_shape[0] if isinstance(input_shape, list) else input_shape
        return self._stack_output_shape(shape[1])

    def call(self, params, state, inputs, *, training=False, rng=None):
        if not isinstance(inputs, (list, tuple)) or len(inputs) < 4:
            raise ValueError("BERT expects [token_ids, token_type_ids, "
                             "position_ids, attention_mask]")
        tokens, types, positions, mask = inputs[:4]
        tokens = tokens.astype(jnp.int32)
        types = types.astype(jnp.int32)
        positions = positions.astype(jnp.int32)
        x = (params["word_emb"][tokens] + params["pos_emb"][positions]
             + params["type_emb"][types])
        if self.compute_dtype is not None:
            x = x.astype(self.compute_dtype)
        x = _layer_norm(params["emb_ln"], x)
        if rng is not None:
            rng, sub = jax.random.split(rng)
            x = _dropout(x, self.hidden_drop, sub, training)
        all_states = []
        for i in range(self.n_block):
            sub = None
            if rng is not None:
                rng, sub = jax.random.split(rng)
            x = self._run_block(params[f"block_{i}"], x, mask, training, sub)
            all_states.append(x)
        pooled = self._pool(params["pooler"], x)
        outs = (all_states if self.output_all_block else [x]) + [pooled]
        return outs, state
