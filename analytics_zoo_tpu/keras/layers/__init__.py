from .core import (  # noqa: F401
    Activation, Dense, Dropout, ElementwiseOp, Flatten, Lambda, Merge, Permute,
    RepeatVector, Reshape, Select, Squeeze, get_activation, merge)
from .embedding import Embedding, WordEmbedding  # noqa: F401
from .norm import BatchNormalization, LayerNormalization  # noqa: F401
from .recurrent import GRU, LSTM, Bidirectional, SimpleRNN  # noqa: F401
from .conv import (  # noqa: F401
    AveragePooling2D, Conv1D, Conv2D, Convolution1D, Convolution2D,
    GlobalAveragePooling1D, GlobalAveragePooling2D, GlobalMaxPooling1D,
    GlobalMaxPooling2D, MaxPooling1D, MaxPooling2D, ZeroPadding2D)
from .attention import (  # noqa: F401
    BERT, MultiHeadAttention, TransformerLayer)
