from .core import (  # noqa: F401
    Activation, Dense, Dropout, ElementwiseOp, Flatten, Lambda, Merge, Permute,
    RepeatVector, Reshape, Select, Squeeze, get_activation, merge)
from .embedding import (  # noqa: F401
    Embedding, SparseDense, SparseEmbedding, WordEmbedding)
from .norm import BatchNormalization, LayerNormalization  # noqa: F401
from .recurrent import (  # noqa: F401
    GRU, LSTM, Bidirectional, ConvLSTM2D, ConvLSTM3D, SimpleRNN)
from .conv import (  # noqa: F401
    AveragePooling2D, Conv1D, Conv2D, Convolution1D, Convolution2D,
    GlobalAveragePooling1D, GlobalAveragePooling2D, GlobalMaxPooling1D,
    GlobalMaxPooling2D, MaxPooling1D, MaxPooling2D, ZeroPadding2D)
from .conv_extended import (  # noqa: F401
    AtrousConvolution1D, AtrousConvolution2D, AveragePooling1D,
    AveragePooling3D, Conv3D, Convolution3D, Cropping1D, Cropping2D,
    Cropping3D, Deconvolution2D, GlobalAveragePooling3D, GlobalMaxPooling3D,
    LocallyConnected1D, LocallyConnected2D, LRN2D, MaxPooling3D,
    ResizeBilinear, SeparableConvolution2D, ShareConvolution2D, UpSampling1D,
    UpSampling2D, UpSampling3D, WithinChannelLRN2D, ZeroPadding1D,
    ZeroPadding3D)
from .advanced import (  # noqa: F401
    AddConstant, BinaryThreshold, CAdd, CMul, ELU, Exp, Expand, ExpandDim,
    GaussianDropout, GaussianNoise, GaussianSampler, GetShape, HardShrink, HardTanh,
    Highway, Identity, LeakyReLU, Log, Masking, Max, MaxoutDense, Mul,
    MulConstant, Narrow, Negative, Power, PReLU, RReLU, Scale, SelectTable,
    Softmax, SoftShrink, SpatialDropout1D, SpatialDropout2D, SpatialDropout3D,
    SplitTensor, Sqrt, Square, SReLU, Threshold, ThresholdedReLU,
    TimeDistributed)
from .attention import (  # noqa: F401
    BERT, MultiHeadAttention, TransformerLayer)
from .crf import CRF, crf_decode, crf_nll  # noqa: F401
