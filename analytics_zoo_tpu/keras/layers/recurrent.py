"""Recurrent layers (reference ``LSTM.scala``/``GRU.scala``/``SimpleRNN``/
``Bidirectional.scala``).

TPU design: the time loop is a single ``lax.scan`` whose body is one fused
cell step — all four LSTM gates come from ONE ``[B, in+hidden] @ [in+hidden,
4*units]`` matmul so the MXU sees a large tile per step instead of eight small
ones (XLA cannot re-fuse gate-by-gate matmuls across a scan boundary). Static
sequence length, no per-step Python.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import initializers
from ..engine import Layer
from .core import get_activation


class _RNNBase(Layer):
    def __init__(self, output_dim: int, return_sequences: bool = False,
                 go_backwards: bool = False, return_state: bool = False,
                 init="glorot_uniform",
                 inner_init="orthogonal", name: Optional[str] = None):
        super().__init__(name)
        self.output_dim = output_dim
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.return_state = return_state
        self.init = initializers.get(init if init != "orthogonal" else "glorot_uniform")
        self.inner_init = self._orthogonal if inner_init == "orthogonal" \
            else initializers.get(inner_init)

    @staticmethod
    def _orthogonal(rng, shape, dtype=jnp.float32):
        rows, cols = shape
        a = jax.random.normal(rng, (max(rows, cols), min(rows, cols)), dtype)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))[None, :]
        if rows < cols:
            q = q.T
        return q[:rows, :cols]

    def compute_output_shape(self, input_shape):
        if isinstance(input_shape, list):
            input_shape = input_shape[0]
        out = ((input_shape[0], input_shape[1], self.output_dim)
               if self.return_sequences else (input_shape[0], self.output_dim))
        if self.return_state:
            n_states = 2 if isinstance(self, LSTM) else 1
            return [out] + [(input_shape[0], self.output_dim)] * n_states
        return out

    def _run_scan(self, step, carry0, inputs):
        xs = jnp.swapaxes(inputs, 0, 1)  # [T, B, D] scan layout
        if self.go_backwards:
            xs = xs[::-1]
        carry, ys = jax.lax.scan(step, carry0, xs)
        if self.go_backwards:
            ys = ys[::-1]
        return carry, jnp.swapaxes(ys, 0, 1)


class LSTM(_RNNBase):
    def build(self, rng, input_shape):
        if isinstance(input_shape, list):
            input_shape = input_shape[0]
        in_dim = input_shape[-1]
        u = self.output_dim
        k1, k2 = jax.random.split(rng)
        # fused gate kernel: [in+hidden, 4u] (i, f, g, o)
        kernel = jnp.concatenate(
            [self.init(k1, (in_dim, 4 * u)), self.inner_init(k2, (u, 4 * u))], axis=0)
        bias = jnp.zeros((4 * u,)).at[u:2 * u].set(1.0)  # forget-gate bias 1
        return {"kernel": kernel, "bias": bias}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        u = self.output_dim
        kernel, bias = params["kernel"], params["bias"]
        if isinstance(inputs, (list, tuple)):  # [x, h0, c0] initial state
            x, h0, c0 = inputs[0], inputs[1], inputs[2]
        else:
            x, h0, c0 = inputs, None, None
        B = x.shape[0]
        dtype = x.dtype

        def step(carry, x_t):
            h, c = carry
            z = jnp.concatenate([x_t, h], axis=-1) @ kernel.astype(dtype) + bias.astype(dtype)
            i, f, g, o = jnp.split(z, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        carry0 = (h0 if h0 is not None else jnp.zeros((B, u), dtype),
                  c0 if c0 is not None else jnp.zeros((B, u), dtype))
        (h, c), ys = self._run_scan(step, carry0, x)
        out = ys if self.return_sequences else h
        if self.return_state:
            return [out, h, c], state
        return out, state


class GRU(_RNNBase):
    def build(self, rng, input_shape):
        if isinstance(input_shape, list):
            input_shape = input_shape[0]
        in_dim = input_shape[-1]
        u = self.output_dim
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        gates = jnp.concatenate(
            [self.init(k1, (in_dim, 2 * u)), self.inner_init(k2, (u, 2 * u))], axis=0)
        cand = jnp.concatenate(
            [self.init(k3, (in_dim, u)), self.inner_init(k4, (u, u))], axis=0)
        return {"gates": gates, "candidate": cand,
                "gate_bias": jnp.zeros((2 * u,)), "cand_bias": jnp.zeros((u,))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        u = self.output_dim
        if isinstance(inputs, (list, tuple)):  # [x, h0] initial state
            x, h0_in = inputs[0], inputs[1]
        else:
            x, h0_in = inputs, None
        B = x.shape[0]
        dtype = x.dtype
        gates_k = params["gates"].astype(dtype)
        cand_k = params["candidate"].astype(dtype)
        gb, cb = params["gate_bias"].astype(dtype), params["cand_bias"].astype(dtype)

        def step(h, x_t):
            zr = jnp.concatenate([x_t, h], axis=-1) @ gates_k + gb
            z, r = jnp.split(jax.nn.sigmoid(zr), 2, axis=-1)
            hh = jnp.tanh(jnp.concatenate([x_t, r * h], axis=-1) @ cand_k + cb)
            h_new = z * h + (1 - z) * hh
            return h_new, h_new

        h0 = h0_in if h0_in is not None else jnp.zeros((B, u), dtype)
        h, ys = self._run_scan(step, h0, x)
        out = ys if self.return_sequences else h
        if self.return_state:
            return [out, h], state
        return out, state


class SimpleRNN(_RNNBase):
    def __init__(self, output_dim: int, activation="tanh", **kwargs):
        super().__init__(output_dim, **kwargs)
        self.activation = get_activation(activation)

    def build(self, rng, input_shape):
        if isinstance(input_shape, list):
            input_shape = input_shape[0]
        in_dim = input_shape[-1]
        u = self.output_dim
        k1, k2 = jax.random.split(rng)
        kernel = jnp.concatenate(
            [self.init(k1, (in_dim, u)), self.inner_init(k2, (u, u))], axis=0)
        return {"kernel": kernel, "bias": jnp.zeros((u,))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        u = self.output_dim
        if isinstance(inputs, (list, tuple)):  # [x, h0] initial state
            x, h0_in = inputs[0], inputs[1]
        else:
            x, h0_in = inputs, None
        B = x.shape[0]
        dtype = x.dtype
        kernel = params["kernel"].astype(dtype)
        bias = params["bias"].astype(dtype)

        def step(h, x_t):
            h_new = self.activation(jnp.concatenate([x_t, h], axis=-1) @ kernel + bias)
            return h_new, h_new

        h0 = h0_in if h0_in is not None else jnp.zeros((B, u), dtype)
        h, ys = self._run_scan(step, h0, x)
        out = ys if self.return_sequences else h
        if self.return_state:
            return [out, h], state
        return out, state


class Bidirectional(Layer):
    """Wraps a recurrent layer; runs forward + backward and merges
    (reference ``Bidirectional.scala``)."""

    def __init__(self, layer: _RNNBase, merge_mode: str = "concat",
                 name: Optional[str] = None):
        super().__init__(name)
        import copy
        self.forward = layer
        self.backward = copy.copy(layer)
        self.backward.name = layer.name + "_bwd"
        self.backward.go_backwards = not layer.go_backwards
        self.merge_mode = merge_mode

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        fp, _ = self.forward.build(k1, input_shape)
        bp, _ = self.backward.build(k2, input_shape)
        return {"forward": fp, "backward": bp}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        yf, _ = self.forward.call(params["forward"], {}, inputs, training=training)
        yb, _ = self.backward.call(params["backward"], {}, inputs, training=training)
        if self.merge_mode == "concat":
            return jnp.concatenate([yf, yb], axis=-1), state
        if self.merge_mode == "sum":
            return yf + yb, state
        if self.merge_mode == "mul":
            return yf * yb, state
        if self.merge_mode == "ave":
            return (yf + yb) / 2, state
        raise ValueError(f"unknown merge_mode {self.merge_mode}")

    def compute_output_shape(self, input_shape):
        shape = self.forward.compute_output_shape(input_shape)
        if self.merge_mode == "concat":
            return tuple(shape[:-1]) + (shape[-1] * 2,)
        return shape


class ConvLSTM2D(Layer):
    """Convolutional LSTM over [B, T, H, W, C] (reference ``ConvLSTM2D.scala``).

    TPU design: one ``lax.scan`` over time whose body does a SINGLE fused
    conv producing all four gates ([kh, kw, cin+units, 4*units]) — the same
    fused-gate trick as LSTM, keeping the MXU tile large per step.
    """

    def __init__(self, nb_filter: int, nb_kernel: int, subsample=(1, 1),
                 border_mode: str = "same", return_sequences: bool = False,
                 go_backwards: bool = False, init="glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = nb_filter
        self.kernel_size = (nb_kernel, nb_kernel) if isinstance(nb_kernel, int) \
            else tuple(nb_kernel)
        self.strides = (subsample, subsample) if isinstance(subsample, int) \
            else tuple(subsample)
        if border_mode != "same":
            raise ValueError("ConvLSTM2D supports border_mode='same' only "
                             "(state and input must share spatial dims)")
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.init = initializers.get(init)

    def build(self, rng, input_shape):
        # input_shape: (B, T, H, W, C)
        cin = input_shape[-1]
        kh, kw = self.kernel_size
        u = self.filters
        kernel = self.init(rng, (kh, kw, cin + u, 4 * u))
        bias = jnp.zeros((4 * u,)).at[u:2 * u].set(1.0)  # forget bias 1
        return {"kernel": kernel, "bias": bias}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        from jax import lax
        u = self.filters
        kernel = params["kernel"].astype(inputs.dtype)
        bias = params["bias"].astype(inputs.dtype)
        B, T, H, W, C = inputs.shape
        sh, sw = self.strides
        Ho, Wo = -(-H // sh), -(-W // sw)

        def step(carry, x_t):
            h, c = carry
            # state is at output resolution; upsample back if strided so the
            # concat shares spatial dims with the input
            if (sh, sw) != (1, 1):
                h_in = jnp.repeat(jnp.repeat(h, sh, axis=1), sw, axis=2)[:, :H, :W]
            else:
                h_in = h
            z = lax.conv_general_dilated(
                jnp.concatenate([x_t, h_in], axis=-1), kernel,
                window_strides=self.strides, padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + bias
            i, f, g, o = jnp.split(z, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        xs = jnp.swapaxes(inputs, 0, 1)  # [T, B, H, W, C]
        if self.go_backwards:
            xs = xs[::-1]
        zeros = jnp.zeros((B, Ho, Wo, u), inputs.dtype)
        (h, c), ys = jax.lax.scan(step, (zeros, zeros), xs)
        if self.go_backwards:
            ys = ys[::-1]
        if self.return_sequences:
            return jnp.swapaxes(ys, 0, 1), state
        return h, state

    def compute_output_shape(self, input_shape):
        n, t, h, w, _ = input_shape
        sh, sw = self.strides
        ho = None if h is None else -(-h // sh)
        wo = None if w is None else -(-w // sw)
        if self.return_sequences:
            return (n, t, ho, wo, self.filters)
        return (n, ho, wo, self.filters)


class ConvLSTM3D(Layer):
    """Convolutional LSTM over [B, T, D, H, W, C] volumes (reference
    ``ConvLSTM3D.scala``). Same fused-gate design as :class:`ConvLSTM2D`,
    with a single 3D conv producing all four gates per scan step."""

    def __init__(self, nb_filter: int, nb_kernel: int, subsample=(1, 1, 1),
                 border_mode: str = "same", return_sequences: bool = False,
                 go_backwards: bool = False, init="glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = nb_filter
        self.kernel_size = (nb_kernel,) * 3 if isinstance(nb_kernel, int) \
            else tuple(nb_kernel)
        self.strides = (subsample,) * 3 if isinstance(subsample, int) \
            else tuple(subsample)
        if border_mode != "same":
            raise ValueError("ConvLSTM3D supports border_mode='same' only")
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.init = initializers.get(init)

    def build(self, rng, input_shape):
        # input_shape: (B, T, D, H, W, C)
        cin = input_shape[-1]
        kd, kh, kw = self.kernel_size
        u = self.filters
        kernel = self.init(rng, (kd, kh, kw, cin + u, 4 * u))
        bias = jnp.zeros((4 * u,)).at[u:2 * u].set(1.0)  # forget bias 1
        return {"kernel": kernel, "bias": bias}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        from jax import lax
        u = self.filters
        kernel = params["kernel"].astype(inputs.dtype)
        bias = params["bias"].astype(inputs.dtype)
        B, T, D, H, W, C = inputs.shape
        sd, sh, sw = self.strides
        Do, Ho, Wo = -(-D // sd), -(-H // sh), -(-W // sw)

        def step(carry, x_t):
            h, c = carry
            if (sd, sh, sw) != (1, 1, 1):
                h_in = jnp.repeat(jnp.repeat(jnp.repeat(
                    h, sd, axis=1), sh, axis=2), sw, axis=3)[:, :D, :H, :W]
            else:
                h_in = h
            z = lax.conv_general_dilated(
                jnp.concatenate([x_t, h_in], axis=-1), kernel,
                window_strides=self.strides, padding="SAME",
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC")) + bias
            i, f, g, o = jnp.split(z, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        xs = jnp.swapaxes(inputs, 0, 1)  # [T, B, D, H, W, C]
        if self.go_backwards:
            xs = xs[::-1]
        zeros = jnp.zeros((B, Do, Ho, Wo, u), inputs.dtype)
        (h, c), ys = jax.lax.scan(step, (zeros, zeros), xs)
        if self.go_backwards:
            ys = ys[::-1]
        if self.return_sequences:
            return jnp.swapaxes(ys, 0, 1), state
        return h, state

    def compute_output_shape(self, input_shape):
        n, t, d, h, w, _ = input_shape
        sd, sh, sw = self.strides
        do = None if d is None else -(-d // sd)
        ho = None if h is None else -(-h // sh)
        wo = None if w is None else -(-w // sw)
        if self.return_sequences:
            return (n, t, do, ho, wo, self.filters)
        return (n, do, ho, wo, self.filters)
