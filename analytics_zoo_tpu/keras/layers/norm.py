"""Normalization layers (reference ``BatchNormalization.scala``,
``LayerNorm`` in ``TransformerLayer.scala``).

BatchNorm carries running statistics as mutable *state* threaded through the
pure ``call`` — the functional equivalent of BigDL's in-place runningMean/Var.
Under data parallelism the batch statistics are computed over the *global*
batch via ``lax.pmean`` over the data axis when inside a shard_map context,
matching the reference's cross-replica ``setParallism`` BN sync semantics
(``examples/resnet/TrainImageNet.scala:90-96``); under plain jit+sharding XLA
computes global-batch moments automatically because the reduction spans the
whole sharded array.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..engine import Layer


class BatchNormalization(Layer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon
        self.momentum = momentum
        self.axis = axis

    def build(self, rng, input_shape):
        dim = input_shape[self.axis]
        params = {"gamma": jnp.ones((dim,)), "beta": jnp.zeros((dim,))}
        state = {"moving_mean": jnp.zeros((dim,)),
                 "moving_var": jnp.ones((dim,))}
        return params, state

    def call(self, params, state, inputs, *, training=False, rng=None):
        reduce_axes = tuple(i for i in range(inputs.ndim)
                            if i != (inputs.ndim + self.axis if self.axis < 0
                                     else self.axis))
        if training:
            # two-moment statistics in ONE pass over the (bf16) activations:
            # the cast/square/reduce chain fuses into a single HBM sweep with
            # f32 accumulators — materializing a float32 copy of the whole
            # activation tensor (the old path) costs ~35% of a ResNet-50
            # train step (see bench ablation)
            xf = inputs.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            m = self.momentum
            new_state = {
                "moving_mean": m * state["moving_mean"] + (1 - m) * mean,
                "moving_var": m * state["moving_var"] + (1 - m) * var,
            }
        else:
            mean, var = state["moving_mean"], state["moving_var"]
            new_state = state
        # fold (mean, var, gamma, beta) into one per-channel scale+shift; the
        # multiply-add runs on f32 VALUES (cast→fma→cast fuses into a single
        # HBM sweep — no f32 tensor is materialized) so x*a and b don't
        # catastrophically cancel in bf16 when |mean| >> std
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + self.epsilon)
        a = params["gamma"] * inv
        b = params["beta"] - params["gamma"] * inv * mean
        return (inputs.astype(jnp.float32) * a + b).astype(inputs.dtype), \
            new_state


class LayerNormalization(Layer):
    def __init__(self, epsilon: float = 1e-5, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon

    def build(self, rng, input_shape):
        dim = input_shape[-1]
        return {"gamma": jnp.ones((dim,)), "beta": jnp.zeros((dim,))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        # one fused sweep: cast/square/reduce with f32 accumulators, then a
        # single scale+shift in the compute dtype (same recipe as BatchNorm —
        # a materialized f32 copy of the activations is the expensive part)
        xf = inputs.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        mean_sq = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        inv = jax.lax.rsqrt(var + self.epsilon)
        a = params["gamma"] * inv
        b = params["beta"] - params["gamma"] * inv * mean
        return (xf * a + b).astype(inputs.dtype), state
