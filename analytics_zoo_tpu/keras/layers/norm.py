"""Normalization layers (reference ``BatchNormalization.scala``,
``LayerNorm`` in ``TransformerLayer.scala``).

BatchNorm carries running statistics as mutable *state* threaded through the
pure ``call`` — the functional equivalent of BigDL's in-place runningMean/Var.
Under data parallelism the batch statistics are computed over the *global*
batch via ``lax.pmean`` over the data axis when inside a shard_map context,
matching the reference's cross-replica ``setParallism`` BN sync semantics
(``examples/resnet/TrainImageNet.scala:90-96``); under plain jit+sharding XLA
computes global-batch moments automatically because the reduction spans the
whole sharded array.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..engine import Layer


class BatchNormalization(Layer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon
        self.momentum = momentum
        self.axis = axis

    def build(self, rng, input_shape):
        dim = input_shape[self.axis]
        params = {"gamma": jnp.ones((dim,)), "beta": jnp.zeros((dim,))}
        state = {"moving_mean": jnp.zeros((dim,)),
                 "moving_var": jnp.ones((dim,))}
        return params, state

    def call(self, params, state, inputs, *, training=False, rng=None):
        reduce_axes = tuple(i for i in range(inputs.ndim)
                            if i != (inputs.ndim + self.axis if self.axis < 0
                                     else self.axis))
        x32 = inputs.astype(jnp.float32)  # stable moments in bf16 pipelines
        if training:
            mean = jnp.mean(x32, axis=reduce_axes)
            var = jnp.var(x32, axis=reduce_axes)
            m = self.momentum
            new_state = {
                "moving_mean": m * state["moving_mean"] + (1 - m) * mean,
                "moving_var": m * state["moving_var"] + (1 - m) * var,
            }
        else:
            mean, var = state["moving_mean"], state["moving_var"]
            new_state = state
        inv = jnp.reciprocal(jnp.sqrt(var + self.epsilon))
        y = (x32 - mean) * inv * params["gamma"] + params["beta"]
        return y.astype(inputs.dtype), new_state


class LayerNormalization(Layer):
    def __init__(self, epsilon: float = 1e-5, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon

    def build(self, rng, input_shape):
        dim = input_shape[-1]
        return {"gamma": jnp.ones((dim,)), "beta": jnp.zeros((dim,))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        x32 = inputs.astype(jnp.float32)  # stable moments even in bf16 pipelines
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + self.epsilon))
        y = y * params["gamma"] + params["beta"]
        return y.astype(inputs.dtype), state
