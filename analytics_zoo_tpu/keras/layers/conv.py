"""Convolution and pooling layers (reference ``Convolution{1,2}D.scala``,
``MaxPooling*.scala``, ``AveragePooling*.scala``, ``GlobalAveragePooling*``).

TPU design: NHWC layout (XLA's preferred TPU conv layout), channels-last
kernels ``[kh, kw, cin, cout]``, ``lax.conv_general_dilated`` so XLA tiles
directly onto the MXU. The reference's Theano/TF "th" channel-first mode is
not reproduced — NHWC is the native layout and converters handle imports.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import initializers
from ..engine import Layer
from .core import get_activation


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v, v)


def _padding_of(border_mode):
    """``border_mode`` → lax padding: "same"/"valid", or an explicit int /
    (ph, pw) pair of symmetric pads (extension beyond keras-1 — needed for
    bit-exact torch-geometry imports, where stride-2 convs pad both sides
    while SAME pads asymmetrically)."""
    if border_mode == "same":
        return "SAME"
    if border_mode == "valid":
        return "VALID"
    ph, pw = _pair(border_mode)
    return ((int(ph), int(ph)), (int(pw), int(pw)))


def _conv_out(size, k, stride, padding, axis=0):
    if size is None:
        return None
    if padding == "SAME":
        return -(-size // stride)
    if padding == "VALID":
        return (size - k) // stride + 1
    lo, hi = padding[axis]
    return (size + lo + hi - k) // stride + 1


class Convolution2D(Layer):
    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), border_mode="valid",
                 init="glorot_uniform", bias: bool = True,
                 dilation=(1, 1), groups: int = 1,
                 int8_training: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.strides = _pair(subsample)
        self.padding = _padding_of(border_mode)
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias
        self.dilation = _pair(dilation)
        self.groups = groups
        # EXPERIMENTAL: run the forward on the int8 MXU path with
        # straight-through-estimator gradients and int8-stored residual
        # activations (ops/int8_training.py) — the byte-cut lever past the
        # bf16 HBM roofline. Quantization noise changes training numerics;
        # opt-in per layer/model.
        self.int8_training = int8_training

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        kh, kw = self.kernel_size
        params = {"kernel": self.init(rng, (kh, kw, cin // self.groups, self.filters))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        kernel = params["kernel"]
        if isinstance(kernel, dict) and "q" in kernel:
            # int8-quantized kernel (inference/quantize.py): int8 conv with
            # calibrated activation scales, weight-dequant otherwise
            from ...inference.quantize import qconv_apply
            y = qconv_apply(inputs, kernel, self.strides, self.padding,
                            self.dilation, self.groups)
        elif self.int8_training:
            from ...ops.int8_training import int8_train_conv
            y = int8_train_conv(inputs, kernel, self.strides, self.padding,
                                self.dilation, self.groups)
        else:
            y = lax.conv_general_dilated(
                inputs, kernel.astype(inputs.dtype),
                window_strides=self.strides, padding=self.padding,
                rhs_dilation=self.dilation, feature_group_count=self.groups,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self.kernel_size
        sh, sw = self.strides
        return (n, _conv_out(h, kh, sh, self.padding, 0),
                _conv_out(w, kw, sw, self.padding, 1), self.filters)


Conv2D = Convolution2D


class Convolution1D(Layer):
    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 subsample_length: int = 1, border_mode="valid",
                 init="glorot_uniform", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = nb_filter
        self.kernel_size = filter_length
        self.stride = subsample_length
        self.padding = "SAME" if border_mode == "same" else "VALID"
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        params = {"kernel": self.init(rng, (self.kernel_size, cin, self.filters))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            inputs, params["kernel"].astype(inputs.dtype),
            window_strides=(self.stride,), padding=self.padding,
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        n, l, _ = input_shape
        return (n, _conv_out(l, self.kernel_size, self.stride, self.padding),
                self.filters)


Conv1D = Convolution1D


class _Pool2D(Layer):
    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 name: Optional[str] = None):
        super().__init__(name)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.padding = _padding_of(border_mode)

    def compute_output_shape(self, input_shape):
        n, h, w, c = input_shape
        ph, pw = self.pool_size
        sh, sw = self.strides
        return (n, _conv_out(h, ph, sh, self.padding, 0),
                _conv_out(w, pw, sw, self.padding, 1), c)

    def _reduce(self, inputs, init, op):
        ph, pw = self.pool_size
        sh, sw = self.strides
        padding = self.padding
        if not isinstance(padding, str):
            padding = ((0, 0), padding[0], padding[1], (0, 0))
        return lax.reduce_window(inputs, init, op, (1, ph, pw, 1),
                                 (1, sh, sw, 1), padding)


class MaxPooling2D(_Pool2D):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return self._reduce(inputs, -jnp.inf, lax.max), state


class AveragePooling2D(_Pool2D):
    def call(self, params, state, inputs, *, training=False, rng=None):
        ph, pw = self.pool_size
        summed = self._reduce(inputs, 0.0, lax.add)
        return summed / (ph * pw), state


class MaxPooling1D(Layer):
    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 name: Optional[str] = None):
        super().__init__(name)
        self.pool = pool_length
        self.stride = stride or pool_length
        self.padding = "SAME" if border_mode == "same" else "VALID"

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = lax.reduce_window(inputs, -jnp.inf, lax.max, (1, self.pool, 1),
                              (1, self.stride, 1), self.padding)
        return y, state

    def compute_output_shape(self, input_shape):
        n, l, c = input_shape
        return (n, _conv_out(l, self.pool, self.stride, self.padding), c)


class GlobalMaxPooling2D(Layer):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.max(inputs, axis=(1, 2)), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], input_shape[3])


class GlobalAveragePooling2D(Layer):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.mean(inputs, axis=(1, 2)), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], input_shape[3])


class GlobalMaxPooling1D(Layer):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.max(inputs, axis=1), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], input_shape[2])


class GlobalAveragePooling1D(Layer):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.mean(inputs, axis=1), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], input_shape[2])


class ZeroPadding2D(Layer):
    def __init__(self, padding=(1, 1), name: Optional[str] = None):
        super().__init__(name)
        self.pad = _pair(padding)

    def call(self, params, state, inputs, *, training=False, rng=None):
        ph, pw = self.pad
        return jnp.pad(inputs, ((0, 0), (ph, ph), (pw, pw), (0, 0))), state

    def compute_output_shape(self, input_shape):
        n, h, w, c = input_shape
        ph, pw = self.pad
        return (n, None if h is None else h + 2 * ph,
                None if w is None else w + 2 * pw, c)
