"""Extended convolution / pooling / resampling layers.

Reference: ``zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/keras/
layers/{Convolution3D,Deconvolution2D,SeparableConvolution2D,
AtrousConvolution1D,AtrousConvolution2D,LocallyConnected1D,LocallyConnected2D,
ShareConvolution2D,AveragePooling1D,AveragePooling3D,MaxPooling3D,
GlobalAveragePooling3D,GlobalMaxPooling3D,Cropping1D,Cropping2D,Cropping3D,
UpSampling1D,UpSampling2D,UpSampling3D,ZeroPadding1D,ZeroPadding3D,
ResizeBilinear,LRN2D,WithinChannelLRN2D}.scala``.

TPU design notes: all convs go through ``lax.conv_general_dilated`` in
channels-last layouts so XLA tiles onto the MXU; 3D uses NDHWC. Transposed
conv uses ``lax.conv_transpose``. Locally-connected layers materialise a
position-indexed kernel and contract with ``einsum`` (one big MXU matmul,
not a Python loop over positions).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import initializers
from ..engine import Layer
from .conv import _conv_out, _pair
from .core import get_activation


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v, v, v)


class Convolution3D(Layer):
    """3D conv over NDHWC volumes (reference ``Convolution3D.scala``)."""

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, activation=None, subsample=(1, 1, 1),
                 border_mode="valid", init="glorot_uniform", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = nb_filter
        self.kernel_size = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.strides = _triple(subsample)
        self.padding = "SAME" if border_mode == "same" else "VALID"
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        kd, kh, kw = self.kernel_size
        params = {"kernel": self.init(rng, (kd, kh, kw, cin, self.filters))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            inputs, params["kernel"].astype(inputs.dtype),
            window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        n, d, h, w, _ = input_shape
        kd, kh, kw = self.kernel_size
        sd, sh, sw = self.strides
        return (n, _conv_out(d, kd, sd, self.padding),
                _conv_out(h, kh, sh, self.padding),
                _conv_out(w, kw, sw, self.padding), self.filters)


Conv3D = Convolution3D


class Deconvolution2D(Layer):
    """Transposed 2D conv (reference ``Deconvolution2D.scala``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), border_mode="valid",
                 init="glorot_uniform", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.strides = _pair(subsample)
        self.padding = "SAME" if border_mode == "same" else "VALID"
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        kh, kw = self.kernel_size
        params = {"kernel": self.init(rng, (kh, kw, cin, self.filters))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = lax.conv_transpose(
            inputs, params["kernel"].astype(inputs.dtype),
            strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self.kernel_size
        sh, sw = self.strides

        def up(size, k, s):
            if size is None:
                return None
            if self.padding == "SAME":
                return size * s
            return size * s + max(k - s, 0)

        return (n, up(h, kh, sh), up(w, kw, sw), self.filters)


class SeparableConvolution2D(Layer):
    """Depthwise + pointwise conv (reference ``SeparableConvolution2D.scala``).

    Depthwise = grouped conv with ``feature_group_count=cin``; the pointwise
    1x1 is a plain MXU matmul over channels.
    """

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), border_mode="valid",
                 depth_multiplier: int = 1, init="glorot_uniform",
                 bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.filters = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.strides = _pair(subsample)
        self.padding = "SAME" if border_mode == "same" else "VALID"
        self.depth_multiplier = depth_multiplier
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        kh, kw = self.kernel_size
        k1, k2 = jax.random.split(rng)
        params = {
            "depthwise": self.init(k1, (kh, kw, 1, cin * self.depth_multiplier)),
            "pointwise": self.init(k2, (1, 1, cin * self.depth_multiplier,
                                        self.filters)),
        }
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        cin = inputs.shape[-1]
        y = lax.conv_general_dilated(
            inputs, params["depthwise"].astype(inputs.dtype),
            window_strides=self.strides, padding=self.padding,
            feature_group_count=cin,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = lax.conv_general_dilated(
            y, params["pointwise"].astype(y.dtype),
            window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self.kernel_size
        sh, sw = self.strides
        return (n, _conv_out(h, kh, sh, self.padding),
                _conv_out(w, kw, sw, self.padding), self.filters)


class AtrousConvolution2D(Layer):
    """Dilated 2D conv (reference ``AtrousConvolution2D.scala``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), atrous_rate=(1, 1),
                 border_mode="valid", init="glorot_uniform", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        from .conv import Convolution2D
        self._conv = Convolution2D(
            nb_filter, nb_row, nb_col, activation=activation,
            subsample=subsample, border_mode=border_mode, init=init,
            bias=bias, dilation=_pair(atrous_rate), name=(name or self.name) + "_inner")
        self.atrous_rate = _pair(atrous_rate)

    def build(self, rng, input_shape):
        return self._conv.build(rng, input_shape)

    def call(self, params, state, inputs, *, training=False, rng=None):
        return self._conv.call(params, state, inputs, training=training, rng=rng)

    def compute_output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self._conv.kernel_size
        dh, dw = self.atrous_rate
        sh, sw = self._conv.strides
        eff_kh = kh + (kh - 1) * (dh - 1)
        eff_kw = kw + (kw - 1) * (dw - 1)
        return (n, _conv_out(h, eff_kh, sh, self._conv.padding),
                _conv_out(w, eff_kw, sw, self._conv.padding), self._conv.filters)


class AtrousConvolution1D(Layer):
    """Dilated 1D conv (reference ``AtrousConvolution1D.scala``)."""

    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 subsample_length: int = 1, atrous_rate: int = 1,
                 border_mode="valid", init="glorot_uniform", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = nb_filter
        self.kernel_size = filter_length
        self.stride = subsample_length
        self.rate = atrous_rate
        self.padding = "SAME" if border_mode == "same" else "VALID"
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        params = {"kernel": self.init(rng, (self.kernel_size, cin, self.filters))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            inputs, params["kernel"].astype(inputs.dtype),
            window_strides=(self.stride,), padding=self.padding,
            rhs_dilation=(self.rate,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        n, l, _ = input_shape
        eff_k = self.kernel_size + (self.kernel_size - 1) * (self.rate - 1)
        return (n, _conv_out(l, eff_k, self.stride, self.padding), self.filters)


class ShareConvolution2D(Layer):
    """Weight-shared conv used by SSD heads (reference
    ``ShareConvolution2D.scala``); functionally a Convolution2D here since
    JAX params are shared by passing the same pytree."""

    def __init__(self, *args, **kwargs):
        super().__init__(kwargs.pop("name", None))
        from .conv import Convolution2D
        self._conv = Convolution2D(*args, **kwargs)

    def build(self, rng, input_shape):
        return self._conv.build(rng, input_shape)

    def call(self, params, state, inputs, *, training=False, rng=None):
        return self._conv.call(params, state, inputs, training=training, rng=rng)

    def compute_output_shape(self, input_shape):
        return self._conv.compute_output_shape(input_shape)


class LocallyConnected1D(Layer):
    """Per-position (unshared) 1D conv (reference ``LocallyConnected1D.scala``).

    Materialised as an einsum over [L_out, K*Cin, F] position-kernels — a
    single batched matmul on the MXU rather than per-position loops.
    """

    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 subsample_length: int = 1, border_mode="valid",
                 init="glorot_uniform", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        if border_mode != "valid":
            raise ValueError("LocallyConnected1D only supports border_mode='valid'")
        self.filters = nb_filter
        self.kernel_size = filter_length
        self.stride = subsample_length
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias

    def _out_len(self, l):
        return (l - self.kernel_size) // self.stride + 1

    def build(self, rng, input_shape):
        _, l, cin = input_shape
        lo = self._out_len(l)
        params = {"kernel": self.init(
            rng, (lo, self.kernel_size * cin, self.filters))}
        if self.use_bias:
            params["bias"] = jnp.zeros((lo, self.filters))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        b, l, cin = inputs.shape
        lo = self._out_len(l)
        idx = (jnp.arange(lo)[:, None] * self.stride
               + jnp.arange(self.kernel_size)[None, :])  # [Lo, K]
        patches = inputs[:, idx, :].reshape(b, lo, self.kernel_size * cin)
        y = jnp.einsum("blk,lkf->blf", patches,
                       params["kernel"].astype(inputs.dtype))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        n, l, _ = input_shape
        return (n, None if l is None else self._out_len(l), self.filters)


class LocallyConnected2D(Layer):
    """Per-position (unshared) 2D conv (reference ``LocallyConnected2D.scala``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), border_mode="valid",
                 init="glorot_uniform", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        if border_mode != "valid":
            raise ValueError("LocallyConnected2D only supports border_mode='valid'")
        self.filters = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.strides = _pair(subsample)
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias

    def _out_hw(self, h, w):
        kh, kw = self.kernel_size
        sh, sw = self.strides
        return (h - kh) // sh + 1, (w - kw) // sw + 1

    def build(self, rng, input_shape):
        _, h, w, cin = input_shape
        ho, wo = self._out_hw(h, w)
        kh, kw = self.kernel_size
        params = {"kernel": self.init(
            rng, (ho * wo, kh * kw * cin, self.filters))}
        if self.use_bias:
            params["bias"] = jnp.zeros((ho * wo, self.filters))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        b, h, w, cin = inputs.shape
        ho, wo = self._out_hw(h, w)
        kh, kw = self.kernel_size
        sh, sw = self.strides
        ridx = jnp.arange(ho)[:, None] * sh + jnp.arange(kh)[None, :]  # [Ho,Kh]
        cidx = jnp.arange(wo)[:, None] * sw + jnp.arange(kw)[None, :]  # [Wo,Kw]
        # gather patches -> [B, Ho, Kh, Wo, Kw, C] -> [B, Ho*Wo, Kh*Kw*C]
        patches = inputs[:, ridx, :, :][:, :, :, cidx, :]
        patches = patches.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, ho * wo, kh * kw * cin)
        y = jnp.einsum("blk,lkf->blf", patches,
                       params["kernel"].astype(inputs.dtype))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y).reshape(b, ho, wo, self.filters), state

    def compute_output_shape(self, input_shape):
        n, h, w, _ = input_shape
        if h is None or w is None:
            return (n, None, None, self.filters)
        ho, wo = self._out_hw(h, w)
        return (n, ho, wo, self.filters)


# -- pooling extras ----------------------------------------------------------


class AveragePooling1D(Layer):
    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 name: Optional[str] = None):
        super().__init__(name)
        self.pool = pool_length
        self.stride = stride or pool_length
        self.padding = "SAME" if border_mode == "same" else "VALID"

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = lax.reduce_window(inputs, 0.0, lax.add, (1, self.pool, 1),
                              (1, self.stride, 1), self.padding)
        return y / self.pool, state

    def compute_output_shape(self, input_shape):
        n, l, c = input_shape
        return (n, _conv_out(l, self.pool, self.stride, self.padding), c)


class _Pool3D(Layer):
    def __init__(self, pool_size=(2, 2, 2), strides=None, border_mode="valid",
                 name: Optional[str] = None):
        super().__init__(name)
        self.pool_size = _triple(pool_size)
        self.strides = _triple(strides) if strides is not None else self.pool_size
        self.padding = "SAME" if border_mode == "same" else "VALID"

    def compute_output_shape(self, input_shape):
        n, d, h, w, c = input_shape
        pd, ph, pw = self.pool_size
        sd, sh, sw = self.strides
        return (n, _conv_out(d, pd, sd, self.padding),
                _conv_out(h, ph, sh, self.padding),
                _conv_out(w, pw, sw, self.padding), c)

    def _reduce(self, inputs, init, op):
        pd, ph, pw = self.pool_size
        sd, sh, sw = self.strides
        return lax.reduce_window(inputs, init, op, (1, pd, ph, pw, 1),
                                 (1, sd, sh, sw, 1), self.padding)


class MaxPooling3D(_Pool3D):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return self._reduce(inputs, -jnp.inf, lax.max), state


class AveragePooling3D(_Pool3D):
    def call(self, params, state, inputs, *, training=False, rng=None):
        pd, ph, pw = self.pool_size
        return self._reduce(inputs, 0.0, lax.add) / (pd * ph * pw), state


class GlobalMaxPooling3D(Layer):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.max(inputs, axis=(1, 2, 3)), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], input_shape[4])


class GlobalAveragePooling3D(Layer):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.mean(inputs, axis=(1, 2, 3)), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], input_shape[4])


# -- cropping / padding / upsampling ----------------------------------------


class Cropping1D(Layer):
    def __init__(self, cropping=(1, 1), name: Optional[str] = None):
        super().__init__(name)
        self.crop = _pair(cropping)

    def call(self, params, state, inputs, *, training=False, rng=None):
        a, b = self.crop
        return inputs[:, a:inputs.shape[1] - b, :], state

    def compute_output_shape(self, input_shape):
        n, l, c = input_shape
        return (n, None if l is None else l - sum(self.crop), c)


class Cropping2D(Layer):
    def __init__(self, cropping=((0, 0), (0, 0)), name: Optional[str] = None):
        super().__init__(name)
        self.crop = tuple(_pair(c) for c in cropping)

    def call(self, params, state, inputs, *, training=False, rng=None):
        (t, b), (l, r) = self.crop
        return inputs[:, t:inputs.shape[1] - b, l:inputs.shape[2] - r, :], state

    def compute_output_shape(self, input_shape):
        n, h, w, c = input_shape
        (t, b), (l, r) = self.crop
        return (n, None if h is None else h - t - b,
                None if w is None else w - l - r, c)


class Cropping3D(Layer):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)),
                 name: Optional[str] = None):
        super().__init__(name)
        self.crop = tuple(_pair(c) for c in cropping)

    def call(self, params, state, inputs, *, training=False, rng=None):
        (a1, b1), (a2, b2), (a3, b3) = self.crop
        return inputs[:, a1:inputs.shape[1] - b1, a2:inputs.shape[2] - b2,
                      a3:inputs.shape[3] - b3, :], state

    def compute_output_shape(self, input_shape):
        n, d, h, w, c = input_shape
        (a1, b1), (a2, b2), (a3, b3) = self.crop
        return (n, None if d is None else d - a1 - b1,
                None if h is None else h - a2 - b2,
                None if w is None else w - a3 - b3, c)


class UpSampling1D(Layer):
    def __init__(self, length: int = 2, name: Optional[str] = None):
        super().__init__(name)
        self.length = length

    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.repeat(inputs, self.length, axis=1), state

    def compute_output_shape(self, input_shape):
        n, l, c = input_shape
        return (n, None if l is None else l * self.length, c)


class UpSampling2D(Layer):
    def __init__(self, size=(2, 2), name: Optional[str] = None):
        super().__init__(name)
        self.size = _pair(size)

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = jnp.repeat(inputs, self.size[0], axis=1)
        return jnp.repeat(y, self.size[1], axis=2), state

    def compute_output_shape(self, input_shape):
        n, h, w, c = input_shape
        return (n, None if h is None else h * self.size[0],
                None if w is None else w * self.size[1], c)


class UpSampling3D(Layer):
    def __init__(self, size=(2, 2, 2), name: Optional[str] = None):
        super().__init__(name)
        self.size = _triple(size)

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = jnp.repeat(inputs, self.size[0], axis=1)
        y = jnp.repeat(y, self.size[1], axis=2)
        return jnp.repeat(y, self.size[2], axis=3), state

    def compute_output_shape(self, input_shape):
        n, d, h, w, c = input_shape
        return (n, None if d is None else d * self.size[0],
                None if h is None else h * self.size[1],
                None if w is None else w * self.size[2], c)


class ZeroPadding1D(Layer):
    def __init__(self, padding=1, name: Optional[str] = None):
        super().__init__(name)
        self.pad = _pair(padding)

    def call(self, params, state, inputs, *, training=False, rng=None):
        a, b = self.pad
        return jnp.pad(inputs, ((0, 0), (a, b), (0, 0))), state

    def compute_output_shape(self, input_shape):
        n, l, c = input_shape
        return (n, None if l is None else l + sum(self.pad), c)


class ZeroPadding3D(Layer):
    def __init__(self, padding=(1, 1, 1), name: Optional[str] = None):
        super().__init__(name)
        self.pad = _triple(padding)

    def call(self, params, state, inputs, *, training=False, rng=None):
        pd, ph, pw = self.pad
        return jnp.pad(inputs, ((0, 0), (pd, pd), (ph, ph), (pw, pw),
                                (0, 0))), state

    def compute_output_shape(self, input_shape):
        n, d, h, w, c = input_shape
        pd, ph, pw = self.pad
        return (n, None if d is None else d + 2 * pd,
                None if h is None else h + 2 * ph,
                None if w is None else w + 2 * pw, c)


class ResizeBilinear(Layer):
    """Bilinear image resize (reference ``ResizeBilinear.scala``)."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.out_hw = (output_height, output_width)
        self.align_corners = align_corners

    def call(self, params, state, inputs, *, training=False, rng=None):
        b, _, _, c = inputs.shape
        method = "bilinear"
        y = jax.image.resize(inputs, (b, self.out_hw[0], self.out_hw[1], c),
                             method=method)
        return y, state

    def compute_output_shape(self, input_shape):
        n, _, _, c = input_shape
        return (n, self.out_hw[0], self.out_hw[1], c)


class LRN2D(Layer):
    """Local response normalization across channels (reference
    ``LRN2D.scala``), NHWC."""

    def __init__(self, alpha: float = 1e-4, k: float = 1.0, beta: float = 0.75,
                 n: int = 5, name: Optional[str] = None):
        super().__init__(name)
        self.alpha, self.k, self.beta, self.n = alpha, k, beta, n

    def call(self, params, state, inputs, *, training=False, rng=None):
        sq = inputs * inputs
        half = self.n // 2
        # channel-window sum via reduce_window over the last axis
        summed = lax.reduce_window(sq, 0.0, lax.add, (1, 1, 1, self.n),
                                   (1, 1, 1, 1),
                                   [(0, 0), (0, 0), (0, 0),
                                    (half, self.n - 1 - half)])
        denom = jnp.power(self.k + self.alpha / self.n * summed, self.beta)
        return inputs / denom, state


class WithinChannelLRN2D(Layer):
    """LRN over a spatial window within each channel (reference
    ``WithinChannelLRN2D.scala``)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size, self.alpha, self.beta = size, alpha, beta

    def call(self, params, state, inputs, *, training=False, rng=None):
        sq = inputs * inputs
        half = self.size // 2
        summed = lax.reduce_window(
            sq, 0.0, lax.add, (1, self.size, self.size, 1), (1, 1, 1, 1),
            [(0, 0), (half, self.size - 1 - half),
             (half, self.size - 1 - half), (0, 0)])
        denom = jnp.power(1.0 + self.alpha / (self.size ** 2) * summed,
                          self.beta)
        return inputs / denom, state
