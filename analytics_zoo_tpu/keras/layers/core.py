"""Core layers (reference: ``pipeline/api/keras/layers/{Dense,Dropout,Flatten,
Reshape,Permute,RepeatVector,Merge,...}.scala`` and python mirror
``pyzoo/zoo/pipeline/api/keras/layers/core.py``)."""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import initializers
from ..engine import Layer, Shape

# -- activations -------------------------------------------------------------

_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "softmax": jax.nn.softmax,
    "log_softmax": jax.nn.log_softmax,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.swish,
    "silu": jax.nn.silu,
    "linear": lambda x: x,
    None: lambda x: x,
}


def get_activation(act: Union[str, Callable, None]) -> Callable:
    if callable(act):
        return act
    if act not in _ACTIVATIONS:
        raise ValueError(f"unknown activation '{act}'")
    return _ACTIVATIONS[act]


class Activation(Layer):
    def __init__(self, activation, name: Optional[str] = None):
        super().__init__(name)
        self.fn = get_activation(activation)

    def call(self, params, state, inputs, *, training=False, rng=None):
        return self.fn(inputs), state


class Dense(Layer):
    """Fully connected layer (reference ``Dense.scala``). bf16-friendly: the
    matmul runs in the input dtype so the MXU sees bfloat16 when the pipeline
    casts activations."""

    def __init__(self, output_dim: int, activation=None, init="glorot_uniform",
                 bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.output_dim = output_dim
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias

    def build(self, rng, input_shape):
        in_dim = input_shape[-1]
        k1, _ = jax.random.split(rng)
        params = {"kernel": self.init(k1, (in_dim, self.output_dim))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.output_dim,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        kernel = params["kernel"]
        if isinstance(kernel, dict) and "q" in kernel:
            # int8-quantized kernel (inference/quantize.py): static path
            from ...inference.quantize import qdense_apply
            y = qdense_apply(inputs, kernel)
        else:
            y = inputs @ kernel.astype(inputs.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class Dropout(Layer):
    def __init__(self, p: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = p

    def call(self, params, state, inputs, *, training=False, rng=None):
        if not training or self.rate <= 0.0:
            return inputs, state
        if rng is None:
            raise ValueError(f"{self.name}: dropout in training mode needs rng")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, inputs.shape)
        return jnp.where(mask, inputs / keep, 0.0).astype(inputs.dtype), state


class Flatten(Layer):
    def call(self, params, state, inputs, *, training=False, rng=None):
        return inputs.reshape(inputs.shape[0], -1), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], int(np.prod(input_shape[1:])))


class Reshape(Layer):
    def __init__(self, target_shape: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.target_shape = tuple(target_shape)

    def call(self, params, state, inputs, *, training=False, rng=None):
        return inputs.reshape((inputs.shape[0],) + self.target_shape), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self.target_shape


class Permute(Layer):
    def __init__(self, dims: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.dims = tuple(dims)  # 1-based over non-batch axes (Keras convention)

    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.transpose(inputs, (0,) + self.dims), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + tuple(input_shape[d] for d in self.dims)


class RepeatVector(Layer):
    def __init__(self, n: int, name: Optional[str] = None):
        super().__init__(name)
        self.n = n

    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.repeat(inputs[:, None, :], self.n, axis=1), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.n, input_shape[1])


class Lambda(Layer):
    """Arbitrary jax function as a layer (reference autograd ``Lambda.scala:95``)."""

    def __init__(self, fn: Callable, output_shape_fn: Optional[Callable] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.fn = fn
        self.output_shape_fn = output_shape_fn

    def call(self, params, state, inputs, *, training=False, rng=None):
        return self.fn(inputs), state

    def compute_output_shape(self, input_shape):
        if self.output_shape_fn is not None:
            return self.output_shape_fn(input_shape)
        # infer via abstract evaluation on the non-batch shape
        def dummy(shape):
            return jnp.zeros(tuple(1 if d is None else d for d in shape))
        if isinstance(input_shape, list):
            args = [dummy(s) for s in input_shape]
            out = jax.eval_shape(self.fn, args)
        else:
            out = jax.eval_shape(self.fn, dummy(input_shape))
        return (None,) + out.shape[1:]


class ElementwiseOp(Layer):
    """Elementwise binary/scalar op layer backing SymbolicTensor operators."""

    def __init__(self, fn: Callable, symbol: str, scalar=None, binary=False,
                 name: Optional[str] = None):
        # auto-named: two `x * y` ops must get DISTINCT names (an id(fn)-based
        # scheme collides for every use of the same ufunc); these layers are
        # parameter-free so positional renaming costs nothing
        super().__init__(name)
        self.symbol = symbol
        self.fn = fn
        self.scalar = scalar
        self.binary = binary

    @classmethod
    def binary(cls, fn, symbol):
        return cls(fn, symbol, binary=True)

    @classmethod
    def with_scalar(cls, fn, symbol, scalar):
        return cls(fn, symbol, scalar=scalar)

    def call(self, params, state, inputs, *, training=False, rng=None):
        if self.binary:
            a, b = inputs
            return self.fn(a, b), state
        return self.fn(inputs, self.scalar), state

    def compute_output_shape(self, input_shape):
        if self.binary:
            return input_shape[0]
        return input_shape


class Merge(Layer):
    """Merge a list of inputs (reference ``Merge.scala``): sum/mul/max/ave/
    concat/dot/cosine."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1,
                 name: Optional[str] = None):
        super().__init__(name)
        if mode not in ("sum", "mul", "max", "ave", "min", "concat", "dot", "cosine"):
            raise ValueError(f"unknown merge mode '{mode}'")
        self.mode = mode
        self.concat_axis = concat_axis

    def call(self, params, state, inputs, *, training=False, rng=None):
        xs = list(inputs)
        if self.mode == "sum":
            out = sum(xs[1:], xs[0])
        elif self.mode == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
        elif self.mode == "max":
            out = jnp.stack(xs).max(axis=0)
        elif self.mode == "min":
            out = jnp.stack(xs).min(axis=0)
        elif self.mode == "ave":
            out = jnp.stack(xs).mean(axis=0)
        elif self.mode == "concat":
            out = jnp.concatenate(xs, axis=self.concat_axis)
        elif self.mode == "dot":
            out = jnp.sum(xs[0] * xs[1], axis=-1, keepdims=True)
        else:  # cosine
            a, b = xs[0], xs[1]
            na = a / (jnp.linalg.norm(a, axis=-1, keepdims=True) + 1e-8)
            nb = b / (jnp.linalg.norm(b, axis=-1, keepdims=True) + 1e-8)
            out = jnp.sum(na * nb, axis=-1, keepdims=True)
        return out, state

    def compute_output_shape(self, input_shape):
        shapes = input_shape
        if self.mode in ("dot", "cosine"):
            return (shapes[0][0], 1)
        if self.mode == "concat":
            ax = self.concat_axis
            out = list(shapes[0])
            dims = [s[ax] for s in shapes]
            out[ax] = None if any(d is None for d in dims) else sum(dims)
            return tuple(out)
        return shapes[0]


def merge(inputs, mode="sum", concat_axis=-1, name=None):
    return Merge(mode, concat_axis, name)(inputs)


class Select(Layer):
    """Select index along a dim (reference ``Select.scala``)."""

    def __init__(self, dim: int, index: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim
        self.index = index

    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.take(inputs, self.index, axis=self.dim), state

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        del s[self.dim]
        return tuple(s)


class Squeeze(Layer):
    def __init__(self, dim: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.squeeze(inputs, axis=self.dim), state

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        del s[self.dim]
        return tuple(s)
