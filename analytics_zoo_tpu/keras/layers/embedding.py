"""Embedding layers (reference ``Embedding.scala``/``SparseEmbedding``/
``WordEmbedding.scala``).

TPU note (SURVEY.md §7 hard part (b)): the reference densifies sparse embedding
grads through BigDL's allreduce; here gradients of ``jnp.take`` are naturally
scatter-adds that XLA executes on-device, and under pure DP the psum of the
dense grad table is the allreduce-stress case benchmarked by Wide&Deep. For
giant tables, shard the vocab axis over the model axis via
``parallel.mesh.param_sharding`` rules.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import initializers
from ..engine import Layer


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 input_length: Optional[int] = None, name: Optional[str] = None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.init = initializers.get(init)
        self.input_length = input_length

    def build(self, rng, input_shape):
        return {"embeddings": self.init(rng, (self.input_dim, self.output_dim))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        idx = inputs.astype(jnp.int32)
        return jnp.take(params["embeddings"], idx, axis=0), state

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class WordEmbedding(Layer):
    """Frozen pretrained word vectors (reference ``WordEmbedding.scala``):
    the table lives in state (non-trainable), not params."""

    def __init__(self, weights: np.ndarray, trainable: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.weights = jnp.asarray(weights)
        self.trainable = trainable

    def build(self, rng, input_shape):
        if self.trainable:
            return {"embeddings": self.weights}, {}
        return {}, {"embeddings": self.weights}

    def call(self, params, state, inputs, *, training=False, rng=None):
        table = params.get("embeddings", state.get("embeddings"))
        return jnp.take(table, inputs.astype(jnp.int32), axis=0), state

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.weights.shape[1],)
