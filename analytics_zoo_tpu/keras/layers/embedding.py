"""Embedding layers (reference ``Embedding.scala``/``SparseEmbedding``/
``WordEmbedding.scala``).

TPU note (SURVEY.md §7 hard part (b)): the reference densifies sparse embedding
grads through BigDL's allreduce; here gradients of ``jnp.take`` are naturally
scatter-adds that XLA executes on-device, and under pure DP the psum of the
dense grad table is the allreduce-stress case benchmarked by Wide&Deep. For
giant tables, shard the vocab axis over the model axis via
``parallel.mesh.param_sharding`` rules.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import initializers
from ..engine import Layer


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 input_length: Optional[int] = None,
                 weights: Optional[np.ndarray] = None, trainable: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.init = initializers.get(init)
        self.input_length = input_length
        self.weights = weights
        self.trainable = trainable

    def build(self, rng, input_shape):
        if self.weights is not None:
            table = jnp.asarray(self.weights, jnp.float32)
            if table.shape != (self.input_dim, self.output_dim):
                raise ValueError(
                    f"pretrained weights {table.shape} != "
                    f"({self.input_dim}, {self.output_dim})")
        else:
            table = self.init(rng, (self.input_dim, self.output_dim))
        if self.trainable:
            return {"embeddings": table}, {}
        return {}, {"embeddings": table}  # frozen: state, not params

    def call(self, params, state, inputs, *, training=False, rng=None):
        idx = inputs.astype(jnp.int32)
        table = params["embeddings"] if self.trainable else state["embeddings"]
        return jnp.take(table, idx, axis=0), state

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class WordEmbedding(Embedding):
    """Pretrained word vectors, frozen by default (reference
    ``WordEmbedding.scala``) — an ``Embedding`` constructed from a table."""

    def __init__(self, weights: np.ndarray, trainable: bool = False,
                 name: Optional[str] = None):
        weights = np.asarray(weights)
        super().__init__(weights.shape[0], weights.shape[1],
                         weights=weights, trainable=trainable, name=name)
