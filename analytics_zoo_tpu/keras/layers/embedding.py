"""Embedding layers (reference ``Embedding.scala``/``SparseEmbedding``/
``WordEmbedding.scala``).

TPU note (SURVEY.md §7 hard part (b)): the reference densifies sparse embedding
grads through BigDL's allreduce; here gradients of ``jnp.take`` are naturally
scatter-adds that XLA executes on-device, and under pure DP the psum of the
dense grad table is the allreduce-stress case benchmarked by Wide&Deep. For
giant tables, pass ``shard=True``: the vocab axis shards over the mesh via
the sparse engine in ``parallel/embedding.py`` (dedup-unique -> all-to-all
exchange -> local gather, segment-sum backward into only the touched shard
rows), with an optional host-DRAM ``cold_rows`` tail for vocabularies that
do not fit HBM even sharded. See docs/embeddings.md.
"""
from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import initializers
from ..engine import Layer
from ...common import file_io
from ...parallel import embedding as _embed


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 input_length: Optional[int] = None,
                 weights: Optional[np.ndarray] = None, trainable: bool = True,
                 name: Optional[str] = None,
                 shard: Union[bool, str, None] = None, cold_rows: int = 0,
                 fused: Optional[bool] = None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.init = initializers.get(init)
        self.input_length = input_length
        self.weights = weights
        self.trainable = trainable
        #: per-layer override of the ``kernels.fused_embedding`` knob:
        #: None follows the config, False pins this layer to the unfused
        #: bit-parity reference path, True forces the fused kernels on.
        self.fused = fused
        #: False/None = replicated table (historical layout); True = shard
        #: the vocab axis over the default embedding mesh axis; a string
        #: names the mesh axis explicitly.
        self.shard = shard
        #: last ``cold_rows`` logical rows live in a host-DRAM shared-
        #: memory slab instead of HBM (parallel.embedding.HostColdTier).
        self.cold_rows = int(cold_rows)
        if self.cold_rows < 0 or self.cold_rows >= input_dim:
            raise ValueError(f"cold_rows={cold_rows} must be in "
                             f"[0, input_dim={input_dim})")
        self._shard_spec = None
        self._cold_tier = None

    @property
    def hot_dim(self) -> int:
        """Rows resident on device (input_dim minus the cold tail)."""
        return self.input_dim - self.cold_rows

    def _fused_kernels(self):
        """Fused-kernel module for this layer's lookups (or None for the
        unfused reference ops): the per-layer ``fused`` override wins,
        else the global ``kernels.fused_embedding`` knob decides."""
        if self.fused is False:
            return None
        ek = _embed.fused_kernels()
        if ek is None and self.fused:
            from ...ops import embedding_kernels as ek  # forced on
        return ek

    def _make_spec(self):
        if not self.shard:
            return None
        axis = self.shard if isinstance(self.shard, str) else None
        return _embed.make_shard_spec(self.hot_dim, self.output_dim,
                                      axis=axis)

    def sharded_tables(self):
        """``{param_key: ShardSpec}`` for the estimator's sparse-update
        plan and GSPMD vocab-sharding rules. Deterministic pre-build (a
        restored checkpoint must init optimizer state before the first
        trace builds the layer)."""
        if not self.trainable:
            return {}
        spec = self._shard_spec or self._make_spec()
        return {"embeddings": spec} if spec is not None else {}

    def build(self, rng, input_shape):
        if self.weights is not None:
            table = jnp.asarray(self.weights, jnp.float32)
            if table.shape != (self.input_dim, self.output_dim):
                raise ValueError(
                    f"pretrained weights {table.shape} != "
                    f"({self.input_dim}, {self.output_dim})")
        else:
            table = self.init(rng, (self.input_dim, self.output_dim))
        if self.cold_rows:
            cold_vals = table[self.hot_dim:]
            table = table[:self.hot_dim]
            if self._cold_tier is None:
                self._cold_tier = _embed.HostColdTier(
                    self.cold_rows, self.output_dim, name=self.name)
            if not isinstance(cold_vals, jax.core.Tracer):
                # abstract (jitted) builds cannot fill the slab; it stays
                # zero until fill()/load() runs with concrete values
                self._cold_tier.fill(np.asarray(cold_vals))
        self._shard_spec = spec = self._make_spec()
        if spec is not None:
            pad = spec.padded - table.shape[0]
            if pad:
                table = jnp.concatenate(
                    [table, jnp.zeros((pad, self.output_dim), table.dtype)])
            _embed.note_table_bytes(self.name, spec.table_bytes)
        if self.trainable:
            return {"embeddings": table}, {}
        return {}, {"embeddings": table}  # frozen: state, not params

    def _lookup(self, table, idx, state):
        """Validated lookup through the sharded engine (with dense and
        cold-tier fallthroughs); returns ``(rows, new_state)`` with the
        exchange blob stashed for the estimator's sparse update."""
        idx = _embed.validate_ids(idx, self.input_dim)
        spec, tier = self._shard_spec, self._cold_tier
        ek = self._fused_kernels()
        if spec is None and tier is None:
            if ek is not None:
                return ek.gather_rows_clip(table, idx), state
            return jnp.take(table, idx, axis=0), state
        flat = idx.reshape(-1)
        is_cold = (flat >= self.hot_dim) if tier is not None else None
        new_state = state
        if spec is not None and _embed.can_run(spec, flat.shape[0]):
            dev_ids = flat if is_cold is None \
                else jnp.where(is_cold, spec.padded, flat)
            out_flat, rows = _embed.sharded_lookup(table, dev_ids, spec)
            new_state = dict(state)
            new_state[_embed.ROWS_PREFIX + "embeddings"] = rows
        else:
            safe = flat if is_cold is None \
                else jnp.minimum(flat, self.hot_dim - 1)
            out_flat = ek.gather_rows_clip(table, safe) if ek is not None \
                else jnp.take(table, safe, axis=0)
        if is_cold is not None:
            rel = jnp.where(is_cold, flat - self.hot_dim, -1)
            cold = _embed.cold_lookup(tier, rel, table[0, 0])
            out_flat = jnp.where(is_cold[:, None],
                                 cold.astype(out_flat.dtype), out_flat)
        return out_flat.reshape(idx.shape + (self.output_dim,)), new_state

    def call(self, params, state, inputs, *, training=False, rng=None):
        idx = inputs.astype(jnp.int32)
        table = params["embeddings"] if self.trainable else state["embeddings"]
        return self._lookup(table, idx, state)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class WordEmbedding(Embedding):
    """Pretrained word vectors, frozen by default (reference
    ``WordEmbedding.scala``) — an ``Embedding`` constructed from a table."""

    def __init__(self, weights: np.ndarray, trainable: bool = False,
                 name: Optional[str] = None):
        weights = np.asarray(weights)
        super().__init__(weights.shape[0], weights.shape[1],
                         weights=weights, trainable=trainable, name=name)

    @staticmethod
    def read_glove(path: str, word_index: Optional[dict] = None):
        """Parse a GloVe-format text file (``word v1 v2 ...`` per line;
        reference ``WordEmbedding.getWordEmbedding``).

        With ``word_index`` (word → 1-based id, the TextSet convention, 0 =
        padding), returns a ``[len(index)+1, dim]`` table holding only the
        indexed words (missing words stay zero). Without it, returns
        ``(table, word_index)`` over the whole file.
        """
        vectors = {}
        dim = None
        with file_io.fopen(path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip().split(" ")
                if len(parts) < 3:
                    continue
                try:
                    # glove.840B-style files contain multi-token "words"
                    # (e.g. ". . ."): once dim is known, take the LAST dim
                    # fields as the vector and the rest as the word
                    if dim is not None and len(parts) != dim + 1:
                        vec = np.asarray(parts[-dim:], dtype=np.float32)
                        word = " ".join(parts[:-dim])
                    else:
                        vec = np.asarray(parts[1:], dtype=np.float32)
                        word = parts[0]
                except ValueError:
                    continue  # unparseable line — skip, don't abort the file
                if dim is None:
                    dim = len(vec)
                elif len(vec) != dim:
                    continue
                vectors[word] = vec
        if dim is None:
            raise ValueError(f"no embeddings parsed from {path}")
        if word_index is not None:
            table = np.zeros((max(word_index.values()) + 1, dim), np.float32)
            for word, idx in word_index.items():
                if word in vectors:
                    table[idx] = vectors[word]
            return table
        word_index = {w: i + 1 for i, w in enumerate(vectors)}
        table = np.zeros((len(vectors) + 1, dim), np.float32)
        for w, i in word_index.items():
            table[i] = vectors[w]
        return table, word_index

    @classmethod
    def from_glove(cls, path: str, word_index: Optional[dict] = None,
                   trainable: bool = False, name: Optional[str] = None):
        """Build the layer straight from a GloVe file (+ optional TextSet
        word index)."""
        if word_index is not None:
            table = cls.read_glove(path, word_index)
        else:
            table, _ = cls.read_glove(path)
        return cls(table, trainable=trainable, name=name)


class SparseEmbedding(Embedding):
    """Embedding over sparse one-hot-style inputs (reference
    ``SparseEmbedding.scala``).

    The reference takes a BigDL SparseTensor; the TPU-native contract is
    integer index arrays (the COO indices), identical to ``Embedding`` —
    gradients are scatter-adds, never a densified [vocab, dim] one-hot
    matmul, so Criteo-scale vocabularies stay HBM-friendly. Supports
    ``combiner`` pooling over a trailing "bag" axis for multi-hot fields.
    """

    def __init__(self, input_dim: int, output_dim: int, combiner: str = "sum",
                 init="uniform", weights=None, trainable: bool = True,
                 name: Optional[str] = None,
                 shard: Union[bool, str, None] = None,
                 fused: Optional[bool] = None):
        super().__init__(input_dim, output_dim, init=init, weights=weights,
                         trainable=trainable, name=name, shard=shard,
                         fused=fused)
        if combiner not in ("sum", "mean", "sqrtn", None):
            raise ValueError(f"unknown combiner {combiner}")
        self.combiner = combiner

    def call(self, params, state, inputs, *, training=False, rng=None):
        # inputs: [..., bag] int indices; negative ids mean padding
        idx = inputs.astype(jnp.int32)
        table = params["embeddings"] if self.trainable else state["embeddings"]
        idx = _embed.validate_ids(idx, self.input_dim, allow_negative=True)
        valid = (idx >= 0).astype(table.dtype)[..., None]
        spec = self._shard_spec
        new_state = state
        flat = idx.reshape(-1)
        if spec is not None and _embed.can_run(spec, flat.shape[0]):
            # padding ids route to the SENTINEL (zero rows, no grad) —
            # the valid-mask multiply keeps the combiner math unchanged
            dev_ids = jnp.where(flat < 0, spec.padded, flat)
            emb_flat, rows = _embed.sharded_lookup(table, dev_ids, spec)
            new_state = dict(state)
            new_state[_embed.ROWS_PREFIX + "embeddings"] = rows
            emb = emb_flat.reshape(idx.shape + (self.output_dim,)) * valid
        else:
            ek = self._fused_kernels()
            if ek is not None:
                # fused gather + mask + pool in one pass (pallas on TPU;
                # the identical op chain off-TPU — bit-parity reference)
                return ek.gather_pool(table, idx, self.combiner), new_state
            emb = jnp.take(table, jnp.maximum(idx, 0), axis=0) * valid
        if self.combiner is None:
            return emb, new_state
        total = jnp.sum(emb, axis=-2)
        if self.combiner == "sum":
            return total, new_state
        n = jnp.maximum(jnp.sum(valid, axis=-2), 1.0)
        if self.combiner == "mean":
            return total / n, new_state
        return total / jnp.sqrt(n), new_state  # sqrtn

    def compute_output_shape(self, input_shape):
        if self.combiner is None:
            return tuple(input_shape) + (self.output_dim,)
        return tuple(input_shape[:-1]) + (self.output_dim,)


class SparseDense(Layer):
    """Dense layer applied to sparse (index, value) inputs (reference
    ``SparseDense.scala``).

    TPU-native contract: inputs are (indices [..., nnz], values [..., nnz])
    over a logical feature dim; computes sum_j v_j * W[i_j] + b by gathering
    kernel rows — one gather + batched matmul instead of a [B, vocab]
    densification.
    """

    def __init__(self, output_dim: int, activation=None,
                 init="glorot_uniform", bias: bool = True,
                 input_dim: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        from .core import get_activation
        self.output_dim = output_dim
        self.activation = get_activation(activation)
        self.init = initializers.get(init)
        self.use_bias = bias
        self.input_dim = input_dim

    def build(self, rng, input_shape):
        if isinstance(input_shape, list):  # (indices, values) pair
            in_dim = self.input_dim
            if in_dim is None:
                raise ValueError("SparseDense with (indices, values) input "
                                 "needs input_dim")
        else:
            in_dim = self.input_dim or input_shape[-1]
        params = {"kernel": self.init(rng, (in_dim, self.output_dim))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.output_dim,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        kernel = params["kernel"]
        if isinstance(inputs, (list, tuple)):
            idx, vals = inputs
            idx = idx.astype(jnp.int32)
            # ids beyond the kernel used to clamp silently to the last
            # row; the data.validate_ids policy now counts or raises
            # (negatives stay legal padding, masked below)
            idx = _embed.validate_ids(idx, kernel.shape[0],
                                      allow_negative=True)
            rows = jnp.take(kernel, jnp.maximum(idx, 0), axis=0)
            rows = rows * (idx >= 0).astype(rows.dtype)[..., None]
            y = jnp.einsum("...n,...nd->...d", vals.astype(rows.dtype), rows)
        else:  # dense fallback
            y = inputs @ kernel.astype(inputs.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def compute_output_shape(self, input_shape):
        if isinstance(input_shape, list):
            return tuple(input_shape[0][:-1]) + (self.output_dim,)
        return tuple(input_shape[:-1]) + (self.output_dim,)
