"""Linear-chain CRF head (reference ``ner.py``'s nlp-architect NERCRF —
the Bi-LSTM+CRF sequence classifier — natively re-designed).

TPU design: the layer lowers emissions ``[B, S, T]`` into per-step
transition log-potentials ``[B, S, T, T]``:

    potentials[b, s, i, j] = emissions[b, s, j] + transitions[i, j]   (s > 0)
    potentials[b, 0, i, j] = emissions[b, 0, j] + start[j]            (all i)

Everything downstream — the negative-log-likelihood (:func:`crf_nll`, the
forward algorithm) and Viterbi decode (:func:`crf_decode`) — is a pure
function of the potentials tensor, so the training loss fits the engine's
``loss(y_true, y_pred)`` contract without reaching into layer parameters,
and both run as single ``lax.scan`` loops over the sequence axis (compiler-
friendly: no data-dependent Python control flow, static shapes). ``T`` is a
tag set (tens), so the T× blow-up over raw emissions is noise next to the
LSTM states feeding it.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from .core import Layer, initializers


class CRF(Layer):
    """Turns emission scores ``[B, S, T]`` into linear-chain log-potentials
    ``[B, S, T, T]`` with learned transition/start scores. Feed a LINEAR
    (no softmax) Dense of width ``num_tags`` into this layer; train with
    :func:`crf_nll`, decode with :func:`crf_decode`."""

    def __init__(self, num_tags: int, init="glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        self.num_tags = num_tags
        self.init = initializers.get(init)

    def build(self, rng, input_shape):
        if input_shape[-1] != self.num_tags:
            raise ValueError(
                f"CRF expects emissions with last dim {self.num_tags}, "
                f"got {input_shape[-1]}")
        k1, k2 = jax.random.split(rng)
        t = self.num_tags
        return {"transitions": self.init(k1, (t, t)),
                "start": self.init(k2, (t,))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        emis = inputs  # [B, S, T]
        pot = emis[:, :, None, :] + params["transitions"][None, None]
        first = emis[:, 0, None, :] + params["start"][None, None, :]
        pot = pot.at[:, 0].set(jnp.broadcast_to(
            first, (emis.shape[0], self.num_tags, self.num_tags)))
        return pot, state

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.num_tags, self.num_tags)


def _seq_mask(y_true: jnp.ndarray, pad_tag: Any) -> jnp.ndarray:
    if pad_tag is None:
        return jnp.ones(y_true.shape, jnp.float32)
    return (y_true.astype(jnp.int32) != pad_tag).astype(jnp.float32)


def crf_nll(pad_tag: Any = None):
    """Negative log-likelihood loss over CRF potentials.

    ``y_true``: tags ``[B, S]`` (``pad_tag`` at suffix pad positions);
    ``y_pred``: potentials ``[B, S, T, T]`` from the :class:`CRF` layer.
    Masked positions contribute neither emission nor transition score and
    are frozen out of the forward recursion.
    """

    def loss_fn(y_true, y_pred):
        pot = y_pred
        idx = jnp.clip(y_true.astype(jnp.int32), 0, None)
        mask = _seq_mask(y_true, pad_tag)  # [B, S]

        # log-partition: forward algorithm over the sequence axis
        alpha = pot[:, 0, 0, :]  # [B, T] (row i is constant at s=0)

        def fwd(alpha, inp):
            pot_s, m = inp  # [B, T, T], [B]
            new = jax.nn.logsumexp(alpha[:, :, None] + pot_s, axis=1)
            alpha = jnp.where(m[:, None] > 0, new, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(
            fwd, alpha,
            (jnp.swapaxes(pot[:, 1:], 0, 1), jnp.swapaxes(mask[:, 1:], 0, 1)))
        log_z = jax.nn.logsumexp(alpha, axis=-1)  # [B]

        # gold-path score
        first = jnp.take_along_axis(pot[:, 0, 0, :], idx[:, :1],
                                    axis=1)[:, 0]  # [B]
        prev, nxt = idx[:, :-1], idx[:, 1:]
        from_prev = jnp.take_along_axis(
            pot[:, 1:], prev[:, :, None, None], axis=2)[:, :, 0]  # [B,S-1,T]
        steps = jnp.take_along_axis(
            from_prev, nxt[:, :, None], axis=2)[:, :, 0]  # [B, S-1]
        score = first + jnp.sum(steps * mask[:, 1:], axis=1)
        return jnp.mean(log_z - score)

    return loss_fn


def crf_decode(potentials, pad_tag: Any = None,
               y_like: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Viterbi decode: best tag path ``[B, S]`` from potentials
    ``[B, S, T, T]``. With ``pad_tag`` + ``y_like`` (the padded tag array),
    masked positions are emitted as ``pad_tag``."""
    pot = jnp.asarray(potentials)
    B, S, T, _ = pot.shape
    mask = (jnp.ones((B, S), jnp.float32) if y_like is None or pad_tag is None
            else _seq_mask(y_like, pad_tag))

    delta = pot[:, 0, 0, :]  # [B, T]

    def fwd(delta, inp):
        pot_s, m = inp
        scores = delta[:, :, None] + pot_s  # [B, T, T]
        best_prev = jnp.argmax(scores, axis=1)  # [B, T]
        new = jnp.max(scores, axis=1)
        keep = m[:, None] > 0
        return (jnp.where(keep, new, delta),
                jnp.where(keep, best_prev,
                          jnp.broadcast_to(jnp.arange(T)[None], (B, T))))

    delta, backptrs = jax.lax.scan(
        fwd, delta,
        (jnp.swapaxes(pot[:, 1:], 0, 1), jnp.swapaxes(mask[:, 1:], 0, 1)))
    last = jnp.argmax(delta, axis=-1)  # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first, path = jax.lax.scan(back, last, backptrs, reverse=True)
    # path[k] = tag at position k+1 (scan stacks in original order even when
    # reversed); the final carry is the tag at position 0
    tags = jnp.concatenate([first[:, None], jnp.swapaxes(path, 0, 1)], axis=1)
    if pad_tag is not None and y_like is not None:
        tags = jnp.where(mask > 0, tags, pad_tag)
    return tags
