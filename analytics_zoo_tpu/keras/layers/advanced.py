"""Advanced activations, noise, and tensor-manipulation layers.

Reference: ``zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/keras/
layers/{ELU,LeakyReLU,PReLU,SReLU,RReLU,ThresholdedReLU,Threshold,
BinaryThreshold,HardTanh,HardShrink,SoftShrink,Softmax,GaussianDropout,
GaussianNoise,GaussianSampler,SpatialDropout1D,SpatialDropout2D,
SpatialDropout3D,Masking,Highway,MaxoutDense,TimeDistributed,SelectTable,
SplitTensor,Narrow,Expand,ExpandDim,AddConstant,MulConstant,CAdd,CMul,Mul,
Scale,Exp,Log,Sqrt,Square,Power,Negative,Identity,Max}.scala``.

All layers are pure elementwise/reshape ops XLA fuses into adjacent matmuls;
stochastic layers draw from the per-call ``rng`` so they stay functional.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..engine import Layer


# -- parametric / fixed activations ------------------------------------------


class _UnaryOp(Layer):
    """Base for stateless unary elementwise layers."""

    def _fn(self, x):
        raise NotImplementedError

    def call(self, params, state, inputs, *, training=False, rng=None):
        return self._fn(inputs), state


class ELU(_UnaryOp):
    def __init__(self, alpha: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.alpha = alpha

    def _fn(self, x):
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(x))


class LeakyReLU(_UnaryOp):
    def __init__(self, alpha: float = 0.01, name: Optional[str] = None):
        super().__init__(name)
        self.alpha = alpha

    def _fn(self, x):
        return jnp.where(x > 0, x, self.alpha * x)


class ThresholdedReLU(_UnaryOp):
    def __init__(self, theta: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.theta = theta

    def _fn(self, x):
        return jnp.where(x > self.theta, x, 0.0)


class Threshold(_UnaryOp):
    def __init__(self, th: float = 1e-6, v: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.th, self.v = th, v

    def _fn(self, x):
        return jnp.where(x > self.th, x, self.v)


class BinaryThreshold(_UnaryOp):
    def __init__(self, value: float = 1e-6, name: Optional[str] = None):
        super().__init__(name)
        self.value = value

    def _fn(self, x):
        return (x > self.value).astype(x.dtype)


class HardTanh(_UnaryOp):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.min_value, self.max_value = min_value, max_value

    def _fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class HardShrink(_UnaryOp):
    def __init__(self, value: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.value = value

    def _fn(self, x):
        return jnp.where(jnp.abs(x) > self.value, x, 0.0)


class SoftShrink(_UnaryOp):
    def __init__(self, value: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.value = value

    def _fn(self, x):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.value, 0.0)


class Softmax(_UnaryOp):
    def _fn(self, x):
        return jax.nn.softmax(x, axis=-1)


class Exp(_UnaryOp):
    def _fn(self, x):
        return jnp.exp(x)


class Log(_UnaryOp):
    def _fn(self, x):
        return jnp.log(x)


class Sqrt(_UnaryOp):
    def _fn(self, x):
        return jnp.sqrt(x)


class Square(_UnaryOp):
    def _fn(self, x):
        return x * x


class Negative(_UnaryOp):
    def _fn(self, x):
        return -x


class Identity(_UnaryOp):
    def _fn(self, x):
        return x


class Power(_UnaryOp):
    """(shift + scale * x) ** power (reference ``Power.scala``)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.power, self.scale, self.shift = power, scale, shift

    def _fn(self, x):
        return jnp.power(self.shift + self.scale * x, self.power)


class AddConstant(_UnaryOp):
    def __init__(self, constant: float, name: Optional[str] = None):
        super().__init__(name)
        self.constant = constant

    def _fn(self, x):
        return x + self.constant


class MulConstant(_UnaryOp):
    def __init__(self, constant: float, name: Optional[str] = None):
        super().__init__(name)
        self.constant = constant

    def _fn(self, x):
        return x * self.constant


class PReLU(Layer):
    """Learned per-channel leak (reference ``PReLU.scala``)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(name)

    def build(self, rng, input_shape):
        return {"alpha": jnp.full((input_shape[-1],), 0.25)}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        a = params["alpha"].astype(inputs.dtype)
        return jnp.where(inputs > 0, inputs, a * inputs), state


class SReLU(Layer):
    """S-shaped ReLU with four learned per-channel params
    (reference ``SReLU.scala``)."""

    def build(self, rng, input_shape):
        c = input_shape[-1]
        return {"t_left": jnp.zeros((c,)), "a_left": jnp.full((c,), 0.2),
                "t_right": jnp.ones((c,)), "a_right": jnp.ones((c,))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        tl = params["t_left"].astype(inputs.dtype)
        al = params["a_left"].astype(inputs.dtype)
        tr = params["t_right"].astype(inputs.dtype)
        ar = params["a_right"].astype(inputs.dtype)
        y = jnp.where(inputs < tl, tl + al * (inputs - tl), inputs)
        return jnp.where(inputs > tr, tr + ar * (inputs - tr), y), state


class RReLU(Layer):
    """Randomized leaky ReLU: leak ~ U(lower, upper) in training, fixed mean
    at inference (reference ``RReLU.scala``)."""

    def __init__(self, lower: float = 1 / 8., upper: float = 1 / 3.,
                 name: Optional[str] = None):
        super().__init__(name)
        self.lower, self.upper = lower, upper

    def call(self, params, state, inputs, *, training=False, rng=None):
        if training and rng is not None:
            a = jax.random.uniform(rng, inputs.shape, inputs.dtype,
                                   self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2
        return jnp.where(inputs >= 0, inputs, a * inputs), state


# -- stochastic regularisers --------------------------------------------------


class GaussianDropout(Layer):
    def __init__(self, p: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = p

    def call(self, params, state, inputs, *, training=False, rng=None):
        if not training or rng is None or self.rate <= 0:
            return inputs, state
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + stddev * jax.random.normal(rng, inputs.shape, inputs.dtype)
        return inputs * noise, state


class GaussianNoise(Layer):
    def __init__(self, sigma: float, name: Optional[str] = None):
        super().__init__(name)
        self.sigma = sigma

    def call(self, params, state, inputs, *, training=False, rng=None):
        if not training or rng is None:
            return inputs, state
        return inputs + self.sigma * jax.random.normal(
            rng, inputs.shape, inputs.dtype), state


class GaussianSampler(Layer):
    """Samples from N(mean, exp(log_var/2)) given [mean, log_var]
    (reference ``GaussianSampler.scala``, the VAE reparam trick)."""

    def call(self, params, state, inputs, *, training=False, rng=None):
        mean, log_var = inputs
        if rng is None:
            raise ValueError(
                "GaussianSampler needs an rng (pass rng= to call/fit); "
                "a fixed seed would make every 'sample' identical")
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(log_var / 2) * eps, state

    def compute_output_shape(self, input_shape):
        return input_shape[0]


class _SpatialDropout(Layer):
    """Drops whole feature maps (reference ``SpatialDropout{1,2,3}D.scala``)."""

    _spatial_axes: Tuple[int, ...] = ()

    def __init__(self, p: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.rate = p

    def call(self, params, state, inputs, *, training=False, rng=None):
        if not training or rng is None or self.rate <= 0:
            return inputs, state
        shape = list(inputs.shape)
        for ax in self._spatial_axes:
            shape[ax] = 1
        keep = jax.random.bernoulli(rng, 1.0 - self.rate, tuple(shape))
        return inputs * keep.astype(inputs.dtype) / (1.0 - self.rate), state


class SpatialDropout1D(_SpatialDropout):
    _spatial_axes = (1,)


class SpatialDropout2D(_SpatialDropout):
    _spatial_axes = (1, 2)


class SpatialDropout3D(_SpatialDropout):
    _spatial_axes = (1, 2, 3)


# -- structural layers --------------------------------------------------------


class Masking(Layer):
    """Zeroes timesteps equal to ``mask_value`` (reference ``Masking.scala``)."""

    def __init__(self, mask_value: float = 0.0, name: Optional[str] = None):
        super().__init__(name)
        self.mask_value = mask_value

    def call(self, params, state, inputs, *, training=False, rng=None):
        keep = jnp.any(inputs != self.mask_value, axis=-1, keepdims=True)
        return inputs * keep.astype(inputs.dtype), state


class Highway(Layer):
    """Dense highway: y = t * h(x) + (1 - t) * x (reference ``Highway.scala``)."""

    def __init__(self, activation="tanh", bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        from .core import get_activation
        self.activation = get_activation(activation)
        self.use_bias = bias

    def build(self, rng, input_shape):
        from .. import initializers
        d = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        init = initializers.get("glorot_uniform")
        params = {"kernel": init(k1, (d, d)), "gate_kernel": init(k2, (d, d))}
        if self.use_bias:
            params["bias"] = jnp.zeros((d,))
            # negative gate bias: start as identity-carry (standard highway init)
            params["gate_bias"] = jnp.full((d,), -2.0)
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        h = inputs @ params["kernel"].astype(inputs.dtype)
        t = inputs @ params["gate_kernel"].astype(inputs.dtype)
        if self.use_bias:
            h = h + params["bias"].astype(h.dtype)
            t = t + params["gate_bias"].astype(t.dtype)
        t = jax.nn.sigmoid(t)
        return t * self.activation(h) + (1 - t) * inputs, state


class MaxoutDense(Layer):
    """Max over ``nb_feature`` linear maps (reference ``MaxoutDense.scala``).
    One [D, P*F] matmul then a reshape+max — a single MXU tile."""

    def __init__(self, output_dim: int, nb_feature: int = 4, bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.use_bias = bias

    def build(self, rng, input_shape):
        from .. import initializers
        d = input_shape[-1]
        init = initializers.get("glorot_uniform")
        params = {"kernel": init(rng, (d, self.nb_feature * self.output_dim))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.nb_feature * self.output_dim,))
        return params, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        y = inputs @ params["kernel"].astype(inputs.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        y = y.reshape(y.shape[:-1] + (self.nb_feature, self.output_dim))
        return jnp.max(y, axis=-2), state

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class TimeDistributed(Layer):
    """Applies an inner layer to every timestep (reference
    ``TimeDistributed.scala``) by folding time into batch — no scan needed,
    one big fused call."""

    def __init__(self, layer: Layer, name: Optional[str] = None):
        super().__init__(name)
        self.inner = layer

    def build(self, rng, input_shape):
        inner_shape = (input_shape[0],) + tuple(input_shape[2:])
        return self.inner.build(rng, inner_shape)

    def call(self, params, state, inputs, *, training=False, rng=None):
        b, t = inputs.shape[0], inputs.shape[1]
        flat = inputs.reshape((b * t,) + inputs.shape[2:])
        y, new_state = self.inner.call(params, state, flat,
                                       training=training, rng=rng)
        return y.reshape((b, t) + y.shape[1:]), new_state

    def compute_output_shape(self, input_shape):
        inner_shape = (input_shape[0],) + tuple(input_shape[2:])
        out = self.inner.compute_output_shape(inner_shape)
        return (input_shape[0], input_shape[1]) + tuple(out[1:])


class SelectTable(Layer):
    """Picks the i-th tensor from a list input (reference
    ``SelectTable.scala``)."""

    def __init__(self, index: int, name: Optional[str] = None):
        super().__init__(name)
        self.index = index

    def call(self, params, state, inputs, *, training=False, rng=None):
        return inputs[self.index], state

    def compute_output_shape(self, input_shape):
        return input_shape[self.index]


class SplitTensor(Layer):
    """Splits along an axis into ``num_split`` outputs (reference
    ``SplitTensor.scala``)."""

    def __init__(self, split_dim: int, num_split: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.split_dim = split_dim
        self.num_split = num_split

    def call(self, params, state, inputs, *, training=False, rng=None):
        return list(jnp.split(inputs, self.num_split, axis=self.split_dim)), state

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        if shape[self.split_dim] is not None:
            shape[self.split_dim] //= self.num_split
        return [tuple(shape)] * self.num_split


class Narrow(Layer):
    """Slice [offset, offset+length) along ``dim`` (reference
    ``Narrow.scala``)."""

    def __init__(self, dim: int, offset: int, length: int = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.offset, self.length = dim, offset, length

    def call(self, params, state, inputs, *, training=False, rng=None):
        idx = [slice(None)] * inputs.ndim
        idx[self.dim] = slice(self.offset, self.offset + self.length)
        return inputs[tuple(idx)], state

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        shape[self.dim] = self.length
        return tuple(shape)


class Expand(Layer):
    """Broadcast singleton dims to ``shape`` (reference ``InternalExpand``)."""

    def __init__(self, shape: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.target = tuple(shape)

    def call(self, params, state, inputs, *, training=False, rng=None):
        target = (inputs.shape[0],) + self.target
        return jnp.broadcast_to(inputs, target), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self.target


class ExpandDim(Layer):
    def __init__(self, dim: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.expand_dims(inputs, self.dim), state

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        d = self.dim if self.dim >= 0 else len(shape) + 1 + self.dim
        shape.insert(d, 1)
        return tuple(shape)


class Max(Layer):
    """Max over ``dim``, optionally keeping it (reference ``Max.scala``)."""

    def __init__(self, dim: int, keep_dim: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.keep_dim = dim, keep_dim

    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.max(inputs, axis=self.dim, keepdims=self.keep_dim), state

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        if self.keep_dim:
            shape[self.dim] = 1
        else:
            del shape[self.dim]
        return tuple(shape)


class CAdd(Layer):
    """Learned bias of arbitrary broadcast shape (reference ``CAdd.scala``)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def build(self, rng, input_shape):
        return {"bias": jnp.zeros(self.size)}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        return inputs + params["bias"].astype(inputs.dtype), state


class CMul(Layer):
    """Learned scale of arbitrary broadcast shape (reference ``CMul.scala``)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(self.size)}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        return inputs * params["weight"].astype(inputs.dtype), state


class Mul(Layer):
    """Single learned scalar multiplier (reference ``Mul.scala``)."""

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(())}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        return inputs * params["weight"].astype(inputs.dtype), state


class Scale(Layer):
    """Per-channel affine: x * w + b (reference ``Scale.scala``)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(self.size), "bias": jnp.zeros(self.size)}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        return (inputs * params["weight"].astype(inputs.dtype)
                + params["bias"].astype(inputs.dtype)), state


class GetShape(Layer):
    """Returns the (static) shape of the input as a 1-D int32 tensor
    (reference ``GetShape.scala``). Under jit shapes are static, so this is a
    compile-time constant — free on device."""

    def call(self, params, state, inputs, *, training=False, rng=None):
        return jnp.asarray(inputs.shape, dtype=jnp.int32), state

    def compute_output_shape(self, input_shape):
        return (len(input_shape),)
