"""Validation metrics (reference ``pipeline/api/keras/metrics/`` — Accuracy,
Top5Accuracy, AUC, MAE, plus BigDL's Loss metric).

Streaming design: each metric is a pure accumulator — ``init_state()`` makes a
zeros pytree, ``update(state, y_true, y_pred, mask)`` folds one (possibly
padded) batch in on-device, ``compute(state)`` finalizes on host. This lets the
Estimator run evaluation as one jitted scan over sharded batches with no
host sync per batch; ``mask`` marks the valid rows of padded tail batches.
"""
from __future__ import annotations

from typing import Callable, Dict, Union

import jax
import jax.numpy as jnp


def _masked_mean_update(state, per_example, mask):
    per_example = per_example.reshape(mask.shape[0], -1).mean(axis=-1)
    return {"sum": state["sum"] + jnp.sum(per_example * mask),
            "count": state["count"] + jnp.sum(mask)}


class Metric:
    name = "metric"

    def init_state(self):
        return {"sum": jnp.zeros(()), "count": jnp.zeros(())}

    def update(self, state, y_true, y_pred, mask):
        raise NotImplementedError

    def compute(self, state):
        return float(state["sum"] / jnp.maximum(state["count"], 1))


class Accuracy(Metric):
    """Binary (threshold 0.5) or categorical accuracy, auto-detected from the
    prediction rank (reference zoo ``Accuracy.scala`` does the same)."""

    name = "accuracy"

    def update(self, state, y_true, y_pred, mask):
        if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            if y_true.ndim == y_pred.ndim:
                true = jnp.argmax(y_true, axis=-1)
            else:
                true = y_true.astype(jnp.int32)
            correct = (pred == true).astype(jnp.float32)
        else:
            p = y_pred.reshape(y_pred.shape[0], -1)[:, 0]
            t = y_true.reshape(y_true.shape[0], -1)[:, 0]
            correct = ((p > 0.5) == (t > 0.5)).astype(jnp.float32)
        return _masked_mean_update(state, correct, mask)


class TopK(Metric):
    def __init__(self, k: int = 5):
        self.k = k
        self.name = f"top{k}_accuracy"

    def update(self, state, y_true, y_pred, mask):
        true = (jnp.argmax(y_true, axis=-1) if y_true.ndim == y_pred.ndim
                else y_true.astype(jnp.int32))
        _, topk = jax.lax.top_k(y_pred, self.k)
        correct = jnp.any(topk == true[..., None], axis=-1).astype(jnp.float32)
        return _masked_mean_update(state, correct, mask)


class MAE(Metric):
    name = "mae"

    def update(self, state, y_true, y_pred, mask):
        err = jnp.abs(y_pred - y_true)
        return _masked_mean_update(state, err, mask)


class MSE(Metric):
    name = "mse"

    def update(self, state, y_true, y_pred, mask):
        err = jnp.square(y_pred - y_true)
        return _masked_mean_update(state, err, mask)


class Loss(Metric):
    """Streams the compiled loss function as a metric (BigDL ``Loss``)."""

    name = "loss"

    def __init__(self, loss_fn: Callable):
        self.loss_fn = loss_fn

    def update(self, state, y_true, y_pred, mask):
        # per-batch loss weighted by valid count (loss fns reduce internally)
        value = self.loss_fn(y_true, y_pred)
        n = jnp.sum(mask)
        return {"sum": state["sum"] + value * n, "count": state["count"] + n}


class AUC(Metric):
    """Streaming ROC-AUC via fixed threshold bins (jit-safe, like TF's AUC;
    the reference wraps TF's metric in ``keras/metrics``)."""

    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.num_thresholds = num_thresholds

    def init_state(self):
        n = self.num_thresholds
        return {"tp": jnp.zeros((n,)), "fp": jnp.zeros((n,)),
                "tn": jnp.zeros((n,)), "fn": jnp.zeros((n,))}

    def update(self, state, y_true, y_pred, mask):
        p = y_pred.reshape(y_pred.shape[0], -1)[:, 0]
        t = (y_true.reshape(y_true.shape[0], -1)[:, 0] > 0.5).astype(jnp.float32)
        thresholds = jnp.linspace(0.0, 1.0, self.num_thresholds)
        pred_pos = (p[None, :] >= thresholds[:, None]).astype(jnp.float32) * mask[None, :]
        actual_pos = t[None, :] * mask[None, :]
        actual_neg = (1 - t)[None, :] * mask[None, :]
        return {
            "tp": state["tp"] + jnp.sum(pred_pos * actual_pos, axis=1),
            "fp": state["fp"] + jnp.sum(pred_pos * actual_neg, axis=1),
            "fn": state["fn"] + jnp.sum((mask[None, :] - pred_pos) * actual_pos, axis=1),
            "tn": state["tn"] + jnp.sum((mask[None, :] - pred_pos) * actual_neg, axis=1),
        }

    def compute(self, state):
        tpr = state["tp"] / jnp.maximum(state["tp"] + state["fn"], 1e-7)
        fpr = state["fp"] / jnp.maximum(state["fp"] + state["tn"], 1e-7)
        # trapezoidal area over decreasing fpr
        return float(jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0))


_REGISTRY: Dict[str, Callable[[], Metric]] = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "top5": lambda: TopK(5),
    "top5_accuracy": lambda: TopK(5),
    "mae": MAE,
    "mse": MSE,
    "auc": AUC,
}


def get(metric: Union[str, Metric]) -> Metric:
    if isinstance(metric, Metric):
        return metric
    if metric not in _REGISTRY:
        raise ValueError(f"unknown metric '{metric}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[metric]()
