"""Validation metrics (reference ``pipeline/api/keras/metrics/`` — Accuracy,
Top5Accuracy, AUC, MAE, plus BigDL's Loss metric).

Streaming design: each metric is a pure accumulator — ``init_state()`` makes a
zeros pytree, ``update(state, y_true, y_pred, mask)`` folds one (possibly
padded) batch in on-device, ``compute(state)`` finalizes on host. This lets the
Estimator run evaluation as one jitted scan over sharded batches with no
host sync per batch; ``mask`` marks the valid rows of padded tail batches.
``compute`` implementations use NUMPY ops on purpose: after
:func:`compute_all`'s single ``device_get`` the finalize is pure host
arithmetic — no follow-up device dispatches, no second sync.
"""
from __future__ import annotations

from typing import Callable, Dict, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np


def compute_all(metrics: Sequence["Metric"], states) -> Dict[str, float]:
    """Finalize a whole eval pass with ONE host sync: every metric's
    device-resident state is fetched in a single ``jax.device_get``, then
    each ``compute`` runs on the host numpy arrays."""
    host_states = jax.device_get(list(states))
    return {m.name: m.compute(s) for m, s in zip(metrics, host_states)}


def _masked_mean_update(state, per_example, mask):
    per_example = per_example.reshape(mask.shape[0], -1).mean(axis=-1)
    return {"sum": state["sum"] + jnp.sum(per_example * mask),
            "count": state["count"] + jnp.sum(mask)}


class Metric:
    name = "metric"

    def init_state(self):
        return {"sum": jnp.zeros(()), "count": jnp.zeros(())}

    def update(self, state, y_true, y_pred, mask):
        raise NotImplementedError

    def compute(self, state):
        return float(np.asarray(state["sum"])
                     / np.maximum(np.asarray(state["count"]), 1))


class Accuracy(Metric):
    """Binary (threshold 0.5) or categorical accuracy, auto-detected from the
    prediction rank (reference zoo ``Accuracy.scala`` does the same)."""

    name = "accuracy"

    def update(self, state, y_true, y_pred, mask):
        if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            if y_true.ndim == y_pred.ndim:
                true = jnp.argmax(y_true, axis=-1)
            else:
                true = y_true.astype(jnp.int32)
            correct = (pred == true).astype(jnp.float32)
        else:
            p = y_pred.reshape(y_pred.shape[0], -1)[:, 0]
            t = y_true.reshape(y_true.shape[0], -1)[:, 0]
            correct = ((p > 0.5) == (t > 0.5)).astype(jnp.float32)
        return _masked_mean_update(state, correct, mask)


class TopK(Metric):
    def __init__(self, k: int = 5):
        self.k = k
        self.name = f"top{k}_accuracy"

    def update(self, state, y_true, y_pred, mask):
        true = (jnp.argmax(y_true, axis=-1) if y_true.ndim == y_pred.ndim
                else y_true.astype(jnp.int32))
        _, topk = jax.lax.top_k(y_pred, self.k)
        correct = jnp.any(topk == true[..., None], axis=-1).astype(jnp.float32)
        return _masked_mean_update(state, correct, mask)


class MAE(Metric):
    name = "mae"

    def update(self, state, y_true, y_pred, mask):
        err = jnp.abs(y_pred - y_true)
        return _masked_mean_update(state, err, mask)


class MSE(Metric):
    name = "mse"

    def update(self, state, y_true, y_pred, mask):
        err = jnp.square(y_pred - y_true)
        return _masked_mean_update(state, err, mask)


class Loss(Metric):
    """Streams the compiled loss function as a metric (BigDL ``Loss``)."""

    name = "loss"

    def __init__(self, loss_fn: Callable):
        self.loss_fn = loss_fn

    def update(self, state, y_true, y_pred, mask):
        # per-batch loss weighted by valid count (loss fns reduce internally)
        value = self.loss_fn(y_true, y_pred)
        n = jnp.sum(mask)
        return {"sum": state["sum"] + value * n, "count": state["count"] + n}


class AUC(Metric):
    """Streaming ROC-AUC via fixed threshold bins (jit-safe, like TF's AUC;
    the reference wraps TF's metric in ``keras/metrics``)."""

    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.num_thresholds = num_thresholds

    def init_state(self):
        n = self.num_thresholds
        return {"tp": jnp.zeros((n,)), "fp": jnp.zeros((n,)),
                "tn": jnp.zeros((n,)), "fn": jnp.zeros((n,))}

    def update(self, state, y_true, y_pred, mask):
        p = y_pred.reshape(y_pred.shape[0], -1)[:, 0]
        t = (y_true.reshape(y_true.shape[0], -1)[:, 0] > 0.5).astype(jnp.float32)
        thresholds = jnp.linspace(0.0, 1.0, self.num_thresholds)
        pred_pos = (p[None, :] >= thresholds[:, None]).astype(jnp.float32) * mask[None, :]
        actual_pos = t[None, :] * mask[None, :]
        actual_neg = (1 - t)[None, :] * mask[None, :]
        return {
            "tp": state["tp"] + jnp.sum(pred_pos * actual_pos, axis=1),
            "fp": state["fp"] + jnp.sum(pred_pos * actual_neg, axis=1),
            "fn": state["fn"] + jnp.sum((mask[None, :] - pred_pos) * actual_pos, axis=1),
            "tn": state["tn"] + jnp.sum((mask[None, :] - pred_pos) * actual_neg, axis=1),
        }

    def compute(self, state):
        state = {k: np.asarray(v) for k, v in state.items()}
        tpr = state["tp"] / np.maximum(state["tp"] + state["fn"], 1e-7)
        fpr = state["fp"] / np.maximum(state["fp"] + state["tn"], 1e-7)
        # trapezoidal area over decreasing fpr
        return float(np.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0))


class _PRF(Metric):
    """Shared tp/fp/fn accumulator for precision/recall/F1.

    Binary contract mirrors :class:`Accuracy`: categorical predictions
    compare ``argmax == positive_class``; single-column probabilities
    threshold at 0.5. Token-level tasks ([B, S] labels) count every
    position of the valid rows."""

    def __init__(self, positive_class: int = 1):
        self.positive_class = positive_class

    def init_state(self):
        # three DISTINCT buffers: the eval step donates metric states, and
        # aliasing one zeros array would donate the same buffer thrice
        return {"tp": jnp.zeros(()), "fp": jnp.zeros(()),
                "fn": jnp.zeros(())}

    def update(self, state, y_true, y_pred, mask):
        if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1) == self.positive_class
            if y_true.ndim == y_pred.ndim:
                true = jnp.argmax(y_true, axis=-1) == self.positive_class
            else:
                true = y_true.astype(jnp.int32) == self.positive_class
        else:
            pred = y_pred.reshape(y_pred.shape[0], -1) > 0.5
            true = y_true.reshape(y_true.shape[0], -1) > 0.5
            if self.positive_class == 0:  # stats for the negative label
                pred, true = ~pred, ~true
        pred = pred.reshape(mask.shape[0], -1)
        true = true.reshape(mask.shape[0], -1)
        m = mask[:, None].astype(jnp.float32)
        return {
            "tp": state["tp"] + jnp.sum((pred & true) * m),
            "fp": state["fp"] + jnp.sum((pred & ~true) * m),
            "fn": state["fn"] + jnp.sum((~pred & true) * m),
        }


class Precision(_PRF):
    name = "precision"

    def compute(self, state):
        tp, fp = np.asarray(state["tp"]), np.asarray(state["fp"])
        return float(tp / np.maximum(tp + fp, 1))


class Recall(_PRF):
    name = "recall"

    def compute(self, state):
        tp, fn = np.asarray(state["tp"]), np.asarray(state["fn"])
        return float(tp / np.maximum(tp + fn, 1))


class F1(_PRF):
    name = "f1"

    def compute(self, state):
        tp = np.asarray(state["tp"])
        p = tp / np.maximum(tp + np.asarray(state["fp"]), 1)
        r = tp / np.maximum(tp + np.asarray(state["fn"]), 1)
        return float(2 * p * r / np.maximum(p + r, 1e-12))


_REGISTRY: Dict[str, Callable[[], Metric]] = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "top5": lambda: TopK(5),
    "top5_accuracy": lambda: TopK(5),
    "mae": MAE,
    "mse": MSE,
    "auc": AUC,
    "precision": Precision,
    "recall": Recall,
    "f1": F1,
}


def get(metric: Union[str, Metric]) -> Metric:
    if isinstance(metric, Metric):
        return metric
    if metric not in _REGISTRY:
        raise ValueError(f"unknown metric '{metric}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[metric]()


# -- ranking metrics (reference models/common/Ranker.scala:108-175) ----------


def _rank_by_pred(y_true, y_pred):
    """Sort each row's labels by descending prediction. [Q, L] -> [Q, L]."""
    order = jnp.argsort(-y_pred, axis=-1)
    return jnp.take_along_axis(y_true, order, axis=-1)


def ndcg_score(y_true, y_pred, k: int, threshold: float = 0.0):
    """Per-query NDCG@k, vectorized over [Q, L] groups.

    Matches ``Ranker.ndcg`` (Ranker.scala:114-146): gain ``2^g / ln(2+i)``
    counted only where ``g > threshold``; ideal ranking sorts by label.
    """
    g_pred = _rank_by_pred(y_true, y_pred)
    g_ideal = -jnp.sort(-y_true, axis=-1)
    i = jnp.arange(y_true.shape[-1])
    disc = jnp.where(i < k, 1.0 / jnp.log(2.0 + i), 0.0)

    def dcg(g):
        gain = jnp.where(g > threshold, jnp.power(2.0, g), 0.0)
        return jnp.sum(gain * disc, axis=-1)

    idcg = dcg(g_ideal)
    return jnp.where(idcg > 0, dcg(g_pred) / jnp.maximum(idcg, 1e-12), 0.0)


def map_score(y_true, y_pred, threshold: float = 0.0):
    """Per-query average precision over [Q, L] groups
    (``Ranker.map``, Ranker.scala:148-174)."""
    g = _rank_by_pred(y_true, y_pred)
    pos = (g > threshold).astype(jnp.float32)
    cum_pos = jnp.cumsum(pos, axis=-1)
    ranks = jnp.arange(1, y_true.shape[-1] + 1)
    prec_at_hit = pos * cum_pos / ranks
    n_pos = jnp.sum(pos, axis=-1)
    return jnp.where(n_pos > 0,
                     jnp.sum(prec_at_hit, axis=-1) / jnp.maximum(n_pos, 1.0),
                     0.0)


def hit_ratio_score(y_true, y_pred, k: int, threshold: float = 0.0):
    """Per-query HitRatio@k over [Q, L] groups (BigDL ``HitRatio``, used by
    the reference NCF example): 1 if any positive lands in the top-k."""
    g = _rank_by_pred(y_true, y_pred)
    topk_pos = jnp.any(g[..., :k] > threshold, axis=-1)
    return topk_pos.astype(jnp.float32)


class _GroupedRankingMetric(Metric):
    """Streams a per-query ranking score over [Q, L]-shaped batches: each
    batch row is one query's candidate list (the reference's 'each Sample is
    a batch of records with both positive and negative labels')."""

    def _score(self, y_true, y_pred):
        raise NotImplementedError

    def update(self, state, y_true, y_pred, mask):
        q = mask.shape[0]
        y_true = y_true.reshape(q, -1)
        l = y_true.shape[1]
        if y_pred.size % (q * l) != 0:
            raise ValueError(
                f"ranking metric needs [Q, L(, C)] predictions matching "
                f"labels [Q, L]; got pred {y_pred.shape} vs true {y_true.shape}")
        # multi-class outputs rank by positive-class (last column) probability
        y_pred = y_pred.reshape(q, l, -1)[..., -1]
        score = self._score(y_true, y_pred)
        return {"sum": state["sum"] + jnp.sum(score * mask),
                "count": state["count"] + jnp.sum(mask)}


class NDCG(_GroupedRankingMetric):
    def __init__(self, k: int = 10, threshold: float = 0.0):
        if k <= 0:
            raise ValueError(f"k for NDCG must be positive, got {k}")
        self.k, self.threshold = k, threshold
        self.name = f"ndcg@{k}"

    def _score(self, y_true, y_pred):
        return ndcg_score(y_true, y_pred, self.k, self.threshold)


class MAP(_GroupedRankingMetric):
    name = "map"

    def __init__(self, threshold: float = 0.0):
        self.threshold = threshold

    def _score(self, y_true, y_pred):
        return map_score(y_true, y_pred, self.threshold)


class HitRatio(_GroupedRankingMetric):
    def __init__(self, k: int = 10, threshold: float = 0.0):
        if k <= 0:
            raise ValueError(f"k for HitRatio must be positive, got {k}")
        self.k, self.threshold = k, threshold
        self.name = f"hit_ratio@{k}"

    def _score(self, y_true, y_pred):
        return hit_ratio_score(y_true, y_pred, self.k, self.threshold)


_REGISTRY.update({
    "ndcg": NDCG,
    "map": MAP,
    "hit_ratio": HitRatio,
})
