"""Optimizers and LR schedules (reference: BigDL ``OptimMethod`` family plus
zoo extras ``keras/optimizers/`` — ``AdamWeightDecay`` with warmup/linear decay
as used for BERT — and the ``Fixed`` schedule in ``common/Optim.scala:23``).

Backed by optax: each wrapper produces an ``optax.GradientTransformation`` so
the optimizer update runs inside the jitted train step on device — the
reference applies its optimizer on parameter-slice owners between Spark jobs;
here it's fused into the same XLA program as the backward pass.
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import optax

Schedule = Union[float, Callable[[int], float]]


def fixed(lr: float) -> Callable[[int], float]:
    """Constant LR (reference ``Fixed``)."""
    return lambda step: lr


def poly(lr: float, power: float, max_steps: int) -> Callable[[int], float]:
    return optax.polynomial_schedule(lr, 0.0, power, max_steps)


def warmup_linear_decay(lr: float, warmup_steps: int, total_steps: int
                        ) -> Callable[[int], float]:
    """Linear warmup then linear decay to 0 (the BERT ``AdamWeightDecay``
    schedule, reference ``keras/optimizers/AdamWeightDecay``)."""
    return optax.join_schedules(
        [optax.linear_schedule(0.0, lr, warmup_steps),
         optax.linear_schedule(lr, 0.0, max(1, total_steps - warmup_steps))],
        [warmup_steps])


def warmup_cosine_decay(lr: float, warmup_steps: int, total_steps: int
                        ) -> Callable[[int], float]:
    return optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, total_steps)


class Optimizer:
    """Named wrapper so models can introspect/serialize their optimizer."""

    #: When set to ``(kind, hyperparams)`` the estimator may apply this
    #: optimizer to vocab-sharded embedding tables as a sparse row-subset
    #: update (parallel/embedding.py) — state for untouched rows is neither
    #: read nor written. ``None`` means the optimizer math has no sparse
    #: equivalent (momentum/decay/schedules) and sharded tables fall back
    #: to the dense optax update.
    sparse_rows = None

    def __init__(self, name: str, tx: optax.GradientTransformation,
                 learning_rate: Schedule):
        self.name = name
        self.tx = tx
        self.learning_rate = learning_rate

    def init(self, params):
        return self.tx.init(params)

    def update(self, grads, opt_state, params=None):
        return self.tx.update(grads, opt_state, params)


def SGD(learningrate: float = 0.01, momentum: float = 0.0, dampening: float = 0.0,
        nesterov: bool = False, weightdecay: float = 0.0,
        learningrate_schedule: Optional[Schedule] = None) -> Optimizer:
    lr = learningrate_schedule if learningrate_schedule is not None else learningrate
    parts = []
    if weightdecay > 0:
        parts.append(optax.add_decayed_weights(weightdecay))
    parts.append(optax.sgd(lr, momentum=momentum or None, nesterov=nesterov))
    opt = Optimizer("sgd", optax.chain(*parts), lr)
    if (momentum == 0.0 and dampening == 0.0 and not nesterov
            and weightdecay == 0.0 and learningrate_schedule is None):
        opt.sparse_rows = ("sgd", {"lr": float(learningrate)})
    return opt


def Adam(learningrate: float = 1e-3, beta1: float = 0.9, beta2: float = 0.999,
         epsilon: float = 1e-8,
         learningrate_schedule: Optional[Schedule] = None) -> Optimizer:
    lr = learningrate_schedule if learningrate_schedule is not None else learningrate
    opt = Optimizer("adam", optax.adam(lr, b1=beta1, b2=beta2, eps=epsilon), lr)
    if learningrate_schedule is None:
        # Lazy adam: moments decay only for touched rows. Same fixed point,
        # NOT bit-identical to dense adam (docs/embeddings.md).
        opt.sparse_rows = ("adam", {"lr": float(learningrate),
                                    "b1": float(beta1), "b2": float(beta2),
                                    "eps": float(epsilon)})
    return opt


def AdamWeightDecay(learningrate: float = 1e-4, warmup_portion: float = -1.0,
                    total: int = -1, schedule: str = "linear",
                    beta1: float = 0.9, beta2: float = 0.999,
                    epsilon: float = 1e-6, weight_decay: float = 0.01
                    ) -> Optimizer:
    """BERT-style AdamW with warmup (reference ``AdamWeightDecay``)."""
    if total > 0 and warmup_portion > 0:
        warmup = int(total * warmup_portion)
        lr = (warmup_linear_decay(learningrate, warmup, total)
              if schedule == "linear"
              else warmup_cosine_decay(learningrate, warmup, total))
    else:
        lr = learningrate
    return Optimizer(
        "adam_weight_decay",
        optax.adamw(lr, b1=beta1, b2=beta2, eps=epsilon, weight_decay=weight_decay),
        lr)


def RMSprop(learningrate: float = 1e-3, decayrate: float = 0.9,
            epsilon: float = 1e-8) -> Optimizer:
    return Optimizer("rmsprop",
                     optax.rmsprop(learningrate, decay=decayrate, eps=epsilon),
                     learningrate)


def Adagrad(learningrate: float = 1e-2, weightdecay: float = 0.0) -> Optimizer:
    parts = []
    if weightdecay > 0:
        parts.append(optax.add_decayed_weights(weightdecay))
    parts.append(optax.adagrad(learningrate))
    opt = Optimizer("adagrad", optax.chain(*parts), learningrate)
    if weightdecay == 0.0:
        opt.sparse_rows = ("adagrad", {"lr": float(learningrate), "eps": 1e-7})
    return opt


def Adadelta(decayrate: float = 0.9, epsilon: float = 1e-10) -> Optimizer:
    return Optimizer("adadelta", optax.adadelta(rho=decayrate, eps=epsilon), 1.0)


def LARS(learningrate: float = 0.1, momentum: float = 0.9,
         weightdecay: float = 1e-4,
         learningrate_schedule: Optional[Schedule] = None) -> Optimizer:
    """Layer-wise adaptive rate scaling for large-batch ResNet training."""
    lr = learningrate_schedule if learningrate_schedule is not None else learningrate
    return Optimizer("lars",
                     optax.lars(lr, weight_decay=weightdecay, momentum=momentum),
                     lr)


_FACTORIES = {
    "sgd": SGD,
    "adam": Adam,
    "adamw": AdamWeightDecay,
    "adam_weight_decay": AdamWeightDecay,
    "rmsprop": RMSprop,
    "adagrad": Adagrad,
    "adadelta": Adadelta,
    "lars": LARS,
}


def get(optimizer: Union[str, Optimizer],
        learning_rate: Optional[float] = None) -> Optimizer:
    """Resolve an optimizer by name/instance; ``learning_rate`` overrides the
    named factory's default (ignored for pre-built instances)."""
    if isinstance(optimizer, Optimizer):
        return optimizer
    if isinstance(optimizer, optax.GradientTransformation):
        return Optimizer("custom", optimizer, 0.0)
    key = str(optimizer).lower()
    if key not in _FACTORIES:
        raise ValueError(f"unknown optimizer '{optimizer}'; have {sorted(_FACTORIES)}")
    factory = _FACTORIES[key]
    return factory() if learning_rate is None else factory(learning_rate)
