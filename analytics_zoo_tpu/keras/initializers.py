"""Weight initializers (reference: BigDL InitializationMethod family used by
the Keras layers' ``init`` argument — glorot_uniform default)."""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

Initializer = Callable[[jax.Array, Sequence[int], jnp.dtype], jax.Array]


def _fans(shape: Sequence[int]) -> tuple:
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
    return shape[-2] * receptive, shape[-1] * receptive


def glorot_uniform(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def glorot_normal(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    std = float(np.sqrt(2.0 / (fan_in + fan_out)))
    return std * jax.random.normal(rng, shape, dtype)


def he_normal(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    return float(np.sqrt(2.0 / fan_in)) * jax.random.normal(rng, shape, dtype)


def he_uniform(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = float(np.sqrt(6.0 / fan_in))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def lecun_uniform(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = float(np.sqrt(3.0 / fan_in))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def uniform(rng, shape, dtype=jnp.float32, scale=0.05):
    return jax.random.uniform(rng, shape, dtype, -scale, scale)


def normal(rng, shape, dtype=jnp.float32, stddev=0.05):
    return stddev * jax.random.normal(rng, shape, dtype)


def zeros(rng, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(rng, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


_REGISTRY = {
    "glorot_uniform": glorot_uniform,
    "glorot_normal": glorot_normal,
    "he_normal": he_normal,
    "he_uniform": he_uniform,
    "lecun_uniform": lecun_uniform,
    "uniform": uniform,
    "normal": normal,
    "zero": zeros,
    "zeros": zeros,
    "one": ones,
    "ones": ones,
}


def get(init: Union[str, Initializer]) -> Initializer:
    if callable(init):
        return init
    if init not in _REGISTRY:
        raise ValueError(f"unknown initializer '{init}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[init]
