"""Autograd variable algebra (reference ``pipeline/api/autograd/math.scala:32-364``,
``pyzoo/zoo/pipeline/api/autograd.py:256``): symbolic-tensor math for building
model graphs and custom losses without writing Layer classes.

Every function takes/returns :class:`~analytics_zoo_tpu.keras.engine.SymbolicTensor`
and stamps a small functional layer into the graph; under jit the resulting
ops fuse like any hand-written jax — the DSL costs nothing at run time.

Also provides the reference's two autograd entry points beyond plain math:
- :func:`Parameter` — a standalone trainable variable usable inside
  expressions (``KerasParameter.scala:1``);
- :class:`CustomLoss` — build a loss function from a symbolic expression of
  ``(y_true, y_pred)`` (``CustomLoss.scala:29``).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import initializers
from .engine import Input, Layer, Model, Node, SymbolicTensor
from .layers.core import Lambda, merge

Sym = SymbolicTensor


def _unary(fn, name):
    def op(x: Sym, **kw) -> Sym:
        return Lambda(lambda t: fn(t, **kw), name=None)(x)
    op.__name__ = name
    return op


def _pairwise(fn):
    def op(a, b) -> Sym:
        if isinstance(a, Sym) and isinstance(b, Sym):
            return Lambda(lambda xs: fn(xs[0], xs[1]))([a, b])
        if isinstance(a, Sym):
            return Lambda(lambda t: fn(t, b))(a)
        return Lambda(lambda t: fn(a, t))(b)
    return op


# -- elementwise unary (math.scala abs/exp/log/sqrt/square/...) -------------

abs = _unary(jnp.abs, "abs")  # noqa: A001 - mirrors the reference API
exp = _unary(jnp.exp, "exp")
log = _unary(jnp.log, "log")
sqrt = _unary(jnp.sqrt, "sqrt")
square = _unary(jnp.square, "square")
neg = _unary(jnp.negative, "neg")
erf = _unary(jax.scipy.special.erf, "erf")
relu = _unary(jax.nn.relu, "relu")
softsign = _unary(jax.nn.soft_sign, "softsign")
softplus = _unary(jax.nn.softplus, "softplus")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")


def epsilon() -> float:
    """Fuzz factor (reference ``AutoGrad.epsilon``)."""
    return 1e-7


def clip(x: Sym, min_value: float, max_value: float) -> Sym:
    return Lambda(lambda t: jnp.clip(t, min_value, max_value))(x)


def pow(x: Sym, a: float) -> Sym:  # noqa: A001
    return x ** a


# -- reductions (axes follow the reference: 0 = first non-batch axis is 1) --


def _reduce(fn):
    def op(x: Sym, axis: int = 0, keepdims: bool = False) -> Sym:
        # reference semantics: axis counts INCLUDE the batch dim (axis 0 =
        # batch); most uses pass axis >= 1
        return Lambda(lambda t: fn(t, axis=axis, keepdims=keepdims))(x)
    return op


mean = _reduce(jnp.mean)
sum = _reduce(jnp.sum)  # noqa: A001
max = _reduce(jnp.max)  # noqa: A001
min = _reduce(jnp.min)  # noqa: A001


def maximum(a, b) -> Sym:
    return _pairwise(jnp.maximum)(a, b)


def minimum(a, b) -> Sym:
    return _pairwise(jnp.minimum)(a, b)


# -- shape ops ---------------------------------------------------------------


def expand_dims(x: Sym, axis: int) -> Sym:
    return Lambda(lambda t: jnp.expand_dims(t, axis))(x)


def squeeze(x: Sym, axis: int) -> Sym:
    return Lambda(lambda t: jnp.squeeze(t, axis))(x)


def reshape(x: Sym, shape: Sequence[int]) -> Sym:
    """``shape`` excludes the batch dim (Keras convention)."""
    return Lambda(lambda t: jnp.reshape(t, (t.shape[0],) + tuple(shape)))(x)


def transpose(x: Sym, perm: Sequence[int]) -> Sym:
    """``perm`` over non-batch axes, 1-based like keras Permute."""
    return Lambda(lambda t: jnp.transpose(t, (0,) + tuple(perm)))(x)


def stack(inputs: Sequence[Sym], axis: int = 1) -> Sym:
    return Lambda(lambda xs: jnp.stack(xs, axis=axis))(list(inputs))


def concat(inputs: Sequence[Sym], axis: int = -1) -> Sym:
    return merge(list(inputs), mode="concat", concat_axis=axis)


def index_select(x: Sym, dim: int, index: int) -> Sym:
    """Select one slice along ``dim`` (reference ``indexSelect``)."""
    return Lambda(lambda t: jnp.take(t, index, axis=dim))(x)


def slice(x: Sym, dim: int, start: int, length: int) -> Sym:  # noqa: A001
    return Lambda(lambda t: jax.lax.slice_in_dim(t, start, start + length,
                                                 axis=dim))(x)


# -- contractions ------------------------------------------------------------


def mm(a: Sym, b: Sym, axes: Optional[Sequence[int]] = None) -> Sym:
    """Batched matmul contracting ``axes`` (reference ``AutoGrad.mm``)."""
    if axes is None:
        return Lambda(lambda xs: jnp.matmul(xs[0], xs[1]))([a, b])

    def dot(xs):
        x, y = xs
        return jax.lax.dot_general(
            x, y, (((axes[0],), (axes[1],)), ((0,), (0,))))
    return Lambda(dot)([a, b])


batch_dot = mm


def dot(a: Sym, b: Sym, axes: Sequence[int] = (1, 1)) -> Sym:
    return mm(a, b, axes=axes)


def l2_normalize(x: Sym, axis: int = -1) -> Sym:
    return Lambda(lambda t: t / jnp.maximum(
        jnp.linalg.norm(t, axis=axis, keepdims=True), epsilon()))(x)


def softmax(x: Sym, axis: int = -1) -> Sym:
    return Lambda(lambda t: jax.nn.softmax(t, axis=axis))(x)


# -- trainable Parameter (KerasParameter.scala role) ------------------------


class _ParameterLayer(Layer):
    """A no-input node whose output IS its trainable weight."""

    def __init__(self, shape: Sequence[int], init="glorot_uniform",
                 trainable: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.shape = tuple(shape)
        self.init = initializers.get(init)
        self.trainable = trainable

    def build(self, rng, input_shape):
        return {"weight": self.init(rng, self.shape)}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        w = params["weight"]
        if not self.trainable:
            w = jax.lax.stop_gradient(w)
        return w, state

    def compute_output_shape(self, input_shape):
        return self.shape


def Parameter(shape: Sequence[int], init="glorot_uniform",
              trainable: bool = True, name: Optional[str] = None) -> Sym:
    """A standalone trainable variable usable in autograd expressions.

    Note the returned tensor has NO batch axis — broadcast it against
    batch-shaped tensors with normal numpy semantics."""
    layer = _ParameterLayer(shape, init=init, trainable=trainable, name=name)
    node = Node(layer, [])
    return SymbolicTensor(layer.shape, node, 0)


# -- CustomLoss (CustomLoss.scala:29) ---------------------------------------


class CustomLoss:
    """Build a loss from a symbolic expression.

    ``loss_expr(y_true, y_pred)`` receives two symbolic tensors and returns a
    symbolic per-record (or scalar) loss; the result is mean-reduced. The
    instance is directly usable as an Estimator/compile ``loss``.

    Example::

        def huber(y_true, y_pred):
            err = abs(y_true - y_pred)
            return mean(minimum(0.5 * err * err, err - 0.5), axis=1)
        model.compile(optimizer="adam", loss=CustomLoss(huber, [1]))
    """

    def __init__(self, loss_expr, y_pred_shape: Sequence[int],
                 y_true_shape: Optional[Sequence[int]] = None):
        yt = Input(shape=tuple(y_true_shape or y_pred_shape),
                   name="customloss_y_true")
        yp = Input(shape=tuple(y_pred_shape), name="customloss_y_pred")
        out = loss_expr(yt, yp)
        self._model = Model([yt, yp], out)
        self._params, self._state = self._model.build(jax.random.PRNGKey(7))
        if jax.tree_util.tree_leaves(self._params):
            raise ValueError(
                "CustomLoss expressions must be parameter-free (use model "
                "layers + a regular objective for trainable pieces)")

    def __call__(self, y_true, y_pred):
        y_true = jnp.asarray(y_true)
        y_pred = jnp.asarray(y_pred)
        if y_true.ndim == y_pred.ndim - 1:  # sparse labels convenience
            y_true = y_true[..., None]
        out, _ = self._model.call(self._params, self._state,
                                  [y_true, y_pred], training=True)
        return jnp.mean(out)
