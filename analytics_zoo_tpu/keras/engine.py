"""Keras-style model engine, TPU-native.

Re-designs the reference's Keras-1 DSL (``zoo/.../pipeline/api/keras/models/
Topology.scala:65`` ``Sequential``/``Model`` compiled to BigDL graphs; Python
mirror ``pyzoo/zoo/pipeline/api/keras/engine/topology.py:31``) as a functional
JAX layer system:

- a :class:`Layer` is a stateless *config*; parameters and mutable state
  (e.g. BatchNorm running stats) live in external pytrees, created by
  ``build`` and consumed by ``call`` — so the whole model is a pure function
  XLA can trace, jit, and shard.
- :class:`Sequential` chains layers; :class:`Model` is the functional graph
  built by calling layers on symbolic tensors (``Input``). Operator
  overloading on symbolic tensors gives the reference's autograd ``Variable``
  algebra (``pipeline/api/autograd/math.scala:378``) for free.
- ``compile/fit/evaluate/predict`` delegate to the Estimator's on-device
  pjit'd train loop.

Shapes follow Keras convention: ``(None, d1, d2, ...)`` with a ``None`` batch.
"""
from __future__ import annotations

import itertools
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Shape = Tuple[Optional[int], ...]

# state-contract key: layers publish scalar penalties (MoE router balance,
# activation regularizers, ...) under this key in their returned state; the
# Estimator adds them to the training objective
AUX_LOSS_KEY = "__aux_loss__"
# state-contract key: capacity-limited layers (MoE) publish a RUNNING count
# of tokens dropped to overflow under this key; the Estimator drains it at
# its per-epoch host-sync point into parallel.moe_dropped_tokens_total so
# capacity-factor dropping is never silent
MOE_DROP_KEY = "__moe_dropped__"
_name_counters: Dict[str, "itertools.count"] = defaultdict(lambda: itertools.count(1))


def _auto_name(cls_name: str) -> str:
    return f"{cls_name.lower()}_{next(_name_counters[cls_name])}"


def reset_name_counters() -> None:
    _name_counters.clear()


class Layer:
    """Base layer: ``build`` makes (params, state) pytrees, ``call`` is pure."""

    def __init__(self, name: Optional[str] = None):
        self._auto_named = name is None
        self.name = name or _auto_name(type(self).__name__)
        self.built_shape: Optional[Any] = None

    # -- to override ----------------------------------------------------------

    def build(self, rng: jax.Array, input_shape) -> Tuple[Any, Any]:
        """Return ``(params, state)`` for ``input_shape``. Default: stateless."""
        return {}, {}

    def call(self, params: Any, state: Any, inputs: Any, *,
             training: bool = False, rng: Optional[jax.Array] = None
             ) -> Tuple[Any, Any]:
        """Pure forward: return ``(outputs, new_state)``."""
        raise NotImplementedError

    def compute_output_shape(self, input_shape):
        return input_shape

    # -- graph building -------------------------------------------------------

    def __call__(self, inputs):
        """Called on symbolic tensor(s): record a graph node."""
        syms = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if not all(isinstance(s, SymbolicTensor) for s in syms):
            raise TypeError(
                f"{self.name} called on non-symbolic input; use "
                f"layer.call(params, state, x) for concrete arrays")
        in_shapes = [s.shape for s in syms]
        shape_arg = in_shapes if isinstance(inputs, (list, tuple)) else in_shapes[0]
        out_shape = self.compute_output_shape(shape_arg)
        node = Node(self, list(syms))
        if isinstance(out_shape, list):
            outs = [SymbolicTensor(tuple(s), node, i) for i, s in enumerate(out_shape)]
            node.n_outputs = len(outs)
            return outs
        return SymbolicTensor(tuple(out_shape), node, 0)

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


def _scope_names(layers: Sequence["Layer"]) -> None:
    """Deterministically rename auto-named layers by position within a
    container, so two structurally identical models share parameter keys
    (checkpoints stay loadable across model instances/processes). A layer is
    renamed by the FIRST container that scopes it — shared layers (graph
    surgery via ``new_graph``, one layer in two graphs) keep their name so an
    existing params tree still matches."""
    counters: Dict[str, int] = defaultdict(int)
    seen = set()
    kept: Dict[str, int] = {}
    for l in layers:
        if not l._auto_named and kept.setdefault(l.name, id(l)) != id(l):
            # two DISTINCT layers carrying the same kept/explicit name would
            # silently share one param-tree slot (build dedups by name) —
            # e.g. layers scoped in two separate graphs then composed; fail
            # loudly so the user renames one
            raise ValueError(
                f"duplicate layer name '{l.name}' from two different layers "
                f"in one container; rename one (names key the param tree)")
    taken = set(kept)
    for layer in layers:
        if id(layer) in seen:
            continue
        seen.add(id(layer))
        cls = type(layer).__name__.lower()
        counters[cls] += 1
        if layer._auto_named:
            # skip names already held by kept/explicit layers in this
            # container — a shared layer keeping its old name must not
            # collide with a freshly scoped one (names are param-tree keys)
            while f"{cls}_{counters[cls]}" in taken:
                counters[cls] += 1
            layer.name = f"{cls}_{counters[cls]}"
            layer._auto_named = False
            taken.add(layer.name)


class Node:
    """One application of a layer to symbolic inputs (supports shared layers)."""

    def __init__(self, layer: Layer, inputs: List["SymbolicTensor"]):
        self.layer = layer
        self.inputs = inputs
        self.n_outputs = 1


class SymbolicTensor:
    """Placeholder tensor in the functional graph (the autograd ``Variable``)."""

    def __init__(self, shape: Shape, node: Optional[Node], index: int = 0,
                 dtype=jnp.float32):
        self.shape = shape
        self.node = node
        self.index = index
        self.dtype = dtype

    # autograd Variable operator algebra (reference api/autograd/math.scala)
    def _binop(self, other, fn, symbol):
        from .layers.core import ElementwiseOp
        if isinstance(other, SymbolicTensor):
            return ElementwiseOp.binary(fn, symbol)([self, other])
        return ElementwiseOp.with_scalar(fn, symbol, other)(self)

    def __add__(self, other):
        return self._binop(other, jnp.add, "add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, jnp.subtract, "sub")

    def __rsub__(self, other):
        from .layers.core import ElementwiseOp
        return ElementwiseOp.with_scalar(lambda x, s: s - x, "rsub", other)(self)

    def __mul__(self, other):
        return self._binop(other, jnp.multiply, "mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, jnp.divide, "div")

    def __neg__(self):
        from .layers.core import ElementwiseOp
        return ElementwiseOp.with_scalar(lambda x, s: -x, "neg", 0.0)(self)

    def __pow__(self, p):
        from .layers.core import ElementwiseOp
        return ElementwiseOp.with_scalar(jnp.power, "pow", p)(self)

    def __repr__(self):
        return f"<SymbolicTensor {self.shape}>"


class InputLayer(Layer):
    def __init__(self, shape: Shape, name: Optional[str] = None):
        super().__init__(name)
        self.shape = (None,) + tuple(shape)

    def call(self, params, state, inputs, *, training=False, rng=None):
        return inputs, state

    def compute_output_shape(self, input_shape):
        return self.shape


def Input(shape: Shape, name: Optional[str] = None) -> SymbolicTensor:
    layer = InputLayer(shape, name)
    node = Node(layer, [])
    return SymbolicTensor(layer.shape, node, 0)


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------


class _TrainableMixin:
    """compile/fit/evaluate/predict surface shared by Sequential and Model
    (the reference ``KerasNet`` contract, Topology.scala:65-260)."""

    # -- transfer learning (reference GraphNet/NetUtils.scala freeze API) -----

    @property
    def frozen_layers(self):
        return getattr(self, "_frozen_layers", frozenset())

    def _param_layer_names(self) -> List[str]:
        """Names of layers that own parameters (top-level param-tree keys)."""
        if getattr(self, "_param_names_cache", None) is None:
            est = getattr(self, "_estimator", None)
            if est is not None and est.params is not None:
                self._param_names_cache = list(est.params)
            else:
                rng = jax.random.PRNGKey(0)
                if self.built_shape is not None:
                    # abstract build: names only, no parameter allocation
                    out = jax.eval_shape(
                        lambda r: self.build(r, self.built_shape), rng)
                elif isinstance(self, Model):
                    out = jax.eval_shape(lambda r: self.build(r), rng)
                else:
                    raise RuntimeError("model must be built before freeze()")
                self._param_names_cache = list(out[0])
        return self._param_names_cache

    def _invalidate_steps(self):
        est = getattr(self, "_estimator", None)
        if est is not None:
            est._train_step = None

    def _all_layer_names(self) -> set:
        """TOP-LEVEL layer names only: the param tree (and therefore the
        freeze mask in the train step) is keyed by these. A nested layer's
        name can never match a top-level key, so offering it for freeze()
        would be the silent no-op this validation exists to prevent —
        freeze the enclosing container instead."""
        if isinstance(self, Model):
            return {n.layer.name for n in self._nodes}
        return {layer.name for layer in getattr(self, "layers", [])}

    def freeze(self, names: Optional[Sequence[str]] = None) -> "Layer":
        """Freeze the given layers (all param layers if ``names`` is None):
        their params receive no gradient and no optimizer update. The train
        step applies ``stop_gradient`` so XLA dead-code-eliminates the
        frozen backward pass entirely (reference ``NetUtils.scala:79``)."""
        if names is None:
            names = self._param_layer_names()
        elif isinstance(names, str):
            names = [names]
        known = self._all_layer_names()
        try:
            known |= set(self._param_layer_names())
        except RuntimeError:
            pass  # unbuilt Sequential: validate against layer names only
        unknown = set(names) - known
        if unknown:
            # a typo here would silently leave a backbone trainable
            raise ValueError(f"freeze: unknown layer name(s) {sorted(unknown)}; "
                             f"known layers: {sorted(known)}")
        self._frozen_layers = frozenset(self.frozen_layers | set(names))
        self._invalidate_steps()
        return self

    def unfreeze(self, names: Optional[Sequence[str]] = None) -> "Layer":
        """Unfreeze the given layers (all if None) (``NetUtils.scala:87``)."""
        if names is None:
            self._frozen_layers = frozenset()
        else:
            if isinstance(names, str):
                names = [names]
            self._frozen_layers = frozenset(self.frozen_layers - set(names))
        self._invalidate_steps()
        return self

    def trainable_param_names(self) -> List[str]:
        return [n for n in self._param_layer_names()
                if n not in self.frozen_layers]

    def compile(self, optimizer, loss, metrics: Optional[List] = None):
        from . import objectives, optimizers as opt_mod
        from ..estimator.estimator import Estimator
        self.loss_fn = objectives.get(loss)
        self.optimizer = opt_mod.get(optimizer)
        self.metric_specs = [m for m in (metrics or [])]
        self._estimator: Optional["Estimator"] = None

    def _require_compiled(self):
        if not hasattr(self, "loss_fn"):
            raise RuntimeError("call compile(optimizer, loss) before fit/evaluate")

    def get_estimator(self):
        from ..estimator.estimator import Estimator
        self._require_compiled()
        if self._estimator is None:
            self._estimator = Estimator(
                model=self, loss_fn=self.loss_fn, optimizer=self.optimizer,
                metrics=self.metric_specs)
        return self._estimator

    def set_tensorboard(self, log_dir: str, app_name: str) -> None:
        self._tb = (log_dir, app_name)

    def _read_summary(self, split: str, tag: str):
        import os
        if not hasattr(self, "_tb"):
            raise RuntimeError("call set_tensorboard(log_dir, app_name) "
                               "before reading summaries")
        from ..utils.tensorboard import read_scalars
        log_dir, app = self._tb
        return read_scalars(os.path.join(log_dir, app, split), tag)

    def get_train_summary(self, tag: str = "Loss"):
        """Read back training scalars as ``[(step, value), ...]`` (reference
        ``KerasNet.getTrainSummary``, Topology.scala:222-224; tags: Loss,
        LearningRate, Throughput)."""
        return self._read_summary("train", tag)

    def get_validation_summary(self, tag: str):
        """Validation scalars per metric name (reference
        ``getValidationSummary``, Topology.scala:232-238)."""
        return self._read_summary("validation", tag)

    def set_checkpoint(self, path: str, trigger=None) -> None:
        self._ckpt = (path, trigger)

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float) -> None:
        self._clip = ("l2", clip_norm)

    def set_constant_gradient_clipping(self, min_value: float, max_value: float) -> None:
        self._clip = ("const", (min_value, max_value))

    def fit(self, x, y=None, batch_size=32, nb_epoch=1, validation_data=None,
            featureset=None, **kwargs):
        # keras-2 callers say epochs=, keras-1 (the reference) says nb_epoch=
        nb_epoch = kwargs.pop("epochs", nb_epoch)
        est = self.get_estimator()
        for attr, setter in (("_tb", "set_tensorboard"), ("_ckpt", "set_checkpoint"),
                             ("_clip", "set_gradient_clipping")):
            if hasattr(self, attr):
                getattr(est, setter)(*getattr(self, attr)) if attr != "_clip" \
                    else est.set_gradient_clipping(getattr(self, attr))
        from ..feature import FeatureSet
        from ..feature.featureset import HostDataset
        if featureset is None:
            featureset = x if isinstance(x, HostDataset) \
                else FeatureSet.from_ndarrays(x, y)
        if validation_data is not None and not isinstance(
                validation_data, HostDataset):
            validation_data = FeatureSet.from_ndarrays(*validation_data)
        return est.train(featureset, batch_size=batch_size, epochs=nb_epoch,
                         validation_set=validation_data, **kwargs)

    def evaluate(self, x, y=None, batch_size=32, featureset=None):
        est = self.get_estimator()
        from ..feature import FeatureSet
        from ..feature.featureset import HostDataset
        if featureset is None:
            featureset = x if isinstance(x, HostDataset) \
                else FeatureSet.from_ndarrays(x, y)
        return est.evaluate(featureset, batch_size=batch_size)

    def predict(self, x, batch_size=32, distributed: bool = True):
        est = self.get_estimator()
        return est.predict(x, batch_size=batch_size)

    def predict_classes(self, x, batch_size=32, zero_based_label=True):
        """Hard class predictions (reference ``predict_classes``,
        topology.py:329): argmax over the final axis for categorical
        outputs, elementwise 0.5-threshold for single-channel outputs
        (trailing singleton squeezed); ``zero_based_label=False`` shifts
        labels to start at 1."""
        probs = self.predict(x, batch_size=batch_size)
        if isinstance(probs, (list, tuple)):
            raise ValueError(
                "predict_classes is ambiguous for multi-output models; "
                "call predict() and decode each output yourself")
        probs = np.asarray(probs)
        if probs.ndim > 1 and probs.shape[-1] > 1:
            classes = probs.argmax(axis=-1)
        else:
            if probs.ndim > 1 and probs.shape[-1] == 1:
                probs = probs[..., 0]
            classes = (probs > 0.5).astype(np.int64)
        return classes if zero_based_label else classes + 1

    def get_weights(self):
        est = self.get_estimator()
        return est.get_params()

    def set_weights(self, params):
        est = self.get_estimator()
        est.set_params(params)

    def save_model(self, path: str) -> None:
        est = self.get_estimator()
        if est.params is None:
            # a fresh (never fit/predicted) model still saves: materialize
            # the deterministic init params so the checkpoint restores with
            # the same structure a trained one has
            shape = getattr(self, "built_shape", None)
            if isinstance(self, Model):
                params, state = self.build(jax.random.PRNGKey(0))
            elif shape is not None:
                params, state = self.build(jax.random.PRNGKey(0), shape)
            else:
                raise ValueError(
                    "save_model on an unbuilt Sequential — run "
                    "fit/predict once (or build(rng, input_shape)) so the "
                    "parameter shapes are known")
            est.set_params(params)
            est.set_model_state(state)
        est.save_checkpoint(path)

    def load_weights(self, path: str) -> None:
        self.get_estimator().load_checkpoint(path)

    def summary(self, input_shape=None, print_fn=print) -> str:
        """Keras-style layer/shape/param table (reference
        ``KerasNet.summary``, Topology.scala:138). For a Sequential not yet
        built, pass ``input_shape`` (without the batch dim)."""
        if isinstance(self, Model):
            layers = [n.layer for n in self._nodes]
            shape = None
        else:
            layers = list(getattr(self, "layers", []))
            shape = ((None,) + tuple(input_shape) if input_shape is not None
                     else self.built_shape)
            if shape is None:
                raise ValueError("summary() on an unbuilt Sequential needs "
                                 "input_shape")
        # abstract build — a failure here is a real model bug and must
        # surface, not render as an all-zero table
        out = jax.eval_shape(
            lambda r: (self.build(r, shape) if shape is not None
                       else self.build(r)), jax.random.PRNGKey(0))
        param_shapes = out[0]

        def count(tree):
            return sum(int(np.prod(l.shape)) for l in
                       jax.tree_util.tree_leaves(tree))

        frozen = self.frozen_layers
        lines = [f"{'Layer (type)':<34}{'Output shape':<22}{'Params':>10}",
                 "-" * 66]
        total = trainable = 0
        counted = set()  # a shared layer's params count once
        cur = shape
        for layer in layers:
            if isinstance(layer, InputLayer):
                continue
            if isinstance(self, Model):
                if id(layer) in counted:
                    continue  # graph dedup: one row per shared layer
                out_shape = ""  # graph layers: shapes live on the symbols
            else:
                # Sequential chains shapes through EVERY application,
                # including repeats of a shared layer
                cur = layer.compute_output_shape(cur)
                out_shape = str(cur)
            n = count(param_shapes.get(layer.name, {}))
            if id(layer) in counted:
                n = 0  # shown again, but params already counted
            counted.add(id(layer))
            total += n
            if layer.name not in frozen:
                trainable += n
            mark = " (frozen)" if layer.name in frozen and n else ""
            lines.append(f"{layer.name + ' (' + type(layer).__name__ + ')':<34}"
                         f"{out_shape:<22}{n:>10,}{mark}")
        lines += ["-" * 66,
                  f"Total params: {total:,}   trainable: {trainable:,}   "
                  f"frozen: {total - trainable:,}"]
        text = "\n".join(lines)
        if print_fn is not None:
            print_fn(text)
        return text


class Sequential(Layer, _TrainableMixin):
    """Linear stack of layers (reference ``Sequential``, Topology.scala:464)."""

    def __init__(self, layers: Optional[Sequence[Layer]] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.layers: List[Layer] = []
        for l in (layers or []):
            self.add(l)

    def add(self, layer: Layer) -> "Sequential":
        self.layers.append(layer)
        _scope_names(self.layers)
        self._param_names_cache = None  # freeze API must see the new layer
        return self

    def build(self, rng, input_shape):
        params, state = {}, {}
        shape = input_shape
        for layer in self.layers:
            rng, sub = jax.random.split(rng)
            p, s = layer.build(sub, shape)
            if p:
                params[layer.name] = p
            if s:
                state[layer.name] = s
            layer.built_shape = shape
            shape = layer.compute_output_shape(shape)
        self.built_shape = input_shape
        self._output_shape = shape
        return params, state

    def call(self, params, state, inputs, *, training=False, rng=None):
        x = inputs
        new_state = dict(state)
        for layer in self.layers:
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            x, s = layer.call(params.get(layer.name, {}),
                              state.get(layer.name, {}), x,
                              training=training, rng=sub)
            if s:
                new_state[layer.name] = s
        return x, new_state

    def compute_output_shape(self, input_shape):
        shape = input_shape
        for layer in self.layers:
            shape = layer.compute_output_shape(shape)
        return shape


class Model(Layer, _TrainableMixin):
    """Functional graph model (reference ``Model``, Topology.scala:678)."""

    def __init__(self, inputs, outputs, name: Optional[str] = None):
        super().__init__(name)
        self.inputs: List[SymbolicTensor] = (
            list(inputs) if isinstance(inputs, (list, tuple)) else [inputs])
        self.outputs: List[SymbolicTensor] = (
            list(outputs) if isinstance(outputs, (list, tuple)) else [outputs])
        self._single_output = not isinstance(outputs, (list, tuple))
        self._nodes = self._topo_sort()
        _scope_names([n.layer for n in self._nodes])

    def _topo_sort(self) -> List[Node]:
        order: List[Node] = []
        seen = set()

        def visit(node: Node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for sym in node.inputs:
                if sym.node is not None:
                    visit(sym.node)
            order.append(node)

        for out in self.outputs:
            visit(out.node)
        return order

    def build(self, rng, input_shape=None):
        params, state = {}, {}
        built = set()
        for node in self._nodes:
            layer = node.layer
            if layer.name in built or isinstance(layer, InputLayer):
                continue
            in_shapes = [s.shape for s in node.inputs]
            shape_arg = in_shapes[0] if len(in_shapes) == 1 else in_shapes
            rng, sub = jax.random.split(rng)
            p, s = layer.build(sub, shape_arg)
            if p:
                params[layer.name] = p
            if s:
                state[layer.name] = s
            layer.built_shape = shape_arg
            built.add(layer.name)
        self.built_shape = input_shape
        return params, state

    def call(self, params, state, inputs, *, training=False, rng=None):
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if len(xs) != len(self.inputs):
            raise ValueError(f"model expects {len(self.inputs)} inputs, got {len(xs)}")
        values: Dict[int, Any] = {}
        for sym, x in zip(self.inputs, xs):
            values[id(sym.node)] = (x,)
        new_state = dict(state)
        for node in self._nodes:
            if id(node) in values:
                continue
            layer = node.layer
            args = [values[id(s.node)][s.index] for s in node.inputs]
            arg = args[0] if len(args) == 1 else args
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            out, s = layer.call(params.get(layer.name, {}),
                                state.get(layer.name, {}), arg,
                                training=training, rng=sub)
            if s:
                new_state[layer.name] = s
            values[id(node)] = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        outs = [values[id(o.node)][o.index] for o in self.outputs]
        return (outs[0] if self._single_output else outs), new_state

    def compute_output_shape(self, input_shape):
        shapes = [o.shape for o in self.outputs]
        return shapes[0] if self._single_output else shapes

    # -- graph surgery (reference GraphNet, NetUtils.scala:29) ----------------

    def flattened_layers(self) -> List[Layer]:
        """All layers in topological order (reference ``flattenedLayers``)."""
        return [n.layer for n in self._nodes]

    def _node_by_layer_name(self, name: str) -> Node:
        for node in self._nodes:
            if node.layer.name == name:
                return node
        raise KeyError(f"no layer named '{name}'; have "
                       f"{[n.layer.name for n in self._nodes]}")

    def new_graph(self, outputs: Union[str, Sequence[str]]) -> "Model":
        """Truncate to a new Model whose outputs are the named layers'
        outputs (reference ``newGraph``, NetUtils.scala:45). Layer names are
        shared, so a params tree built for the original model works on the
        truncated one (extra keys are simply unused)."""
        names = [outputs] if isinstance(outputs, str) else list(outputs)
        out_syms = []
        for name in names:
            node = self._node_by_layer_name(name)
            shape = node.layer.compute_output_shape(
                node.inputs[0].shape if len(node.inputs) == 1
                else [s.shape for s in node.inputs])
            if isinstance(shape, list):  # multi-output layer: take first
                shape = shape[0]
            out_syms.append(SymbolicTensor(tuple(shape), node, 0))
        model = Model(self.inputs,
                      out_syms if len(out_syms) > 1 else out_syms[0])
        model._frozen_layers = frozenset(
            self.frozen_layers & {n.layer.name for n in model._nodes})
        return model

    def freeze_up_to(self, names: Union[str, Sequence[str]]) -> "Model":
        """Freeze every layer from the inputs up to and including the named
        layers (reference ``freezeUpTo``, NetUtils.scala:95)."""
        names = [names] if isinstance(names, str) else list(names)
        seen: set = set()

        def visit(node: Node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for sym in node.inputs:
                if sym.node is not None:
                    visit(sym.node)

        frozen_names = []
        for name in names:
            visit(self._node_by_layer_name(name))
        for node in self._nodes:
            if id(node) in seen and not isinstance(node.layer, InputLayer):
                frozen_names.append(node.layer.name)
        param_names = set(self._param_layer_names())
        return self.freeze([n for n in frozen_names if n in param_names])


def init_model(model: Layer, rng: jax.Array, sample_input) -> Tuple[Any, Any]:
    """Build params/state from a concrete sample input (shape inference)."""
    def shape_of(x):
        a = np.asarray(x)
        return (None,) + a.shape[1:]
    if isinstance(sample_input, (list, tuple)):
        shape = [shape_of(x) for x in sample_input]
        if len(shape) == 1:
            shape = shape[0]
    elif isinstance(sample_input, dict):
        shape = {k: shape_of(v) for k, v in sample_input.items()}
    else:
        shape = shape_of(sample_input)
    return model.build(rng, shape)
