"""Loss objectives (reference: ``pipeline/api/keras/objectives/`` — 16 losses).

Every loss is ``fn(y_true, y_pred) -> scalar`` (mean over the batch), pure and
jit-safe. Classification losses operate on probabilities by default (matching
the reference's Keras-1 contract) with ``from_logits`` variants where numeric
stability on TPU wants the fused log-softmax form.
"""
from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

_EPS = 1e-7


def _clip(p):
    return jnp.clip(p, _EPS, 1.0 - _EPS)


def mean_squared_error(y_true, y_pred):
    return jnp.mean(jnp.square(y_pred - y_true))


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(y_pred - y_true))


def mean_absolute_percentage_error(y_true, y_pred):
    diff = jnp.abs((y_true - y_pred) / jnp.clip(jnp.abs(y_true), _EPS, None))
    return 100.0 * jnp.mean(diff)


def mean_squared_logarithmic_error(y_true, y_pred):
    a = jnp.log(jnp.clip(y_pred, _EPS, None) + 1.0)
    b = jnp.log(jnp.clip(y_true, _EPS, None) + 1.0)
    return jnp.mean(jnp.square(a - b))


def binary_crossentropy(y_true, y_pred):
    p = _clip(y_pred)
    return -jnp.mean(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log(1.0 - p))


def binary_crossentropy_from_logits(y_true, y_pred):
    return jnp.mean(jnp.maximum(y_pred, 0) - y_pred * y_true
                    + jnp.log1p(jnp.exp(-jnp.abs(y_pred))))


def categorical_crossentropy(y_true, y_pred):
    return -jnp.mean(jnp.sum(y_true * jnp.log(_clip(y_pred)), axis=-1))


def categorical_crossentropy_from_logits(y_true, y_pred):
    return -jnp.mean(jnp.sum(y_true * jax.nn.log_softmax(y_pred, axis=-1), axis=-1))


def sparse_categorical_crossentropy(y_true, y_pred):
    idx = y_true.astype(jnp.int32)
    logp = jnp.log(_clip(y_pred))
    return -jnp.mean(jnp.take_along_axis(logp, idx[..., None], axis=-1))


def sparse_categorical_crossentropy_from_logits(y_true, y_pred):
    idx = y_true.astype(jnp.int32)
    logp = jax.nn.log_softmax(y_pred, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, idx[..., None], axis=-1))


def kullback_leibler_divergence(y_true, y_pred):
    p = _clip(y_true)
    q = _clip(y_pred)
    return jnp.mean(jnp.sum(p * jnp.log(p / q), axis=-1))


def poisson(y_true, y_pred):
    return jnp.mean(y_pred - y_true * jnp.log(y_pred + _EPS))


def cosine_proximity(y_true, y_pred):
    a = y_true / (jnp.linalg.norm(y_true, axis=-1, keepdims=True) + _EPS)
    b = y_pred / (jnp.linalg.norm(y_pred, axis=-1, keepdims=True) + _EPS)
    return -jnp.mean(jnp.sum(a * b, axis=-1))


def hinge(y_true, y_pred):
    return jnp.mean(jnp.maximum(1.0 - y_true * y_pred, 0.0))


def squared_hinge(y_true, y_pred):
    return jnp.mean(jnp.square(jnp.maximum(1.0 - y_true * y_pred, 0.0)))


def rank_hinge(y_true, y_pred, margin: float = 1.0):
    """Pairwise ranking hinge for text matching (reference ``RankHinge.scala``):
    consecutive (positive, negative) pairs along the batch axis."""
    pos = y_pred[0::2]
    neg = y_pred[1::2]
    return jnp.mean(jnp.maximum(margin - pos + neg, 0.0))


def log_cosh(y_true, y_pred):
    d = y_pred - y_true
    return jnp.mean(d + jax.nn.softplus(-2.0 * d) - jnp.log(2.0))


def huber(y_true, y_pred, delta: float = 1.0):
    d = jnp.abs(y_pred - y_true)
    quad = jnp.minimum(d, delta)
    return jnp.mean(0.5 * quad ** 2 + delta * (d - quad))


_REGISTRY = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "binary_crossentropy": binary_crossentropy,
    "binary_crossentropy_from_logits": binary_crossentropy_from_logits,
    "categorical_crossentropy": categorical_crossentropy,
    "categorical_crossentropy_from_logits": categorical_crossentropy_from_logits,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_from_logits":
        sparse_categorical_crossentropy_from_logits,
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "rank_hinge": rank_hinge,
    "log_cosh": log_cosh,
    "huber": huber,
}


def get(loss: Union[str, Callable]) -> Callable:
    if callable(loss):
        return loss
    if loss not in _REGISTRY:
        raise ValueError(f"unknown loss '{loss}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[loss]
