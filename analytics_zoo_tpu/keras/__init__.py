from .engine import (  # noqa: F401
    Input, InputLayer, Layer, Model, Sequential, SymbolicTensor, init_model)
from . import initializers, layers, metrics, objectives, optimizers  # noqa: F401
