"""ONNX graph → native Keras-engine ``Model`` (+ params/state pytrees).

Plays the role of the reference's ONNX loader
(``pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:1`` +
``onnx/ops_mapping.py``), but instead of building a BigDL graph it emits the
functional JAX ``Model`` from :mod:`analytics_zoo_tpu.keras.engine` with a
ready-made parameter tree, so an imported network drops straight into the
Estimator/fine-tuning path.

TPU-first layout policy: ONNX is NCHW; TPU convs want NHWC. Rather than
wrapping every conv in transposes, the importer converts the *graph* once —
4-D inputs become NHWC, conv kernels are permuted OIHW→HWIO, and a
Flatten-then-Gemm boundary permutes the Gemm kernel rows so results match the
original bit-for-bit (up to float assoc).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import onnx_wire as wire
from ..common import file_io


class _Value:
    """A tensor flowing through the import: symbolic or constant."""

    def __init__(self, sym=None, const: Optional[np.ndarray] = None,
                 layout: Optional[str] = None,
                 nhwc_shape: Optional[Tuple[int, int, int]] = None):
        self.sym = sym              # engine SymbolicTensor (runtime tensor)
        self.const = const          # numpy constant (initializer/Constant op)
        self.layout = layout        # 'nhwc' = converted from NCHW 4-D
        self.nhwc_shape = nhwc_shape  # (h, w, c) just before a flatten


class OnnxLoaderError(ValueError):
    pass


def _auto(node: Dict[str, Any], prefix: str, idx: int) -> str:
    name = node.get("name") or ""
    if name:
        # keep ONNX names but make them identifier-ish (param-tree keys)
        return name.replace("/", "_").replace(":", "_").replace(".", "_")
    return f"{prefix}_{idx}"


def _pads_4(attrs) -> Tuple[int, int, int, int]:
    pads = attrs.get("pads") or [0, 0, 0, 0]
    if len(pads) == 2:  # 1-D op
        return pads[0], 0, pads[1], 0
    return tuple(pads)  # (h_begin, w_begin, h_end, w_end)


def _same_lower_pads(in_hw, kernel, strides, dilations=(1, 1)
                     ) -> Tuple[int, int, int, int]:
    """Explicit pads for ONNX auto_pad=SAME_LOWER (extra pad at the BEGIN
    side; XLA's "SAME" is SAME_UPPER, so this must be materialized)."""
    out = []
    for size, k, s, d in zip(in_hw, kernel, strides, dilations):
        eff_k = (k - 1) * d + 1
        total = max((-(-size // s) - 1) * s + eff_k - size, 0)
        out.append((total - total // 2, total // 2))  # (begin>=end)
    (h0, h1), (w0, w1) = out
    return h0, w0, h1, w1


def _permute_flat_kernel(kernel: np.ndarray,
                         nhwc_shape: Tuple[int, int, int]) -> np.ndarray:
    """Reorder a Gemm/MatMul kernel's input rows from ONNX's (c,h,w) flat
    order to the converted graph's (h,w,c) flat order."""
    h, w, ch = nhwc_shape
    perm = np.arange(ch * h * w).reshape(ch, h, w).transpose(1, 2, 0)
    return kernel[perm.reshape(-1), :]


class _GraphBuilder:
    def __init__(self, graph: Dict[str, Any], dtype=np.float32,
                 attr_fn=None):
        self.graph = graph
        self.dtype = dtype
        # attribute decoder: wire-format by default; the caffe frontend
        # injects already-decoded attr dicts instead
        self.attr_fn = attr_fn if attr_fn is not None else wire.attributes
        self.values: Dict[str, _Value] = {}
        self.params: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {}
        self.inputs: List[Any] = []

    # -- helpers -----------------------------------------------------------

    def val(self, name: str) -> _Value:
        if name not in self.values:
            raise OnnxLoaderError(f"tensor '{name}' referenced before defined")
        return self.values[name]

    def const(self, name: str) -> np.ndarray:
        v = self.val(name)
        if v.const is None:
            raise OnnxLoaderError(
                f"tensor '{name}' must be a constant/initializer for this op")
        return v.const

    def sym(self, name: str):
        v = self.val(name)
        if v.sym is None:
            raise OnnxLoaderError(f"tensor '{name}' is a constant where a "
                                  f"runtime tensor was expected")
        return v.sym

    def set(self, name: str, value: _Value) -> None:
        self.values[name] = value

    def add_params(self, layer_name: str, p: Dict[str, Any],
                   s: Optional[Dict[str, Any]] = None) -> None:
        self.params[layer_name] = {k: np.asarray(v, dtype=self.dtype)
                                   for k, v in p.items()}
        if s:
            self.state[layer_name] = {k: np.asarray(v, dtype=self.dtype)
                                      for k, v in s.items()}

    # -- graph walk --------------------------------------------------------

    def build(self) -> Tuple[Any, Dict[str, Any], Dict[str, Any]]:
        from ..keras.engine import Input, Model

        for init in self.graph.get("initializer", []):
            self.values[init["name"]] = _Value(const=wire.tensor_to_numpy(init))

        input_syms = []
        for vi in self.graph.get("input", []):
            name = vi["name"]
            if name in self.values:  # initializer doubling as graph input
                continue
            shape = wire.value_info_shape(vi)
            if len(shape) == 4:
                n, c, h, w = shape
                sym = Input(shape=(h, w, c), name=f"input_{name}")
                self.set(name, _Value(sym=sym, layout="nhwc"))
            else:
                sym = Input(shape=tuple(shape[1:]), name=f"input_{name}")
                self.set(name, _Value(sym=sym))
            input_syms.append(sym)
        if not input_syms:
            raise OnnxLoaderError("ONNX graph has no runtime inputs")

        for i, node in enumerate(self.graph.get("node", [])):
            op = node.get("op_type", "")
            handler = getattr(self, f"op_{op.lower()}", None)
            if handler is None:
                raise OnnxLoaderError(
                    f"unsupported ONNX op '{op}' (node {node.get('name') or i})")
            handler(node, self.attr_fn(node), _auto(node, op.lower(), i))

        outs = []
        for vi in self.graph.get("output", []):
            outs.append(self.sym(vi["name"]))
        model = Model(input_syms, outs if len(outs) > 1 else outs[0])
        return model, self.params, self.state

    # -- op handlers -------------------------------------------------------

    def _set_out(self, node, sym, layout=None, nhwc_shape=None):
        self.set(node["output"][0], _Value(sym=sym, layout=layout,
                                           nhwc_shape=nhwc_shape))

    def op_gemm(self, node, attrs, name):
        from ..keras.layers import Dense
        a = self.val(node["input"][0])
        b = self.const(node["input"][1])
        c = (self.const(node["input"][2])
             if len(node["input"]) > 2 else None)
        if attrs.get("transA"):
            raise OnnxLoaderError("Gemm with transA on a runtime tensor")
        kernel = b.T if attrs.get("transB") else b
        alpha = attrs["alpha"] if attrs.get("alpha") is not None else 1.0
        beta = attrs["beta"] if attrs.get("beta") is not None else 1.0
        kernel = kernel * alpha
        if a.nhwc_shape is not None:
            # data was flattened from converted-NHWC; reorder kernel rows
            kernel = _permute_flat_kernel(kernel, a.nhwc_shape)
        layer = Dense(kernel.shape[1], bias=c is not None, name=name)
        p = {"kernel": kernel}
        if c is not None:
            p["bias"] = np.reshape(c * beta, (-1,))
        self.add_params(name, p)
        self._set_out(node, layer(a.sym))

    def op_matmul(self, node, attrs, name):
        from ..keras.layers import Dense, Lambda, merge
        a, b = self.val(node["input"][0]), self.val(node["input"][1])
        if b.const is not None and b.const.ndim == 2:
            layer = Dense(b.const.shape[1], bias=False, name=name)
            kernel = b.const
            if a.nhwc_shape is not None:
                kernel = _permute_flat_kernel(kernel, a.nhwc_shape)
            self.add_params(name, {"kernel": kernel})
            self._set_out(node, layer(a.sym))
        elif a.sym is not None and b.sym is not None:
            import jax.numpy as jnp
            out = Lambda(lambda xs: jnp.matmul(xs[0], xs[1]), name=name)(
                [a.sym, b.sym])
            self._set_out(node, out)
        else:
            raise OnnxLoaderError("MatMul operand combination unsupported")

    def _binary(self, node, name, mode, fn):
        from ..keras.layers import Lambda, merge
        a, b = self.val(node["input"][0]), self.val(node["input"][1])
        if a.const is not None and b.const is not None:
            # exporter left an un-folded constant expression: fold it here
            self.set(node["output"][0], _Value(const=fn(a.const, b.const)))
            return
        if a.sym is not None and b.sym is not None:
            if mode is not None:
                self._set_out(node, merge([a.sym, b.sym], mode=mode, name=name),
                              layout=a.layout, nhwc_shape=a.nhwc_shape)
                return
            out = Lambda(lambda xs: fn(xs[0], xs[1]), name=name)([a.sym, b.sym])
            self._set_out(node, out, layout=a.layout, nhwc_shape=a.nhwc_shape)
            return
        # one side constant: captured as an XLA literal (non-trainable)
        v, const = (a, b.const) if a.sym is not None else (b, a.const)
        if v.layout == "nhwc" and const.ndim >= 3:
            # move the channel axis of an NCHW-broadcast constant to the end
            const = np.moveaxis(const, -3, -1)
        cst = np.asarray(const, dtype=self.dtype)
        if a.sym is not None:
            out = Lambda(lambda x, c=cst: fn(x, c), name=name)(v.sym)
        else:
            out = Lambda(lambda x, c=cst: fn(c, x), name=name)(v.sym)
        self._set_out(node, out, layout=v.layout, nhwc_shape=v.nhwc_shape)

    def op_add(self, node, attrs, name):
        self._binary(node, name, "sum", lambda x, y: x + y)

    def op_sum(self, node, attrs, name):
        from ..keras.layers import merge
        syms = [self.sym(i) for i in node["input"]]
        v0 = self.val(node["input"][0])
        self._set_out(node, merge(syms, mode="sum", name=name),
                      layout=v0.layout, nhwc_shape=v0.nhwc_shape)

    def op_sub(self, node, attrs, name):
        self._binary(node, name, None, lambda x, y: x - y)

    def op_mul(self, node, attrs, name):
        self._binary(node, name, "mul", lambda x, y: x * y)

    def op_div(self, node, attrs, name):
        self._binary(node, name, None, lambda x, y: x / y)

    def op_pow(self, node, attrs, name):
        self._binary(node, name, None, lambda x, y: x ** y)

    def _activation(self, node, name, act):
        from ..keras.layers import Activation
        v = self.val(node["input"][0])
        self._set_out(node, Activation(act, name=name)(v.sym),
                      layout=v.layout, nhwc_shape=v.nhwc_shape)

    def op_relu(self, node, attrs, name):
        self._activation(node, name, "relu")

    def op_sigmoid(self, node, attrs, name):
        self._activation(node, name, "sigmoid")

    def op_tanh(self, node, attrs, name):
        self._activation(node, name, "tanh")

    def op_softmax(self, node, attrs, name):
        self._activation(node, name, "softmax")

    def op_exp(self, node, attrs, name):
        self._activation(node, name, "exp")

    def op_identity(self, node, attrs, name):
        self.set(node["output"][0], self.val(node["input"][0]))

    def op_cast(self, node, attrs, name):
        self.set(node["output"][0], self.val(node["input"][0]))

    def op_dropout(self, node, attrs, name):
        from ..keras.layers import Dropout
        v = self.val(node["input"][0])
        ratio = attrs.get("ratio")
        if ratio is None and len(node["input"]) > 1 and node["input"][1]:
            ratio = float(self.const(node["input"][1]))  # opset >= 12
        if ratio is None:
            ratio = 0.5
        out = Dropout(float(ratio), name=name)(v.sym)
        self.set(node["output"][0], _Value(sym=out, layout=v.layout,
                                           nhwc_shape=v.nhwc_shape))

    def op_leakyrelu(self, node, attrs, name):
        from ..keras.layers import LeakyReLU
        v = self.val(node["input"][0])
        self._set_out(node, LeakyReLU(attrs["alpha"] if attrs.get("alpha") is not None else 0.01,
                                  name=name)(v.sym),
                      layout=v.layout, nhwc_shape=v.nhwc_shape)

    def op_elu(self, node, attrs, name):
        from ..keras.layers import ELU
        v = self.val(node["input"][0])
        self._set_out(node, ELU(attrs["alpha"] if attrs.get("alpha") is not None else 1.0,
                                 name=name)(v.sym),
                      layout=v.layout, nhwc_shape=v.nhwc_shape)

    def op_clip(self, node, attrs, name):
        from ..keras.layers import Lambda
        import jax.numpy as jnp
        lo = attrs.get("min")
        hi = attrs.get("max")
        if lo is None and len(node["input"]) > 1 and node["input"][1]:
            lo = float(self.const(node["input"][1]))
        if hi is None and len(node["input"]) > 2 and node["input"][2]:
            hi = float(self.const(node["input"][2]))
        v = self.val(node["input"][0])
        out = Lambda(lambda x: jnp.clip(x, lo, hi), name=name)(v.sym)
        self._set_out(node, out, layout=v.layout, nhwc_shape=v.nhwc_shape)

    def op_pad(self, node, attrs, name):
        from ..keras.layers import Lambda
        import jax.numpy as jnp
        v = self.val(node["input"][0])
        pads = attrs.get("pads")
        if pads is None and len(node["input"]) > 1:
            pads = [int(x) for x in self.const(node["input"][1]).reshape(-1)]
        if pads is None:
            raise OnnxLoaderError("Pad without pads")
        mode = attrs.get("mode") or "constant"
        if isinstance(mode, bytes):
            mode = mode.decode()
        if mode != "constant":
            raise OnnxLoaderError(f"Pad mode {mode!r} unsupported")
        value = float(attrs.get("value") or 0.0)
        if len(node["input"]) > 2 and node["input"][2]:
            value = float(self.const(node["input"][2]))  # opset >= 11
        ndim = len(pads) // 2
        begins, ends = pads[:ndim], pads[ndim:]
        if v.layout == "nhwc" and ndim == 4:
            # pads arrive in NCHW axis order; the tensor is NHWC now
            n, c, h, w = range(4)
            spec = ((begins[n], ends[n]), (begins[h], ends[h]),
                    (begins[w], ends[w]), (begins[c], ends[c]))
        else:
            spec = tuple(zip(begins, ends))
        out = Lambda(lambda x: jnp.pad(x, spec, constant_values=value),
                     name=name)(v.sym)
        self._set_out(node, out, layout=v.layout)

    def op_constant(self, node, attrs, name):
        t = attrs.get("value")
        if t is None:
            raise OnnxLoaderError("Constant node without a tensor value")
        self.set(node["output"][0], _Value(const=np.asarray(t)))

    def op_conv(self, node, attrs, name):
        from ..keras.engine import SymbolicTensor
        from ..keras.layers import Convolution2D, Lambda
        v = self.val(node["input"][0])
        w = self.const(node["input"][1])  # OIHW
        b = self.const(node["input"][2]) if len(node["input"]) > 2 else None
        if w.ndim != 4:
            raise OnnxLoaderError("only 2-D Conv supported")
        strides = tuple(attrs.get("strides") or (1, 1))
        dil = tuple(attrs.get("dilations") or (1, 1))
        groups = int(attrs.get("group") or 1)
        h0, w0, h1, w1 = _pads_4(attrs)
        sym = v.sym
        auto_pad = attrs.get("auto_pad")
        if auto_pad == "SAME_UPPER":
            border = "same"  # XLA SAME == ONNX SAME_UPPER
        else:
            if auto_pad == "SAME_LOWER":
                h0, w0, h1, w1 = _same_lower_pads(
                    v.sym.shape[1:3], (w.shape[2], w.shape[3]), strides, dil)
            border = "valid"
            if any((h0, w0, h1, w1)):
                import jax.numpy as jnp
                sym = Lambda(lambda x: jnp.pad(
                    x, ((0, 0), (h0, h1), (w0, w1), (0, 0))),
                    name=f"{name}_pad")(sym)
        layer = Convolution2D(w.shape[0], w.shape[2], w.shape[3],
                              subsample=strides, border_mode=border,
                              bias=b is not None, dilation=dil, groups=groups,
                              name=name)
        p = {"kernel": np.transpose(w, (2, 3, 1, 0))}  # OIHW → HWIO
        if b is not None:
            p["bias"] = b
        self.add_params(name, p)
        self._set_out(node, layer(sym), layout="nhwc")

    def op_batchnormalization(self, node, attrs, name):
        from ..keras.layers import BatchNormalization
        v = self.val(node["input"][0])
        scale = self.const(node["input"][1])
        bias = self.const(node["input"][2])
        mean = self.const(node["input"][3])
        var = self.const(node["input"][4])
        layer = BatchNormalization(
            epsilon=attrs["epsilon"] if attrs.get("epsilon") is not None else 1e-5,
            momentum=attrs["momentum"] if attrs.get("momentum") is not None
            else 0.9, axis=-1, name=name)
        self.add_params(name, {"gamma": scale, "beta": bias},
                        {"moving_mean": mean, "moving_var": var})
        self._set_out(node, layer(v.sym), layout=v.layout,
                      nhwc_shape=v.nhwc_shape)

    def _pool(self, node, attrs, name, cls):
        from ..keras.layers import Lambda
        v = self.val(node["input"][0])
        ks = tuple(attrs.get("kernel_shape") or (2, 2))
        strides = tuple(attrs.get("strides") or ks)
        h0, w0, h1, w1 = _pads_4(attrs)
        sym = v.sym
        border = "valid"
        auto_pad = attrs.get("auto_pad")
        if auto_pad == "SAME_UPPER":
            border = "same"  # XLA SAME == ONNX SAME_UPPER
        else:
            if auto_pad == "SAME_LOWER":
                h0, w0, h1, w1 = _same_lower_pads(v.sym.shape[1:3], ks,
                                                  strides)
            if any((h0, w0, h1, w1)):
                import jax.numpy as jnp
                fill = -np.inf if cls.__name__.startswith("Max") else 0.0
                sym = Lambda(lambda x: jnp.pad(
                    x, ((0, 0), (h0, h1), (w0, w1), (0, 0)),
                    constant_values=fill), name=f"{name}_pad")(sym)
        layer = cls(pool_size=ks, strides=strides, border_mode=border,
                    name=name)
        self._set_out(node, layer(sym), layout="nhwc")

    def op_maxpool(self, node, attrs, name):
        from ..keras.layers import MaxPooling2D
        self._pool(node, attrs, name, MaxPooling2D)

    def op_averagepool(self, node, attrs, name):
        from ..keras.layers import AveragePooling2D, Lambda
        h0, w0, h1, w1 = _pads_4(attrs)
        include_pad = bool(attrs.get("count_include_pad", 0))
        if any((h0, w0, h1, w1)) and not include_pad:
            # ONNX default excludes padding from the divisor: divide the
            # zero-padded window sum by a same-padded ones-mask window sum
            import jax.numpy as jnp
            from jax import lax
            v = self.val(node["input"][0])
            ks = tuple(attrs.get("kernel_shape") or (2, 2))
            strides = tuple(attrs.get("strides") or ks)

            def avg_excl_pad(x):
                xp = jnp.pad(x, ((0, 0), (h0, h1), (w0, w1), (0, 0)))
                mask = jnp.pad(jnp.ones_like(x), ((0, 0), (h0, h1),
                                                  (w0, w1), (0, 0)))
                dims, strd = (1, ks[0], ks[1], 1), (1,) + strides + (1,)
                s = lax.reduce_window(xp, 0.0, lax.add, dims, strd, "VALID")
                n = lax.reduce_window(mask, 0.0, lax.add, dims, strd, "VALID")
                return s / n

            self._set_out(node, Lambda(avg_excl_pad, name=name)(v.sym),
                          layout="nhwc")
            return
        self._pool(node, attrs, name, AveragePooling2D)

    def op_globalaveragepool(self, node, attrs, name):
        from ..keras.layers import GlobalAveragePooling2D
        v = self.val(node["input"][0])
        # ONNX keeps (N,C,1,1); downstream Flatten/Reshape collapses it — our
        # layer goes straight to (N,C), so mark the output already-flat
        self._set_out(node, GlobalAveragePooling2D(name=name)(v.sym))

    def op_globalmaxpool(self, node, attrs, name):
        from ..keras.layers import GlobalMaxPooling2D
        v = self.val(node["input"][0])
        self._set_out(node, GlobalMaxPooling2D(name=name)(v.sym))

    def op_flatten(self, node, attrs, name):
        from ..keras.layers import Flatten
        v = self.val(node["input"][0])
        if v.sym.shape is not None and len(v.sym.shape) == 2:
            self.set(node["output"][0], v)  # already flat (e.g. after GAP)
            return
        nhwc = None
        if v.layout == "nhwc" and len(v.sym.shape) == 4:
            _, h, w, c = v.sym.shape
            nhwc = (h, w, c)
        self._set_out(node, Flatten(name=name)(v.sym), nhwc_shape=nhwc)

    def op_reshape(self, node, attrs, name):
        from ..keras.layers import Flatten, Reshape
        v = self.val(node["input"][0])
        target = attrs.get("shape")
        if target is None and len(node["input"]) > 1:
            target = [int(x) for x in self.const(node["input"][1]).reshape(-1)]
        if target is None:
            raise OnnxLoaderError("Reshape without target shape")
        tail = list(target[1:])
        if len(tail) == 1:
            # flatten-style reshape; reject widths that would mix rows across
            # the batch axis (silently passing those through is worse than
            # failing the import)
            in_tail = v.sym.shape[1:]
            flat = (int(np.prod(in_tail))
                    if all(d is not None for d in in_tail) else None)
            if tail[0] != -1 and flat is not None and tail[0] != flat:
                raise OnnxLoaderError(
                    f"Reshape {v.sym.shape} -> (N, {tail[0]}) changes the "
                    f"per-record element count ({flat}); batch-mixing "
                    f"reshapes are unsupported")
            if len(v.sym.shape) == 2:
                self.set(node["output"][0], v)
                return
            nhwc = None
            if v.layout == "nhwc" and len(v.sym.shape) == 4:
                _, h, w, c = v.sym.shape
                nhwc = (h, w, c)
            self._set_out(node, Flatten(name=name)(v.sym), nhwc_shape=nhwc)
            return
        if v.layout == "nhwc":
            raise OnnxLoaderError(
                "general Reshape on an NCHW-converted tensor is ambiguous; "
                "only flatten-style reshapes are supported after convs")
        self._set_out(node, Reshape(tail, name=name)(v.sym))

    def op_concat(self, node, attrs, name):
        from ..keras.layers import merge
        vals = [self.val(i) for i in node["input"]]
        axis = int(attrs.get("axis") or 0)
        if all(v.const is not None for v in vals):
            # shape-arithmetic chains (Shape→Concat→Reshape) fold statically
            self.set(node["output"][0], _Value(const=np.concatenate(
                [np.atleast_1d(v.const) for v in vals], axis=axis)))
            return
        if vals[0].layout == "nhwc":
            # NCHW axes → NHWC: C(1)→3, H(2)→1, W(3)→2
            axis = {1: 3, 2: 1, 3: 2}.get(axis, axis)
        self._set_out(node, merge([v.sym for v in vals], mode="concat",
                                  concat_axis=axis, name=name),
                      layout=vals[0].layout)

    def op_transpose(self, node, attrs, name):
        from ..keras.layers import Permute
        v = self.val(node["input"][0])
        if v.layout == "nhwc":
            raise OnnxLoaderError("Transpose after conv conversion unsupported")
        perm = attrs.get("perm")
        if perm is None or perm[0] != 0:
            raise OnnxLoaderError("Transpose must keep the batch axis first")
        self._set_out(node, Permute([int(p) for p in perm[1:]], name=name)(v.sym))

    def op_unsqueeze(self, node, attrs, name):
        from ..keras.layers import ExpandDim
        v = self.val(node["input"][0])
        axes = attrs.get("axes")
        if axes is None and len(node["input"]) > 1:
            axes = [int(x) for x in self.const(node["input"][1]).reshape(-1)]
        if v.const is not None:
            self.set(node["output"][0],
                     _Value(const=np.expand_dims(v.const, tuple(axes))))
            return
        sym = v.sym
        for ax in sorted(axes):
            sym = ExpandDim(ax, name=f"{name}_{ax}")(sym)
        self._set_out(node, sym)

    def op_squeeze(self, node, attrs, name):
        from ..keras.layers import Squeeze
        v = self.val(node["input"][0])
        axes = attrs.get("axes")
        if axes is None and len(node["input"]) > 1:
            axes = [int(x) for x in self.const(node["input"][1]).reshape(-1)]
        if v.const is not None:
            self.set(node["output"][0],
                     _Value(const=np.squeeze(v.const, tuple(axes))))
            return
        sym = v.sym
        for ax in sorted(axes, reverse=True):
            sym = Squeeze(ax, name=f"{name}_{ax}")(sym)
        self._set_out(node, sym)

    def op_reducemean(self, node, attrs, name):
        from ..keras.layers import Lambda
        import jax.numpy as jnp
        v = self.val(node["input"][0])
        axes = tuple(attrs.get("axes") or ())
        keep = bool(attrs.get("keepdims", 1))
        if v.layout == "nhwc" and axes:
            # graph was converted NCHW→NHWC: remap axis 1(C)→3, 2(H)→1, 3(W)→2
            axes = tuple({1: 3, 2: 1, 3: 2}.get(a, a) for a in axes)
        out = Lambda(lambda x: jnp.mean(x, axis=axes or None, keepdims=keep),
                     name=name)(v.sym)
        # keepdims on a converted tensor stays NHWC; a full spatial reduce
        # without keepdims yields (N, C) — already flat, no layout to track
        layout = v.layout if (keep and v.layout == "nhwc") else None
        self._set_out(node, out, layout=layout)

    # -- additional elementwise / reduction / shape ops ---------------------

    def _unary_lambda(self, node, name, fn):
        v = self.val(node["input"][0])
        if v.const is not None:
            self.set(node["output"][0], _Value(const=np.asarray(fn(v.const))))
            return
        from ..keras.layers import Lambda
        self._set_out(node, Lambda(fn, name=name)(v.sym),
                      layout=v.layout, nhwc_shape=v.nhwc_shape)

    def op_abs(self, node, attrs, name):
        import jax.numpy as jnp
        self._unary_lambda(node, name, jnp.abs)

    def op_neg(self, node, attrs, name):
        import jax.numpy as jnp
        self._unary_lambda(node, name, jnp.negative)

    def op_sqrt(self, node, attrs, name):
        import jax.numpy as jnp
        self._unary_lambda(node, name, jnp.sqrt)

    def op_reciprocal(self, node, attrs, name):
        import jax.numpy as jnp
        self._unary_lambda(node, name, jnp.reciprocal)

    def op_erf(self, node, attrs, name):
        import jax
        self._unary_lambda(node, name, jax.scipy.special.erf)

    def op_floor(self, node, attrs, name):
        import jax.numpy as jnp
        self._unary_lambda(node, name, jnp.floor)

    def op_log(self, node, attrs, name):
        import jax.numpy as jnp
        self._unary_lambda(node, name, jnp.log)

    def op_hardsigmoid(self, node, attrs, name):
        import jax.numpy as jnp
        alpha = attrs["alpha"] if attrs.get("alpha") is not None else 0.2
        beta = attrs["beta"] if attrs.get("beta") is not None else 0.5
        self._unary_lambda(node, name,
                           lambda t: jnp.clip(alpha * t + beta, 0.0, 1.0))

    def op_prelu(self, node, attrs, name):
        from ..keras.layers import Lambda
        import jax.numpy as jnp
        v = self.val(node["input"][0])
        slope = self.const(node["input"][1]).astype(np.float32)
        if v.layout == "nhwc" and slope.ndim >= 3:
            slope = np.moveaxis(slope, -3, -1)  # channel axis to the end
        slope = np.squeeze(slope) if slope.size > 1 else slope.reshape(())
        out = Lambda(lambda t, s=slope: jnp.where(t >= 0, t, t * s),
                     name=name)(v.sym)
        self._set_out(node, out, layout=v.layout, nhwc_shape=v.nhwc_shape)

    def _nary_minmax(self, node, name, fn):
        from ..keras.layers import Lambda
        vals = [self.val(i) for i in node["input"]]
        if all(v.const is not None for v in vals):
            out = vals[0].const
            for v in vals[1:]:
                out = fn(out, v.const)
            self.set(node["output"][0], _Value(const=np.asarray(out)))
            return
        # mixed operands (e.g. Max(x, const) clip patterns): fold the
        # constants together, close them over the lambda
        syms = [v.sym for v in vals if v.sym is not None]
        consts = [v.const for v in vals if v.const is not None]
        cfold = None
        if consts:
            cfold = consts[0]
            for c in consts[1:]:
                cfold = fn(cfold, c)
            cfold = np.asarray(cfold, dtype=self.dtype)

        def apply(xs, c=cfold):
            xs = xs if isinstance(xs, (list, tuple)) else [xs]
            out = xs[0]
            for x in xs[1:]:
                out = fn(out, x)
            if c is not None:
                out = fn(out, c)
            return out
        ref = next(v for v in vals if v.sym is not None)
        self._set_out(node, Lambda(apply, name=name)(
            syms if len(syms) > 1 else syms[0]),
            layout=ref.layout, nhwc_shape=ref.nhwc_shape)

    def op_min(self, node, attrs, name):
        import jax.numpy as jnp
        self._nary_minmax(node, name, jnp.minimum)

    def op_max(self, node, attrs, name):
        import jax.numpy as jnp
        self._nary_minmax(node, name, jnp.maximum)

    def _reduce_op(self, node, attrs, name, fn):
        from ..keras.layers import Lambda
        v = self.val(node["input"][0])
        axes = attrs.get("axes")
        if axes is None and len(node["input"]) > 1 and node["input"][1]:
            axes = [int(x) for x in self.const(node["input"][1]).reshape(-1)]
        axes = tuple(axes or ())
        keep = bool(attrs.get("keepdims", 1))
        if v.layout == "nhwc" and axes:
            axes = tuple({1: 3, 2: 1, 3: 2}.get(a, a) for a in axes)
        out = Lambda(lambda t: fn(t, axis=axes or None, keepdims=keep),
                     name=name)(v.sym)
        layout = v.layout if (keep and v.layout == "nhwc") else None
        self._set_out(node, out, layout=layout)

    def op_reducesum(self, node, attrs, name):
        import jax.numpy as jnp
        self._reduce_op(node, attrs, name, jnp.sum)

    def op_reducemax(self, node, attrs, name):
        import jax.numpy as jnp
        self._reduce_op(node, attrs, name, jnp.max)

    def op_reducemin(self, node, attrs, name):
        import jax.numpy as jnp
        self._reduce_op(node, attrs, name, jnp.min)

    def op_argmax(self, node, attrs, name):
        from ..keras.layers import Lambda
        import jax.numpy as jnp
        v = self.val(node["input"][0])
        axis = int(attrs.get("axis") or 0)
        if v.layout == "nhwc":
            axis = {1: 3, 2: 1, 3: 2}.get(axis, axis)
        keep = bool(attrs.get("keepdims", 1))
        out = Lambda(lambda t: jnp.argmax(t, axis=axis, keepdims=keep)
                     .astype(jnp.int32), name=name)(v.sym)
        self._set_out(node, out)

    def op_shape(self, node, attrs, name):
        """Static shape as a constant — exporters use Shape→Gather→Concat→
        Reshape chains for flattens; returning the ONNX-layout (NCHW) shape
        keeps that arithmetic consistent. The batch dim is emitted as -1
        (unknown at import time; Reshape treats leading -1 as batch)."""
        v = self.val(node["input"][0])
        dims = list(v.sym.shape)
        if v.layout == "nhwc" and len(dims) == 4:
            n, h, w, c = dims
            dims = [n, c, h, w]
        out = np.asarray([-1 if d is None else int(d) for d in dims],
                         dtype=np.int64)
        self.set(node["output"][0], _Value(const=out))

    def op_slice(self, node, attrs, name):
        from ..keras.layers import Lambda
        v = self.val(node["input"][0])
        starts = attrs.get("starts")
        ends = attrs.get("ends")
        axes = attrs.get("axes")
        steps = None
        if starts is None and len(node["input"]) > 1:
            starts = [int(x) for x in self.const(node["input"][1]).reshape(-1)]
            ends = [int(x) for x in self.const(node["input"][2]).reshape(-1)]
            if len(node["input"]) > 3 and node["input"][3]:
                axes = [int(x) for x in
                        self.const(node["input"][3]).reshape(-1)]
            if len(node["input"]) > 4 and node["input"][4]:
                steps = [int(x) for x in
                         self.const(node["input"][4]).reshape(-1)]
        axes = axes or list(range(len(starts)))
        steps = steps or [1] * len(starts)
        int_max = 2 ** 31 - 1

        def spec(ndim):
            sl = [slice(None)] * ndim
            for a, s, e, st in zip(axes, starts, ends, steps):
                end = None if (st > 0 and e >= int_max) \
                    or (st < 0 and e <= -int_max) else e
                sl[a] = slice(s, end, st)
            return tuple(sl)

        if v.const is not None:
            self.set(node["output"][0], _Value(const=v.const[spec(v.const.ndim)]))
            return
        if v.layout == "nhwc":
            axes = [{1: 3, 2: 1, 3: 2}.get(a, a) for a in axes]
        self._set_out(node, Lambda(lambda t: t[spec(t.ndim)], name=name)(v.sym),
                      layout=v.layout)

    def op_split(self, node, attrs, name):
        from ..keras.layers import Lambda
        import jax
        v = self.val(node["input"][0])
        axis = int(attrs.get("axis") or 0)
        if v.layout == "nhwc":
            axis = {1: 3, 2: 1, 3: 2}.get(axis, axis)
        sizes = attrs.get("split")
        if sizes is None and len(node["input"]) > 1 and node["input"][1]:
            sizes = [int(x) for x in self.const(node["input"][1]).reshape(-1)]
        n_out = len(node["output"])
        if sizes is None:
            dim = v.sym.shape[axis]
            sizes = [dim // n_out] * n_out
        offsets = np.cumsum([0] + list(sizes))
        for i, out_name in enumerate(node["output"]):
            s, e = int(offsets[i]), int(offsets[i + 1])
            sym = Lambda(
                lambda t, s=s, e=e: jax.lax.slice_in_dim(t, s, e, axis=axis),
                name=f"{name}_{i}")(v.sym)
            self.set(out_name, _Value(sym=sym, layout=v.layout))

    def op_expand(self, node, attrs, name):
        from ..keras.layers import Lambda
        import jax.numpy as jnp
        v = self.val(node["input"][0])
        target = [int(x) for x in self.const(node["input"][1]).reshape(-1)]
        if v.const is not None:
            self.set(node["output"][0], _Value(
                const=np.broadcast_to(v.const, target).copy()))
            return

        def expand(t):
            # ONNX Expand is numpy-style RIGHT-aligned broadcasting; a target
            # dim of 1 (or -1) keeps the input's dim
            shape = list(target)
            offset = len(shape) - t.ndim
            for i in range(t.ndim):
                if shape[offset + i] in (1, -1) and t.shape[i] != 1:
                    shape[offset + i] = t.shape[i]
            return jnp.broadcast_to(t, tuple(shape))
        self._set_out(node, Lambda(expand, name=name)(v.sym))

    def op_resize(self, node, attrs, name):
        """Nearest/linear upsampling with constant scales (NHWC path)."""
        from ..keras.layers import Lambda
        import jax
        v = self.val(node["input"][0])
        mode = attrs.get("mode") or "nearest"
        if isinstance(mode, bytes):
            mode = mode.decode()
        scales = sizes = None
        if attrs.get("scales"):  # Upsample opset 7/8: attribute
            scales = np.asarray(attrs["scales"], np.float32)
        elif node.get("op_type") == "Upsample" and len(node["input"]) > 1:
            scales = self.const(node["input"][1]).reshape(-1)  # opset 9
        if scales is None and len(node["input"]) > 2 and node["input"][2]:
            scales = self.const(node["input"][2]).reshape(-1)
        if len(node["input"]) > 3 and node["input"][3]:
            sizes = [int(x) for x in self.const(node["input"][3]).reshape(-1)]
        if v.layout != "nhwc" or len(v.sym.shape) != 4:
            raise OnnxLoaderError("Resize supported on 4-D conv tensors only")
        _, h, w, c = v.sym.shape
        if sizes is not None:
            nh, nw = sizes[2], sizes[3]  # NCHW order
        elif scales is not None and len(scales) == 4:
            nh, nw = int(round(h * scales[2])), int(round(w * scales[3]))
        else:
            raise OnnxLoaderError("Resize needs scales or sizes")
        method = {"nearest": "nearest", "linear": "bilinear"}.get(mode)
        if method is None:
            raise OnnxLoaderError(f"Resize mode {mode!r} unsupported")
        out = Lambda(lambda t: jax.image.resize(
            t, (t.shape[0], nh, nw, t.shape[3]), method=method),
            name=name)(v.sym)
        self._set_out(node, out, layout="nhwc")

    op_upsample = op_resize

    def op_gather(self, node, attrs, name):
        from ..keras.layers import Embedding
        v = self.val(node["input"][0])
        idx = self.val(node["input"][1])
        if v.const is not None and idx.const is not None:
            self.set(node["output"][0], _Value(const=np.take(
                v.const, idx.const.astype(np.int64),
                axis=int(attrs.get("axis") or 0))))
            return
        if v.const is not None and idx.sym is not None and v.const.ndim == 2 \
                and int(attrs.get("axis") or 0) == 0:
            # embedding lookup: table is the constant, indices are runtime
            layer = Embedding(v.const.shape[0], v.const.shape[1], name=name)
            self.add_params(name, {"table": v.const})
            self._set_out(node, layer(idx.sym))
            return
        raise OnnxLoaderError("Gather supported only as embedding lookup")


def load_onnx(path_or_bytes, dtype=np.float32):
    """Import an ONNX model.

    Returns ``(model, params, state)`` where ``model`` is an engine ``Model``
    and ``params``/``state`` are ready for ``Estimator.set_params`` /
    ``model.call``.
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with file_io.fopen(path_or_bytes, "rb") as f:
            data = f.read()
    proto = wire.load_model(data)
    graph = proto.get("graph")
    if not graph:
        raise OnnxLoaderError("no graph in ONNX model (corrupt file?)")
    return _GraphBuilder(graph, dtype=dtype).build()
