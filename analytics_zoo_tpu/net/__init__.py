"""Transfer learning and model import (reference ``pipeline/api/net``).

- :class:`Net` — static loaders: our own saved models, ONNX graphs,
  torch weights (reference ``Net.scala:40`` load/loadBigDL/loadTF/
  loadCaffe family, re-targeted at the formats that matter on TPU).
- Graph surgery + freezing live on the engine ``Model`` itself
  (``new_graph``/``freeze``/``freeze_up_to``/``unfreeze``), mirroring the
  reference's GraphNet (``NetUtils.scala:29``).
"""
from .caffe_loader import load_caffe  # noqa: F401
from .onnx_loader import OnnxLoaderError, load_onnx  # noqa: F401
from .torch_import import load_torch, load_torch_state_dict  # noqa: F401
from ..common import file_io


class Net:
    """Static import facade (reference ``Net.scala:40``)."""

    @staticmethod
    def load(path: str):
        """Load a model saved with ``ZooModel.save_model`` or
        ``model.save_model`` (our native checkpoint format)."""
        import os
        from ..models.common import ZooModel
        if file_io.exists(file_io.join(path, "zoo_model.json")):
            return ZooModel.load_model(path)
        raise ValueError(
            f"{path} is not a saved zoo model; for raw estimator "
            f"checkpoints use Estimator.load_checkpoint")

    @staticmethod
    def load_onnx(path, dtype=None):
        """ONNX file → ``(model, params, state)`` (reference
        ``pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:1``)."""
        import numpy as np
        return load_onnx(path, dtype=dtype or np.float32)

    @staticmethod
    def load_torch(model, module_or_path, strict: bool = True):
        """Torch weights → ``(params, state)`` for a matching native model."""
        return load_torch(model, module_or_path, strict=strict)

    @staticmethod
    def load_caffe(prototxt_path, caffemodel_path=None, input_shape=None):
        """Caffe prototxt (+ caffemodel) → ``(model, params, state)``
        (reference ``Net.loadCaffe``, ``CaffeLoader.scala:1``)."""
        return load_caffe(prototxt_path, caffemodel_path,
                          input_shape=input_shape)
