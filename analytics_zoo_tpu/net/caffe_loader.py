"""Caffe model import (reference ``CaffeLoader.scala:1`` /
``Net.loadCaffe``): ``.prototxt`` (text topology) + optional ``.caffemodel``
(binary weights) → native Keras-engine Model.

Design: rather than a second graph builder, the parsed Caffe net is
*translated into ONNX-style nodes* and fed through the existing
:class:`~analytics_zoo_tpu.net.onnx_loader._GraphBuilder` — Caffe blobs are
OIHW like ONNX initializers, InnerProduct is ``Gemm(transB=1)``, and the
NCHW→NHWC conversion, flatten-boundary kernel permutation, and
count_include_pad handling all come for free. Caffe's ceil-mode pooling is
materialized as extra end-padding so shapes match the original net.

The ``.caffemodel`` binary is decoded with the shared protobuf wire reader
(no caffe/protobuf dependency); the ``.prototxt`` with a ~60-line text-proto
parser.
"""
from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.protowire import Field, parse
from .onnx_loader import _GraphBuilder, OnnxLoaderError, _Value
from ..common import file_io

# --------------------------------------------------------------------------
# prototxt (text protobuf) parsing
# --------------------------------------------------------------------------

_TOKEN = re.compile(r"""
    (?P<brace>[{}])
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)\s*(?P<colon>:)?
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+\.?\d*(?:[eE][-+]?\d+)?)
""", re.VERBOSE)


def _tokenize(text: str):
    # strip comments
    text = re.sub(r"#[^\n]*", "", text)
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            if text[pos].isspace():
                pos += 1
                continue
            raise ValueError(f"prototxt parse error at: {text[pos:pos+40]!r}")
        pos = m.end()
        yield m


def parse_prototxt(text: str) -> Dict[str, Any]:
    """Text-format protobuf → nested dict; repeated fields become lists."""
    root: Dict[str, Any] = {}
    stack: List[Dict[str, Any]] = [root]
    pending_key: Optional[str] = None
    for tok in _tokenize(text):
        if tok.group("brace") == "{":
            child: Dict[str, Any] = {}
            _append(stack[-1], pending_key, child)
            stack.append(child)
            pending_key = None
        elif tok.group("brace") == "}":
            stack.pop()
            if not stack:
                raise ValueError("unbalanced braces in prototxt")
        elif tok.group("name") is not None and pending_key is None:
            pending_key = tok.group("name")
            if not tok.group("colon"):
                continue  # message field: next token should be '{'
        elif pending_key is not None:
            if tok.group("string") is not None:
                value: Any = tok.group("string")[1:-1]
            elif tok.group("number") is not None:
                num = tok.group("number")
                value = float(num) if ("." in num or "e" in num.lower()) \
                    else int(num)
            elif tok.group("name") is not None:  # enum / bool literal
                word = tok.group("name")
                value = {"true": True, "false": False}.get(word, word)
            else:
                raise ValueError(f"unexpected token {tok.group(0)!r}")
            _append(stack[-1], pending_key, value)
            pending_key = None
    if len(stack) != 1:
        raise ValueError("unbalanced braces in prototxt")
    return root


def _append(container: Dict[str, Any], key: Optional[str], value: Any):
    if key is None:
        raise ValueError("prototxt value without a field name")
    if key in container:
        if not isinstance(container[key], list):
            container[key] = [container[key]]
        container[key].append(value)
    else:
        container[key] = value


def _as_list(v) -> List[Any]:
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# --------------------------------------------------------------------------
# .caffemodel (binary NetParameter) weights via the shared wire decoder
# --------------------------------------------------------------------------

_BLOB_SHAPE = {1: Field("dim", "int", repeated=True)}
_BLOB = {
    1: Field("num", "int"), 2: Field("channels", "int"),
    3: Field("height", "int"), 4: Field("width", "int"),
    5: Field("data", "float32", repeated=True),
    7: Field("shape", "message", schema=_BLOB_SHAPE),
}
_LAYER = {
    1: Field("name", "string"),
    2: Field("type", "string"),
    7: Field("blobs", "message", repeated=True, schema=_BLOB),
}
_V1LAYER = {  # legacy 'layers' field (V1LayerParameter: name=4, blobs=6;
    # field 1 is an embedded V0LayerParameter message we don't need)
    4: Field("name", "string"),
    6: Field("blobs", "message", repeated=True, schema=_BLOB),
}
_NET = {
    1: Field("name", "string"),
    2: Field("layers_v1", "message", repeated=True, schema=_V1LAYER),
    100: Field("layer", "message", repeated=True, schema=_LAYER),
}


def _blob_array(blob: Dict[str, Any]) -> np.ndarray:
    data = np.asarray(blob.get("data", []), dtype=np.float32)
    shape = (blob.get("shape") or {}).get("dim") or []
    if not shape:
        shape = [blob.get(k) for k in ("num", "channels", "height", "width")]
        shape = [int(s) for s in shape if s]
    if shape and int(np.prod(shape)) == data.size:
        return data.reshape([int(s) for s in shape])
    return data


def load_caffemodel_weights(path: str) -> Dict[str, List[np.ndarray]]:
    """.caffemodel → {layer_name: [blob arrays]}."""
    with file_io.fopen(path, "rb") as f:
        net = parse(f.read(), _NET)
    out: Dict[str, List[np.ndarray]] = {}
    for layer in net.get("layer", []):
        if layer.get("blobs"):
            out[layer.get("name", "")] = [_blob_array(b)
                                          for b in layer["blobs"]]
    for layer in net.get("layers_v1", []):
        name = layer.get("name") or ""
        if layer.get("blobs") and name not in out:
            out[name] = [_blob_array(b) for b in layer["blobs"]]
    return out


# --------------------------------------------------------------------------
# Caffe net → ONNX-style nodes → existing graph builder
# --------------------------------------------------------------------------


def _pool_pads(size_hw, kernel, stride, pad) -> Tuple[int, int, int, int]:
    """Caffe pools use CEIL output sizing; express the difference as extra
    end-padding so the VALID-mode builder produces identical shapes."""
    pads = [pad[0], pad[1], pad[0], pad[1]]  # h0, w0, h1, w1
    for i, (size, k, s, p) in enumerate(zip(size_hw, kernel, stride, pad)):
        if size is None:
            continue
        out_ceil = int(math.ceil((size + 2 * p - k) / s)) + 1
        # caffe clips windows that start in the padding
        if p > 0 and (out_ceil - 1) * s >= size + p:
            out_ceil -= 1
        need = (out_ceil - 1) * s + k - size - p
        pads[2 + i] = max(p, need)
    return tuple(pads)


class CaffeGraphBuilder:
    def __init__(self, net: Dict[str, Any],
                 weights: Optional[Dict[str, List[np.ndarray]]],
                 input_shape: Optional[Tuple[int, ...]] = None):
        self.net = net
        self.weights = weights or {}
        self.input_shape = input_shape  # (C, H, W) override
        # per-top (H, W); refined by load_caffe's fixpoint loop so ceil-mode
        # pooling pads see real sizes
        self._shape_of: Dict[str, Tuple[Optional[int], Optional[int]]] = {}

    def _layers(self) -> List[Dict[str, Any]]:
        return _as_list(self.net.get("layer") or self.net.get("layers"))

    def build(self):
        nodes: List[Dict[str, Any]] = []
        initializers: Dict[str, np.ndarray] = {}
        inputs: List[Tuple[str, List[Optional[int]]]] = []
        # net-level input declaration styles
        if self.net.get("input"):
            names = _as_list(self.net["input"])
            dims_msgs = _as_list(self.net.get("input_shape"))
            for i, name in enumerate(names):
                if self.input_shape is not None:
                    shape = [None] + list(self.input_shape)
                elif i < len(dims_msgs):
                    dims = [int(d) for d in _as_list(dims_msgs[i].get("dim"))]
                    shape = [None] + dims[1:]
                else:
                    dims = [int(d) for d in _as_list(self.net.get("input_dim"))]
                    shape = [None] + dims[4 * i + 1:4 * i + 4]
                inputs.append((name, shape))

        pending_bn: Dict[str, Dict[str, Any]] = {}  # top name → BN node parts
        for layer in self._layers():
            ltype = str(layer.get("type", "")).lower()
            name = layer.get("name", f"layer{len(nodes)}")
            bottoms = [str(b) for b in _as_list(layer.get("bottom"))]
            tops = [str(t) for t in _as_list(layer.get("top"))]
            blobs = self.weights.get(name, [])

            if ltype in ("input", "data"):
                shape_msg = (layer.get("input_param") or {}).get("shape")
                if self.input_shape is not None:
                    shape = [None] + list(self.input_shape)
                elif shape_msg:
                    dims = [int(d) for d in _as_list(
                        _as_list(shape_msg)[0].get("dim"))]
                    shape = [None] + dims[1:]
                else:
                    raise OnnxLoaderError(
                        f"input layer '{name}' has no shape; pass "
                        f"input_shape=(C,H,W) to load_caffe")
                inputs.append((tops[0], shape))
                continue

            if ltype == "convolution":
                cp = layer.get("convolution_param") or {}
                k = _as_list(cp.get("kernel_size")) or [int(cp.get("kernel_h", 1))]
                kh = int(cp.get("kernel_h") or k[0])
                kw = int(cp.get("kernel_w") or (k[1] if len(k) > 1 else k[0]))
                s = _as_list(cp.get("stride")) or [1]
                sh = int(cp.get("stride_h") or s[0])
                sw = int(cp.get("stride_w") or (s[1] if len(s) > 1 else s[0]))
                p = _as_list(cp.get("pad")) or [0]
                ph = int(cp.get("pad_h") or p[0])
                pw = int(cp.get("pad_w") or (p[1] if len(p) > 1 else p[0]))
                group = int(cp.get("group") or 1)
                bias = bool(cp.get("bias_term", True))
                if not blobs:
                    raise OnnxLoaderError(
                        f"conv layer '{name}' has no weights; load the "
                        f".caffemodel alongside the .prototxt")
                w = blobs[0].reshape(int(cp.get("num_output")), -1, kh, kw)
                initializers[f"{name}_w"] = w
                node_inputs = [bottoms[0], f"{name}_w"]
                if bias and len(blobs) > 1:
                    initializers[f"{name}_b"] = blobs[1].reshape(-1)
                    node_inputs.append(f"{name}_b")
                nodes.append({
                    "op_type": "Conv", "name": name,
                    "input": node_inputs, "output": [tops[0]],
                    "attrs": {"kernel_shape": [kh, kw], "strides": [sh, sw],
                              "pads": [ph, pw, ph, pw], "group": group}})
            elif ltype == "innerproduct":
                ip = layer.get("inner_product_param") or {}
                if not blobs:
                    raise OnnxLoaderError(
                        f"InnerProduct '{name}' has no weights; load the "
                        f".caffemodel")
                w = blobs[0].reshape(int(ip.get("num_output")), -1)
                initializers[f"{name}_w"] = w
                node_inputs = [bottoms[0], f"{name}_w"]
                if bool(ip.get("bias_term", True)) and len(blobs) > 1:
                    initializers[f"{name}_b"] = blobs[1].reshape(-1)
                    node_inputs.append(f"{name}_b")
                # caffe IP flattens implicitly
                nodes.append({"op_type": "Flatten", "name": f"{name}_flat",
                              "input": [bottoms[0]],
                              "output": [f"{name}_flat_out"],
                              "attrs": {"axis": 1}})
                node_inputs[0] = f"{name}_flat_out"
                nodes.append({"op_type": "Gemm", "name": name,
                              "input": node_inputs, "output": [tops[0]],
                              "attrs": {"transB": 1}})
            elif ltype == "pooling":
                pp = layer.get("pooling_param") or {}
                if pp.get("global_pooling"):
                    op = ("GlobalAveragePool"
                          if str(pp.get("pool", "MAX")).upper() == "AVE"
                          else "GlobalMaxPool")
                    nodes.append({"op_type": op, "name": name,
                                  "input": [bottoms[0]], "output": [tops[0]],
                                  "attrs": {}})
                    continue
                kh = int(pp.get("kernel_h") or pp.get("kernel_size") or 2)
                kw = int(pp.get("kernel_w") or pp.get("kernel_size") or kh)
                sh = int(pp.get("stride_h") or pp.get("stride") or 1)
                sw = int(pp.get("stride_w") or pp.get("stride") or sh)
                ph = int(pp.get("pad_h") or pp.get("pad") or 0)
                pw = int(pp.get("pad_w") or pp.get("pad") or 0)
                shape_hw = self._shape_of.get(bottoms[0], (None, None))
                h0, w0, h1, w1 = _pool_pads(shape_hw, (kh, kw), (sh, sw),
                                            (ph, pw))
                eh, ew = h1 - ph, w1 - pw  # synthetic ceil-mode end extras
                is_ave = str(pp.get("pool", "MAX")).upper() == "AVE"
                if is_ave:
                    # caffe AVE divides by the window CLIPPED at size+pad:
                    # bake the declared pad into the data (zeros) and let the
                    # excl-pad average see only the synthetic ceil extras
                    src = bottoms[0]
                    if ph or pw:
                        nodes.append({
                            "op_type": "Pad", "name": f"{name}_pad",
                            "input": [src], "output": [f"{name}_pad_out"],
                            "attrs": {"pads": [0, 0, ph, pw, 0, 0, ph, pw]}})
                        src = f"{name}_pad_out"
                    nodes.append({
                        "op_type": "AveragePool", "name": name,
                        "input": [src], "output": [tops[0]],
                        "attrs": {"kernel_shape": [kh, kw],
                                  "strides": [sh, sw],
                                  "pads": [0, 0, max(0, eh), max(0, ew)],
                                  "count_include_pad": 0}})
                else:
                    nodes.append({
                        "op_type": "MaxPool", "name": name,
                        "input": [bottoms[0]], "output": [tops[0]],
                        "attrs": {"kernel_shape": [kh, kw],
                                  "strides": [sh, sw],
                                  "pads": [h0, w0, h1, w1]}})
            elif ltype == "relu":
                nodes.append({"op_type": "Relu", "name": name,
                              "input": [bottoms[0]], "output": [tops[0]],
                              "attrs": {}})
            elif ltype == "sigmoid":
                nodes.append({"op_type": "Sigmoid", "name": name,
                              "input": [bottoms[0]], "output": [tops[0]],
                              "attrs": {}})
            elif ltype == "tanh":
                nodes.append({"op_type": "Tanh", "name": name,
                              "input": [bottoms[0]], "output": [tops[0]],
                              "attrs": {}})
            elif ltype == "softmax":
                nodes.append({"op_type": "Softmax", "name": name,
                              "input": [bottoms[0]], "output": [tops[0]],
                              "attrs": {}})
            elif ltype == "dropout":
                ratio = (layer.get("dropout_param") or {}).get(
                    "dropout_ratio", 0.5)
                nodes.append({"op_type": "Dropout", "name": name,
                              "input": [bottoms[0]], "output": [tops[0]],
                              "attrs": {"ratio": float(ratio)}})
            elif ltype == "concat":
                axis = int((layer.get("concat_param") or {}).get("axis", 1))
                nodes.append({"op_type": "Concat", "name": name,
                              "input": bottoms, "output": [tops[0]],
                              "attrs": {"axis": axis}})
            elif ltype == "eltwise":
                op_code = str((layer.get("eltwise_param") or {})
                              .get("operation", "SUM")).upper()
                op = {"SUM": "Sum", "PROD": "Mul", "MAX": "Max"}.get(op_code)
                if op == "Max":
                    raise OnnxLoaderError("Eltwise MAX not supported")
                if op == "Mul" and len(bottoms) != 2:
                    raise OnnxLoaderError("Eltwise PROD needs 2 bottoms")
                nodes.append({"op_type": op, "name": name,
                              "input": bottoms, "output": [tops[0]],
                              "attrs": {}})
            elif ltype == "batchnorm":
                # caffe BN carries (mean, var, scale_factor); affine params
                # come from the FOLLOWING Scale layer
                if len(blobs) < 3:
                    raise OnnxLoaderError(
                        f"BatchNorm '{name}' missing statistics blobs")
                factor = float(blobs[2].reshape(-1)[0]) or 1.0
                pending_bn[tops[0]] = {
                    "name": name, "bottom": bottoms[0],
                    "mean": blobs[0].reshape(-1) / factor,
                    "var": blobs[1].reshape(-1) / factor,
                    "eps": float((layer.get("batch_norm_param") or {})
                                 .get("eps", 1e-5))}
            elif ltype == "scale":
                bn = pending_bn.pop(bottoms[0], None)
                if bn is None:
                    raise OnnxLoaderError(
                        f"standalone Scale '{name}' unsupported (expected "
                        f"BatchNorm→Scale pair)")
                if len(blobs) < 2:
                    raise OnnxLoaderError(f"Scale '{name}' missing blobs")
                base = bn["name"]
                initializers[f"{base}_gamma"] = blobs[0].reshape(-1)
                initializers[f"{base}_beta"] = blobs[1].reshape(-1)
                initializers[f"{base}_mean"] = bn["mean"]
                initializers[f"{base}_var"] = bn["var"]
                nodes.append({
                    "op_type": "BatchNormalization", "name": base,
                    "input": [bn["bottom"], f"{base}_gamma", f"{base}_beta",
                              f"{base}_mean", f"{base}_var"],
                    "output": [tops[0]],
                    "attrs": {"epsilon": bn["eps"]}})
            elif ltype == "flatten":
                nodes.append({"op_type": "Flatten", "name": name,
                              "input": [bottoms[0]], "output": [tops[0]],
                              "attrs": {"axis": 1}})
            elif ltype in ("accuracy", "loss", "softmaxwithloss", "silence"):
                continue  # train-only plumbing
            else:
                raise OnnxLoaderError(f"unsupported caffe layer type "
                                      f"'{layer.get('type')}' ({name})")
        if pending_bn:
            names = [bn["name"] for bn in pending_bn.values()]
            raise OnnxLoaderError(
                f"BatchNorm layer(s) {names} have no following Scale layer; "
                f"affine-free BN import is unsupported — silently skipping "
                f"normalization would corrupt the model")
        return inputs, nodes, initializers

    # shape tracking (H, W per top) for ceil-mode pooling pads
    def _track_shapes(self, inputs, nodes):
        shapes: Dict[str, Tuple[Optional[int], Optional[int]]] = {}
        for name, shape in inputs:
            if len(shape) == 4:
                shapes[name] = (shape[2], shape[3])  # N,C,H,W
        for node in nodes:
            op = node["op_type"]
            attrs = node["attrs"]
            src = shapes.get(node["input"][0], (None, None))
            if op == "Conv" or op in ("MaxPool", "AveragePool"):
                kh, kw = attrs["kernel_shape"]
                sh, sw = attrs["strides"]
                h0, w0, h1, w1 = attrs["pads"]
                h = ((src[0] + h0 + h1 - kh) // sh + 1) if src[0] else None
                w = ((src[1] + w0 + w1 - kw) // sw + 1) if src[1] else None
                shapes[node["output"][0]] = (h, w)
            elif op == "Pad":
                p = attrs["pads"]  # NCHW begins+ends
                h = (src[0] + p[2] + p[6]) if src[0] else None
                w = (src[1] + p[3] + p[7]) if src[1] else None
                shapes[node["output"][0]] = (h, w)
            elif op in ("Relu", "Sigmoid", "Tanh", "Dropout",
                        "BatchNormalization", "Sum", "Mul", "Concat"):
                shapes[node["output"][0]] = src
            else:
                shapes[node["output"][0]] = (None, None)
        return shapes


def load_caffe(prototxt_path: str, caffemodel_path: Optional[str] = None,
               input_shape: Optional[Tuple[int, int, int]] = None):
    """Import Caffe ``prototxt`` (+ optional ``caffemodel`` weights).

    Returns ``(model, params, state)`` like :func:`load_onnx`; inputs follow
    the same NCHW→NHWC conversion (pass NHWC images at call time).
    ``input_shape`` = (C, H, W) overrides/supplies the input declaration.
    """
    with file_io.fopen(prototxt_path) as f:
        net = parse_prototxt(f.read())
    weights = (load_caffemodel_weights(caffemodel_path)
               if caffemodel_path else None)
    builder = CaffeGraphBuilder(net, weights, input_shape)
    # iterate shape-tracking to a fixpoint: each pass propagates correct
    # spatial sizes one ceil-mode pooling deeper (stacked poolings would
    # otherwise compute their extra end-padding from stale shapes)
    builder._shape_of = {}
    inputs, nodes, initializers = builder.build()
    for _ in range(len(nodes) + 1):
        shapes = builder._track_shapes(inputs, nodes)
        if shapes == builder._shape_of:
            break
        builder._shape_of = shapes
        inputs, nodes, initializers = builder.build()

    # synthesize the ONNX-graph dict the existing builder consumes
    def vi(name, shape):
        dims = [{"dim_param": "N"} if d is None else {"dim_value": d}
                for d in shape]
        return {"name": name,
                "type": {"tensor_type": {"elem_type": 1,
                                         "shape": {"dim": dims}}}}

    # a top is a network output when nothing AFTER its last producer reads
    # it — a set difference alone breaks on Caffe's in-place idiom
    # (top == bottom), where the final tensor appears in its own inputs
    last_producer = {t: i for i, n in enumerate(nodes) for t in n["output"]}
    graph_outputs = [
        t for t, i in sorted(last_producer.items(), key=lambda kv: kv[1])
        if not any(t in nodes[j]["input"]
                   for j in range(i + 1, len(nodes)))]
    graph = {
        "node": [{"op_type": n["op_type"], "name": n["name"],
                  "input": n["input"], "output": n["output"],
                  "attribute": []} for n in nodes],
        "initializer": [],
        "input": [vi(name, shape) for name, shape in inputs],
        "output": [vi(name, [None]) for name in graph_outputs],
    }
    attr_by_node = {id(g): n["attrs"] for g, n in zip(graph["node"], nodes)}
    gb = _GraphBuilder(graph,
                       attr_fn=lambda node: attr_by_node.get(id(node), {}))
    # install decoded numpy initializers directly (no wire format involved)
    for name, arr in initializers.items():
        gb.values[name] = _Value(const=np.asarray(arr))
    return gb.build()

