"""Import torch module weights into native engine models.

The reference's ``TorchNet`` ships a TorchScript blob to JVM executors
(``zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/net/TorchNet.scala:1``);
here the useful capability is *weight transfer*: take a trained
``torch.nn.Module`` (or saved ``state_dict``) and produce the parameter
pytree for a structurally matching native model, so fine-tuning continues on
TPU. (Inference on an opaque TorchScript module is served separately by
``inference.InferenceModel.load_torch`` on host CPU.)

Matching is *by order and kind*: parameter-bearing torch submodules
(Linear/Conv2d/BatchNorm2d/Embedding/...) are aligned with the native
model's parameter-bearing layers in topological order — the same contract
torchvision-style sequential definitions satisfy naturally.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _group_state_dict(state_dict) -> List[Tuple[str, Dict[str, np.ndarray]]]:
    """Group flat ``a.b.weight``-style keys by owning module prefix,
    preserving insertion order (torch state_dicts are ordered)."""
    groups: Dict[str, Dict[str, np.ndarray]] = {}
    for key, tensor in state_dict.items():
        prefix, _, leaf = key.rpartition(".")
        arr = np.asarray(tensor.detach().cpu().numpy()
                         if hasattr(tensor, "detach") else tensor)
        groups.setdefault(prefix, {})[leaf] = arr
    return list(groups.items())


def _kind_of_group(leaves: Dict[str, np.ndarray]) -> Optional[str]:
    if "running_mean" in leaves:
        return "batchnorm"
    w = leaves.get("weight")
    if w is None:
        return None
    if w.ndim == 4:
        return "conv2d"
    if w.ndim == 2:
        return "linear"  # 2-D: Linear (or Embedding — resolved at match time)
    if w.ndim == 1:
        return "norm1d"  # LayerNorm / affine-only
    return None


def _native_kind(layer) -> Optional[str]:
    name = type(layer).__name__
    if name in ("Dense",):
        return "linear"
    if name in ("Convolution2D", "Conv2D", "SeparableConvolution2D",
                "Deconvolution2D", "AtrousConvolution2D", "ShareConvolution2D"):
        return "conv2d"
    if name == "BatchNormalization":
        return "batchnorm"
    if name == "LayerNormalization":
        return "norm1d"
    if name in ("Embedding", "WordEmbedding", "SparseEmbedding"):
        return "embedding"
    return None


def _param_layers(model) -> List[Tuple[Tuple[str, ...], Any]]:
    """Parameter-bearing layers in build order, with their param-tree paths.

    The native param tree nests by container (``Sequential.build`` stores a
    sub-dict per child container), so each leaf layer is addressed by the
    chain of container-level keys down to it.
    """
    from ..keras.engine import Model, Sequential
    out: List[Tuple[Tuple[str, ...], Any]] = []

    def walk(m, path):
        if isinstance(m, Sequential):
            for l in m.layers:
                walk(l, path + (l.name,))
        elif isinstance(m, Model):
            seen = set()
            for node in m._nodes:
                if id(node.layer) not in seen:
                    seen.add(id(node.layer))
                    walk(node.layer, path + (node.layer.name,))
        else:
            if _native_kind(m) is not None:
                out.append((path, m))
    walk(model, ())
    return out


def _set_path(tree: Dict[str, Any], path: Tuple[str, ...], value) -> None:
    for key in path[:-1]:
        tree = tree.setdefault(key, {})
    tree[path[-1]] = value


def convert_group(kind: str, leaves: Dict[str, np.ndarray]
                  ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """One torch module's tensors → native (params, state) for its layer."""
    if kind == "linear":
        p = {"kernel": leaves["weight"].T}
        if "bias" in leaves:
            p["bias"] = leaves["bias"]
        return p, {}
    if kind == "conv2d":
        # torch OIHW → native HWIO
        p = {"kernel": np.transpose(leaves["weight"], (2, 3, 1, 0))}
        if "bias" in leaves:
            p["bias"] = leaves["bias"]
        return p, {}
    if kind == "batchnorm":
        return ({"gamma": leaves["weight"], "beta": leaves["bias"]},
                {"moving_mean": leaves["running_mean"],
                 "moving_var": leaves["running_var"]})
    if kind == "norm1d":
        return {"gamma": leaves["weight"], "beta": leaves.get(
            "bias", np.zeros_like(leaves["weight"]))}, {}
    if kind == "embedding":
        return {"table": leaves["weight"]}, {}
    raise ValueError(f"unhandled torch module kind {kind}")


def load_torch_state_dict(model, state_dict, strict: bool = True
                          ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Map a torch ``state_dict`` onto ``model``'s layers by order + kind.

    Returns ``(params, state)`` pytrees keyed by native layer names. With
    ``strict`` every torch parameter group must be consumed and every native
    param layer filled.
    """
    groups = [(prefix, leaves, _kind_of_group(leaves))
              for prefix, leaves in _group_state_dict(state_dict)]
    groups = [(p, l, k) for p, l, k in groups if k is not None]
    layers = _param_layers(model)
    params: Dict[str, Any] = {}
    state: Dict[str, Any] = {}
    gi = 0
    for path, layer in layers:
        want = _native_kind(layer)
        # embeddings and linears share torch kind 'linear' when 2-D; accept
        matches = {want, "linear" if want == "embedding" else want}
        while gi < len(groups) and groups[gi][2] not in matches:
            if strict:
                raise ValueError(
                    f"torch group '{groups[gi][0]}' ({groups[gi][2]}) does "
                    f"not match native layer '{layer.name}' ({want})")
            gi += 1
        if gi >= len(groups):
            raise ValueError(
                f"ran out of torch parameter groups at native layer "
                f"'{layer.name}' ({want}); {len(layers)} layers vs "
                f"{len(groups)} groups")
        prefix, leaves, kind = groups[gi]
        gi += 1
        p, s = convert_group(want if want == "embedding" else kind, leaves)
        _set_path(params, path, p)
        if s:
            _set_path(state, path, s)
    if strict and gi != len(groups):
        leftover = [g[0] for g in groups[gi:]]
        raise ValueError(f"unconsumed torch parameter groups: {leftover}")
    return params, state


def load_torch(model, module_or_path, strict: bool = True):
    """Accept an ``nn.Module``, a ``state_dict``, or a ``.pt`` path."""
    sd = module_or_path
    if isinstance(module_or_path, str):
        import torch
        sd = torch.load(module_or_path, map_location="cpu",
                        weights_only=True)
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    return load_torch_state_dict(model, sd, strict=strict)


def torchvision_resnet18(num_classes: int = 1000):
    """A torchvision-compatible ResNet-18 built from plain ``torch.nn``
    (torchvision itself is not a dependency): module DEFINITION ORDER
    matches torchvision's, so real published ``resnet18`` state_dicts load
    into it — and its state_dict imports into the native
    ``resnet(18, padding_mode="torch")`` graph bit-faithfully (the golden
    test and the pretrained-import example both build their reference from
    here)."""
    import torch
    from torch import nn

    class BasicBlock(nn.Module):
        def __init__(self, cin, cout, stride=1):
            super().__init__()
            self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(cout)
            self.relu = nn.ReLU(inplace=True)
            self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(cout)
            self.downsample = None
            if stride != 1 or cin != cout:
                self.downsample = nn.Sequential(
                    nn.Conv2d(cin, cout, 1, stride, bias=False),
                    nn.BatchNorm2d(cout))

        def forward(self, x):
            idt = x if self.downsample is None else self.downsample(x)
            out = self.bn2(self.conv2(self.relu(self.bn1(self.conv1(x)))))
            return self.relu(out + idt)

    class ResNet18(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
            self.bn1 = nn.BatchNorm2d(64)
            self.relu = nn.ReLU(inplace=True)
            self.maxpool = nn.MaxPool2d(3, 2, 1)
            self.layer1 = nn.Sequential(BasicBlock(64, 64),
                                        BasicBlock(64, 64))
            self.layer2 = nn.Sequential(BasicBlock(64, 128, 2),
                                        BasicBlock(128, 128))
            self.layer3 = nn.Sequential(BasicBlock(128, 256, 2),
                                        BasicBlock(256, 256))
            self.layer4 = nn.Sequential(BasicBlock(256, 512, 2),
                                        BasicBlock(512, 512))
            self.avgpool = nn.AdaptiveAvgPool2d(1)
            self.fc = nn.Linear(512, num_classes)

        def forward(self, x):
            x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
            x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
            return self.fc(self.avgpool(x).flatten(1))

    return ResNet18()
