"""ONNX ModelProto schemas over the shared wire decoder
(:mod:`analytics_zoo_tpu.utils.protowire`). Field numbers follow the
public ``onnx.proto3`` schema.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.protowire import Field, parse  # noqa: F401 (re-export)


# --------------------------------------------------------------------------
# ONNX message schemas (field numbers from onnx/onnx.proto3)
# --------------------------------------------------------------------------

TENSOR_SCHEMA: Dict[int, Field] = {
    1: Field("dims", "int", repeated=True),
    2: Field("data_type", "int"),
    4: Field("float_data", "float32", repeated=True),
    5: Field("int32_data", "int", repeated=True),
    6: Field("string_data", "bytes", repeated=True),
    7: Field("int64_data", "int", repeated=True),
    8: Field("name", "string"),
    9: Field("raw_data", "bytes"),
    10: Field("double_data", "float64", repeated=True),
    11: Field("uint64_data", "int", repeated=True),
}

_DIM_SCHEMA = {
    1: Field("dim_value", "int"),
    2: Field("dim_param", "string"),
}
_SHAPE_SCHEMA = {1: Field("dim", "message", repeated=True, schema=_DIM_SCHEMA)}
_TENSOR_TYPE_SCHEMA = {
    1: Field("elem_type", "int"),
    2: Field("shape", "message", schema=_SHAPE_SCHEMA),
}
_TYPE_SCHEMA = {1: Field("tensor_type", "message", schema=_TENSOR_TYPE_SCHEMA)}
VALUE_INFO_SCHEMA = {
    1: Field("name", "string"),
    2: Field("type", "message", schema=_TYPE_SCHEMA),
}

ATTRIBUTE_SCHEMA: Dict[int, Field] = {
    1: Field("name", "string"),
    2: Field("f", "float32"),
    3: Field("i", "int"),
    4: Field("s", "bytes"),
    5: Field("t", "message", schema=TENSOR_SCHEMA),
    7: Field("floats", "float32", repeated=True),
    8: Field("ints", "int", repeated=True),
    9: Field("strings", "bytes", repeated=True),
    10: Field("tensors", "message", repeated=True, schema=TENSOR_SCHEMA),
    20: Field("type", "int"),
}

NODE_SCHEMA: Dict[int, Field] = {
    1: Field("input", "string", repeated=True),
    2: Field("output", "string", repeated=True),
    3: Field("name", "string"),
    4: Field("op_type", "string"),
    5: Field("attribute", "message", repeated=True, schema=ATTRIBUTE_SCHEMA),
    7: Field("domain", "string"),
}

GRAPH_SCHEMA: Dict[int, Field] = {
    1: Field("node", "message", repeated=True, schema=NODE_SCHEMA),
    2: Field("name", "string"),
    5: Field("initializer", "message", repeated=True, schema=TENSOR_SCHEMA),
    11: Field("input", "message", repeated=True, schema=VALUE_INFO_SCHEMA),
    12: Field("output", "message", repeated=True, schema=VALUE_INFO_SCHEMA),
    13: Field("value_info", "message", repeated=True, schema=VALUE_INFO_SCHEMA),
}

_OPSET_SCHEMA = {1: Field("domain", "string"), 2: Field("version", "int")}
MODEL_SCHEMA: Dict[int, Field] = {
    1: Field("ir_version", "int"),
    2: Field("producer_name", "string"),
    7: Field("graph", "message", schema=GRAPH_SCHEMA),
    8: Field("opset_import", "message", repeated=True, schema=_OPSET_SCHEMA),
}

# TensorProto.DataType → numpy
_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}


def tensor_to_numpy(t: Dict[str, Any]) -> np.ndarray:
    """TensorProto dict → ndarray (raw_data or the typed repeated fields)."""
    dims = tuple(t.get("dims", []))
    dt = _DTYPES.get(t.get("data_type", 1))
    if dt is None:
        raise ValueError(f"unsupported ONNX tensor dtype {t.get('data_type')}")
    raw = t.get("raw_data")
    if raw:
        arr = np.frombuffer(raw, dtype=np.dtype(dt).newbyteorder("<"))
    elif t.get("float_data"):
        arr = np.asarray(t["float_data"], dtype=np.float32)
    elif t.get("int64_data"):
        arr = np.asarray(t["int64_data"], dtype=np.int64)
    elif t.get("int32_data"):
        arr = np.asarray(t["int32_data"], dtype=np.int32)
    elif t.get("double_data"):
        arr = np.asarray(t["double_data"], dtype=np.float64)
    else:
        arr = np.zeros(int(np.prod(dims)) if dims else 0, dtype=dt)
    return arr.astype(dt, copy=False).reshape(dims)


def attributes(node: Dict[str, Any]) -> Dict[str, Any]:
    """NodeProto attribute list → {name: python value}."""
    out: Dict[str, Any] = {}
    for a in node.get("attribute", []):
        name = a.get("name", "")
        # AttributeProto.type: 1=FLOAT 2=INT 3=STRING 4=TENSOR 6=FLOATS 7=INTS 8=STRINGS
        # proto3 omits default-valued scalars from the wire, so a typed FLOAT/
        # INT attribute with no payload means 0.0/0 — not "absent"
        atype = a.get("type")
        if atype == 1 or (atype is None and "f" in a):
            out[name] = a.get("f", 0.0)
        elif atype == 2 or (atype is None and "i" in a):
            out[name] = a.get("i", 0)
        elif atype == 3 or (atype is None and "s" in a):
            s = a.get("s", b"")
            out[name] = s.decode("utf-8", errors="replace")
        elif atype == 4 or (atype is None and "t" in a):
            out[name] = tensor_to_numpy(a["t"])
        elif atype == 6 or a.get("floats"):
            out[name] = [float(v) for v in a.get("floats", [])]
        elif atype == 7 or a.get("ints"):
            out[name] = [int(v) for v in a.get("ints", [])]
        elif atype == 8 or a.get("strings"):
            out[name] = [s.decode("utf-8", errors="replace")
                         for s in a.get("strings", [])]
        else:
            out[name] = None
    return out


def load_model(data: bytes) -> Dict[str, Any]:
    """Decode serialized ModelProto bytes → nested dict."""
    return parse(data, MODEL_SCHEMA)


def value_info_shape(vi: Dict[str, Any]) -> List[Optional[int]]:
    """ValueInfoProto → [dim or None, ...] (None = symbolic/batch dim)."""
    tt = (vi.get("type") or {}).get("tensor_type") or {}
    dims = (tt.get("shape") or {}).get("dim", [])
    shape: List[Optional[int]] = []
    for d in dims:
        v = d.get("dim_value")
        shape.append(int(v) if v else None)
    return shape
