"""Minimal protobuf wire-format decoder for ONNX ModelProto.

The reference imports ONNX graphs through the ``onnx`` python package
(``pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:1``); that package is not a
dependency here, and the wire format is simple enough that a schema-driven
decoder for the handful of ONNX messages we need (ModelProto, GraphProto,
NodeProto, TensorProto, AttributeProto, ValueInfoProto) is ~200 lines and
imports nothing but numpy. Field numbers follow the public ``onnx.proto3``
schema.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long (corrupt protobuf)")


def _skip(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == _VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire_type == _I64:
        return pos + 8
    if wire_type == _LEN:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wire_type == _I32:
        return pos + 4
    raise ValueError(f"unsupported wire type {wire_type}")


def _zigzag(v: int) -> int:
    # onnx uses plain int64 (not sint64); negative ints arrive as 2^64-|v|
    return v - (1 << 64) if v >= (1 << 63) else v


class Field:
    """One schema entry: how to decode a field number."""

    def __init__(self, name: str, kind: str, repeated: bool = False,
                 schema: Optional[Dict[int, "Field"]] = None):
        self.name = name
        self.kind = kind  # int | float32 | string | bytes | message | packed_int | packed_float
        self.repeated = repeated
        self.schema = schema


def parse(buf: bytes, schema: Dict[int, Field]) -> Dict[str, Any]:
    """Decode one message with the given schema; unknown fields are skipped."""
    out: Dict[str, Any] = {}
    for fno, f in schema.items():
        if f.repeated:
            out[f.name] = []
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        fno, wt = key >> 3, key & 7
        f = schema.get(fno)
        if f is None:
            pos = _skip(buf, pos, wt)
            continue
        val: Any
        if f.kind == "int":
            if wt == _VARINT:
                v, pos = _read_varint(buf, pos)
                val = _zigzag(v)
            elif wt == _LEN:  # packed repeated ints
                n, pos = _read_varint(buf, pos)
                sub_end = pos + n
                vals = []
                while pos < sub_end:
                    v, pos = _read_varint(buf, pos)
                    vals.append(_zigzag(v))
                out[f.name].extend(vals)
                continue
            else:
                pos = _skip(buf, pos, wt)
                continue
        elif f.kind == "float32":
            if wt == _I32:
                val = struct.unpack_from("<f", buf, pos)[0]
                pos += 4
            elif wt == _LEN:  # packed floats
                n, pos = _read_varint(buf, pos)
                out[f.name].extend(
                    np.frombuffer(buf, dtype="<f4", count=n // 4, offset=pos))
                pos += n
                continue
            else:
                pos = _skip(buf, pos, wt)
                continue
        elif f.kind == "float64":
            if wt == _I64:
                val = struct.unpack_from("<d", buf, pos)[0]
                pos += 8
            elif wt == _LEN:
                n, pos = _read_varint(buf, pos)
                out[f.name].extend(
                    np.frombuffer(buf, dtype="<f8", count=n // 8, offset=pos))
                pos += n
                continue
            else:
                pos = _skip(buf, pos, wt)
                continue
        elif f.kind in ("string", "bytes", "message"):
            if wt != _LEN:
                pos = _skip(buf, pos, wt)
                continue
            n, pos = _read_varint(buf, pos)
            raw = buf[pos:pos + n]
            pos += n
            if f.kind == "string":
                val = raw.decode("utf-8", errors="replace")
            elif f.kind == "bytes":
                val = raw
            else:
                val = parse(raw, f.schema)
        else:
            raise ValueError(f"unknown schema kind {f.kind}")
        if f.repeated:
            out[f.name].append(val)
        else:
            out[f.name] = val
    return out


# --------------------------------------------------------------------------
# ONNX message schemas (field numbers from onnx/onnx.proto3)
# --------------------------------------------------------------------------

TENSOR_SCHEMA: Dict[int, Field] = {
    1: Field("dims", "int", repeated=True),
    2: Field("data_type", "int"),
    4: Field("float_data", "float32", repeated=True),
    5: Field("int32_data", "int", repeated=True),
    6: Field("string_data", "bytes", repeated=True),
    7: Field("int64_data", "int", repeated=True),
    8: Field("name", "string"),
    9: Field("raw_data", "bytes"),
    10: Field("double_data", "float64", repeated=True),
    11: Field("uint64_data", "int", repeated=True),
}

_DIM_SCHEMA = {
    1: Field("dim_value", "int"),
    2: Field("dim_param", "string"),
}
_SHAPE_SCHEMA = {1: Field("dim", "message", repeated=True, schema=_DIM_SCHEMA)}
_TENSOR_TYPE_SCHEMA = {
    1: Field("elem_type", "int"),
    2: Field("shape", "message", schema=_SHAPE_SCHEMA),
}
_TYPE_SCHEMA = {1: Field("tensor_type", "message", schema=_TENSOR_TYPE_SCHEMA)}
VALUE_INFO_SCHEMA = {
    1: Field("name", "string"),
    2: Field("type", "message", schema=_TYPE_SCHEMA),
}

ATTRIBUTE_SCHEMA: Dict[int, Field] = {
    1: Field("name", "string"),
    2: Field("f", "float32"),
    3: Field("i", "int"),
    4: Field("s", "bytes"),
    5: Field("t", "message", schema=TENSOR_SCHEMA),
    7: Field("floats", "float32", repeated=True),
    8: Field("ints", "int", repeated=True),
    9: Field("strings", "bytes", repeated=True),
    10: Field("tensors", "message", repeated=True, schema=TENSOR_SCHEMA),
    20: Field("type", "int"),
}

NODE_SCHEMA: Dict[int, Field] = {
    1: Field("input", "string", repeated=True),
    2: Field("output", "string", repeated=True),
    3: Field("name", "string"),
    4: Field("op_type", "string"),
    5: Field("attribute", "message", repeated=True, schema=ATTRIBUTE_SCHEMA),
    7: Field("domain", "string"),
}

GRAPH_SCHEMA: Dict[int, Field] = {
    1: Field("node", "message", repeated=True, schema=NODE_SCHEMA),
    2: Field("name", "string"),
    5: Field("initializer", "message", repeated=True, schema=TENSOR_SCHEMA),
    11: Field("input", "message", repeated=True, schema=VALUE_INFO_SCHEMA),
    12: Field("output", "message", repeated=True, schema=VALUE_INFO_SCHEMA),
    13: Field("value_info", "message", repeated=True, schema=VALUE_INFO_SCHEMA),
}

_OPSET_SCHEMA = {1: Field("domain", "string"), 2: Field("version", "int")}
MODEL_SCHEMA: Dict[int, Field] = {
    1: Field("ir_version", "int"),
    2: Field("producer_name", "string"),
    7: Field("graph", "message", schema=GRAPH_SCHEMA),
    8: Field("opset_import", "message", repeated=True, schema=_OPSET_SCHEMA),
}

# TensorProto.DataType → numpy
_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}


def tensor_to_numpy(t: Dict[str, Any]) -> np.ndarray:
    """TensorProto dict → ndarray (raw_data or the typed repeated fields)."""
    dims = tuple(t.get("dims", []))
    dt = _DTYPES.get(t.get("data_type", 1))
    if dt is None:
        raise ValueError(f"unsupported ONNX tensor dtype {t.get('data_type')}")
    raw = t.get("raw_data")
    if raw:
        arr = np.frombuffer(raw, dtype=np.dtype(dt).newbyteorder("<"))
    elif t.get("float_data"):
        arr = np.asarray(t["float_data"], dtype=np.float32)
    elif t.get("int64_data"):
        arr = np.asarray(t["int64_data"], dtype=np.int64)
    elif t.get("int32_data"):
        arr = np.asarray(t["int32_data"], dtype=np.int32)
    elif t.get("double_data"):
        arr = np.asarray(t["double_data"], dtype=np.float64)
    else:
        arr = np.zeros(int(np.prod(dims)) if dims else 0, dtype=dt)
    return arr.astype(dt, copy=False).reshape(dims)


def attributes(node: Dict[str, Any]) -> Dict[str, Any]:
    """NodeProto attribute list → {name: python value}."""
    out: Dict[str, Any] = {}
    for a in node.get("attribute", []):
        name = a.get("name", "")
        # AttributeProto.type: 1=FLOAT 2=INT 3=STRING 4=TENSOR 6=FLOATS 7=INTS 8=STRINGS
        # proto3 omits default-valued scalars from the wire, so a typed FLOAT/
        # INT attribute with no payload means 0.0/0 — not "absent"
        atype = a.get("type")
        if atype == 1 or (atype is None and "f" in a):
            out[name] = a.get("f", 0.0)
        elif atype == 2 or (atype is None and "i" in a):
            out[name] = a.get("i", 0)
        elif atype == 3 or (atype is None and "s" in a):
            s = a.get("s", b"")
            out[name] = s.decode("utf-8", errors="replace")
        elif atype == 4 or (atype is None and "t" in a):
            out[name] = tensor_to_numpy(a["t"])
        elif atype == 6 or a.get("floats"):
            out[name] = [float(v) for v in a.get("floats", [])]
        elif atype == 7 or a.get("ints"):
            out[name] = [int(v) for v in a.get("ints", [])]
        elif atype == 8 or a.get("strings"):
            out[name] = [s.decode("utf-8", errors="replace")
                         for s in a.get("strings", [])]
        else:
            out[name] = None
    return out


def load_model(data: bytes) -> Dict[str, Any]:
    """Decode serialized ModelProto bytes → nested dict."""
    return parse(data, MODEL_SCHEMA)


def value_info_shape(vi: Dict[str, Any]) -> List[Optional[int]]:
    """ValueInfoProto → [dim or None, ...] (None = symbolic/batch dim)."""
    tt = (vi.get("type") or {}).get("tensor_type") or {}
    dims = (tt.get("shape") or {}).get("dim", [])
    shape: List[Optional[int]] = []
    for d in dims:
        v = d.get("dim_value")
        shape.append(int(v) if v else None)
    return shape
