from .image_set import DistributedImageSet, ImageSet, LocalImageSet  # noqa: F401
from .transforms import (  # noqa: F401
    AspectScale, Brightness, CenterCrop, ChannelNormalize, ChannelOrder,
    ChannelScaledNormalizer, ColorJitter, Contrast, Expand, Filler,
    FixedCrop, Grayscale, HFlip, Hue, ImageSetToSample, MatToFloats, Mirror,
    PixelBytesToMat, PixelNormalizer, RandomAspectScale, RandomCrop,
    RandomPreprocessing, RandomResize, RandomTransformer, Resize, Saturation,
    VFlip)
from .detection import (  # noqa: F401
    ExpandWithBoxes, RandomHFlipWithBoxes, RandomSampleCrop, ResizeWithBoxes)
