from .image_set import DistributedImageSet, ImageSet, LocalImageSet  # noqa: F401
from .transforms import (  # noqa: F401
    AspectScale, Brightness, CenterCrop, ChannelNormalize, ChannelOrder,
    ColorJitter, Contrast, Expand, FixedCrop, Hue, ImageSetToSample,
    MatToFloats, PixelBytesToMat, RandomCrop, RandomPreprocessing,
    RandomTransformer, Resize, Saturation, HFlip)
