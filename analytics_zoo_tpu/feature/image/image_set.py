"""ImageSet (reference ``feature/image/ImageSet.scala:140``:
``LocalImageSet``/``DistributedImageSet`` collections + ``read`` factory +
``transform`` chaining + ``toDataSet``).

TPU-host shape: a LocalImageSet holds host images (list of HWC arrays,
possibly ragged before resize); a DistributedImageSet lowers with per-host
sharding enabled (the split itself happens in the FeatureSet).
``to_featureset`` is the ``ImageSetToSample → FeatureSet`` lowering that
feeds the device."""
from __future__ import annotations

import glob
import os
from typing import Any, List, Optional, Sequence

import numpy as np

from ..featureset import FeatureSet
from ..preprocessing import Preprocessing

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


class ImageSet:
    def __init__(self, images: List[np.ndarray],
                 labels: Optional[np.ndarray] = None,
                 paths: Optional[List[str]] = None):
        self.images = list(images)
        self.labels = None if labels is None else np.asarray(labels)
        self.paths = paths

    # -- factories (reference ImageSet.read) ----------------------------------

    @staticmethod
    def read(path: str, with_label: bool = False,
             one_based_label: bool = True) -> "LocalImageSet":
        """Read images from ``path`` (a dir of images, or with ``with_label``
        a dir of class-named subdirs, labels alphabetical)."""
        import cv2
        images, labels, paths = [], [], []
        if with_label:
            classes = sorted(d for d in os.listdir(path)
                             if os.path.isdir(os.path.join(path, d)))
            base = 1 if one_based_label else 0
            for ci, cls in enumerate(classes):
                for f in sorted(glob.glob(os.path.join(path, cls, "*"))):
                    if not f.lower().endswith(_IMG_EXTS):
                        continue
                    img = cv2.imread(f)
                    if img is None:
                        continue
                    images.append(img)
                    labels.append(ci + base)
                    paths.append(f)
            return LocalImageSet(images, np.asarray(labels, np.float32), paths)
        for f in sorted(glob.glob(os.path.join(path, "*"))):
            if not f.lower().endswith(_IMG_EXTS):
                continue
            img = cv2.imread(f)
            if img is not None:
                images.append(img)
                paths.append(f)
        return LocalImageSet(images, None, paths)

    @staticmethod
    def from_arrays(images: Sequence[np.ndarray],
                    labels: Optional[np.ndarray] = None) -> "LocalImageSet":
        return LocalImageSet(list(images), labels)

    # -- transform chaining ---------------------------------------------------

    def transform(self, preprocessing: Preprocessing) -> "ImageSet":
        out = [preprocessing.apply(img) for img in self.images]
        return type(self)(out, self.labels, self.paths)

    def __len__(self) -> int:
        return len(self.images)

    # -- lowering to the device feed ------------------------------------------

    def to_featureset(self, **kwargs) -> FeatureSet:
        shapes = {np.asarray(i).shape for i in self.images}
        if len(shapes) > 1:
            raise ValueError(
                f"images have mixed shapes {shapes}; apply Resize/Crop "
                "transforms before to_featureset (XLA needs static shapes)")
        feats = np.stack([np.asarray(i, np.float32) for i in self.images])
        return FeatureSet.from_ndarrays(feats, self.labels, **kwargs)


class LocalImageSet(ImageSet):
    """Single-host image collection (reference ``LocalImageSet:98``)."""


class DistributedImageSet(ImageSet):
    """Sharded image collection (reference ``DistributedImageSet:119``) —
    per-host sharding is applied by the FeatureSet it lowers into
    (``transform`` preserves the type via the base's ``type(self)``)."""

    def to_featureset(self, **kwargs) -> FeatureSet:
        kwargs.setdefault("shard", True)
        return super().to_featureset(**kwargs)
