"""ImageSet (reference ``feature/image/ImageSet.scala:140``:
``LocalImageSet``/``DistributedImageSet`` collections + ``read`` factory +
``transform`` chaining + ``toDataSet``).

TPU-host shape: a LocalImageSet holds host images (list of HWC arrays,
possibly ragged before resize); a DistributedImageSet lowers with per-host
sharding enabled (the split itself happens in the FeatureSet).
``to_featureset`` is the ``ImageSetToSample → FeatureSet`` lowering that
feeds the device."""
from __future__ import annotations

import glob
import os
from typing import Any, List, Optional, Sequence

import numpy as np

from ..featureset import FeatureSet
from ..preprocessing import Preprocessing
from ...common import file_io

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


class ImageSet:
    def __init__(self, images: List[np.ndarray],
                 labels: Optional[np.ndarray] = None,
                 paths: Optional[List[str]] = None):
        self.images = list(images)
        self.labels = None if labels is None else np.asarray(labels)
        self.paths = paths

    # -- factories (reference ImageSet.read) ----------------------------------

    @staticmethod
    def read(path: str, with_label: bool = False,
             one_based_label: bool = True) -> "LocalImageSet":
        """Read images from ``path`` (a dir of images, or with ``with_label``
        a dir of class-named subdirs, labels alphabetical). ``path`` may be a
        local directory or a ``scheme://`` URI (gs://...) — all reads go
        through the filesystem layer and decode from bytes."""
        import cv2

        def _load(fpath):
            with file_io.fopen(fpath, "rb") as f:
                buf = np.frombuffer(f.read(), np.uint8)
            return cv2.imdecode(buf, cv2.IMREAD_COLOR)

        images, labels, paths = [], [], []
        if with_label:
            classes = sorted(d for d in file_io.listdir(path)
                             if file_io.isdir(file_io.join(path, d)))
            base = 1 if one_based_label else 0
            for ci, cls in enumerate(classes):
                cdir = file_io.join(path, cls)
                for name in sorted(file_io.listdir(cdir)):
                    if not name.lower().endswith(_IMG_EXTS):
                        continue
                    f = file_io.join(cdir, name)
                    img = _load(f)
                    if img is None:
                        continue
                    images.append(img)
                    labels.append(ci + base)
                    paths.append(f)
            return LocalImageSet(images, np.asarray(labels, np.float32), paths)
        for name in sorted(file_io.listdir(path)):
            if not name.lower().endswith(_IMG_EXTS):
                continue
            f = file_io.join(path, name)
            img = _load(f)
            if img is not None:
                images.append(img)
                paths.append(f)
        return LocalImageSet(images, None, paths)

    @staticmethod
    def from_arrays(images: Sequence[np.ndarray],
                    labels: Optional[np.ndarray] = None) -> "LocalImageSet":
        return LocalImageSet(list(images), labels)

    # -- transform chaining ---------------------------------------------------

    def transform(self, preprocessing: Preprocessing) -> "ImageSet":
        out = [preprocessing.apply(img) for img in self.images]
        return type(self)(out, self.labels, self.paths)

    def __len__(self) -> int:
        return len(self.images)

    # -- lowering to the device feed ------------------------------------------

    def to_featureset(self, **kwargs) -> FeatureSet:
        shapes = {np.asarray(i).shape for i in self.images}
        if len(shapes) > 1:
            raise ValueError(
                f"images have mixed shapes {shapes}; apply Resize/Crop "
                "transforms before to_featureset (XLA needs static shapes)")
        feats = np.stack([np.asarray(i, np.float32) for i in self.images])
        return FeatureSet.from_ndarrays(feats, self.labels, **kwargs)


class LocalImageSet(ImageSet):
    """Single-host image collection (reference ``LocalImageSet:98``)."""


class DistributedImageSet(ImageSet):
    """Sharded image collection (reference ``DistributedImageSet:119``) —
    per-host sharding is applied by the FeatureSet it lowers into
    (``transform`` preserves the type via the base's ``type(self)``)."""

    def to_featureset(self, **kwargs) -> FeatureSet:
        kwargs.setdefault("shard", True)
        return super().to_featureset(**kwargs)
