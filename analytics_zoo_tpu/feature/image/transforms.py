"""Image preprocessing ops (reference ``zoo/.../feature/image/*.scala``, 33
files of OpenCV-backed transforms, SURVEY §2.2 "ImageSet").

TPU-host design: transforms run on the host CPU over numpy HWC uint8/float
arrays (cv2 where it wins, numpy otherwise) inside the FeatureSet
preprocessing chain; the device only ever sees fixed-shape normalized
batches. Each op is a ``Preprocessing`` so the reference's ``->`` chaining
contract carries over."""
from __future__ import annotations

import random
from typing import Optional, Sequence, Tuple

import numpy as np

from ..preprocessing import Preprocessing

try:
    import cv2
except Exception:  # pragma: no cover - cv2 is in the image, but stay robust
    cv2 = None


class ImageTransform(Preprocessing):
    """Base: apply(img HWC ndarray) -> HWC ndarray."""

    def apply(self, img):
        raise NotImplementedError


class Resize(ImageTransform):
    def __init__(self, height: int, width: int, interpolation: str = "linear"):
        self.height = height
        self.width = width
        self.interpolation = interpolation

    def apply(self, img):
        if cv2 is not None:
            interp = (cv2.INTER_NEAREST if self.interpolation == "nearest"
                      else cv2.INTER_LINEAR)
            return cv2.resize(np.asarray(img), (self.width, self.height),
                              interpolation=interp)
        # numpy nearest fallback
        img = np.asarray(img)
        ys = (np.arange(self.height) * img.shape[0] / self.height).astype(int)
        xs = (np.arange(self.width) * img.shape[1] / self.width).astype(int)
        return img[ys][:, xs]


class AspectScale(ImageTransform):
    """Scale the short side to ``min_size``, capping the long side
    (reference ``AspectScale.scala``)."""

    def __init__(self, min_size: int, max_size: int = 1000):
        self.min_size = min_size
        self.max_size = max_size

    def apply(self, img):
        h, w = img.shape[:2]
        scale = self.min_size / min(h, w)
        if max(h, w) * scale > self.max_size:
            scale = self.max_size / max(h, w)
        return Resize(int(round(h * scale)), int(round(w * scale))).apply(img)


class CenterCrop(ImageTransform):
    def __init__(self, height: int, width: int):
        self.height = height
        self.width = width

    def apply(self, img):
        h, w = img.shape[:2]
        y0 = max(0, (h - self.height) // 2)
        x0 = max(0, (w - self.width) // 2)
        return img[y0:y0 + self.height, x0:x0 + self.width]


class RandomCrop(ImageTransform):
    def __init__(self, height: int, width: int, seed: Optional[int] = None):
        self.height = height
        self.width = width
        self._rng = random.Random(seed)

    def apply(self, img):
        h, w = img.shape[:2]
        y0 = self._rng.randint(0, max(0, h - self.height))
        x0 = self._rng.randint(0, max(0, w - self.width))
        return img[y0:y0 + self.height, x0:x0 + self.width]


class FixedCrop(ImageTransform):
    """Crop by absolute or normalized box (reference ``Crop.scala``)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def apply(self, img):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        return img[int(y1):int(y2), int(x1):int(x2)]


class HFlip(ImageTransform):
    def apply(self, img):
        return np.ascontiguousarray(img[:, ::-1])


class Brightness(ImageTransform):
    """Add a random delta in [delta_low, delta_high] (reference
    ``Brightness.scala``)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self._rng = random.Random(seed)

    def apply(self, img):
        delta = self._rng.uniform(self.low, self.high)
        return np.asarray(img, np.float32) + delta


class Contrast(ImageTransform):
    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self._rng = random.Random(seed)

    def apply(self, img):
        return np.asarray(img, np.float32) * self._rng.uniform(self.low,
                                                               self.high)


class Saturation(ImageTransform):
    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self._rng = random.Random(seed)

    def apply(self, img):
        f = self._rng.uniform(self.low, self.high)
        img = np.asarray(img, np.float32)
        gray = img.mean(axis=-1, keepdims=True)
        return gray + (img - gray) * f


class Hue(ImageTransform):
    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: Optional[int] = None):
        self.low, self.high = delta_low, delta_high
        self._rng = random.Random(seed)

    def apply(self, img):
        delta = self._rng.uniform(self.low, self.high)
        img = np.asarray(img, np.float32)
        if cv2 is None:
            return img
        hsv = cv2.cvtColor(np.clip(img, 0, 255).astype(np.uint8),
                           cv2.COLOR_BGR2HSV).astype(np.float32)
        hsv[..., 0] = (hsv[..., 0] + delta) % 180
        return cv2.cvtColor(hsv.astype(np.uint8),
                            cv2.COLOR_HSV2BGR).astype(np.float32)


class ColorJitter(ImageTransform):
    """Random brightness/contrast/saturation in random order (reference
    ``ColorJitter.scala``)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)
        self.ops = [Brightness(seed=seed), Contrast(seed=seed),
                    Saturation(seed=seed)]

    def apply(self, img):
        ops = list(self.ops)
        self._rng.shuffle(ops)
        for op in ops:
            img = op.apply(img)
        return img


class Expand(ImageTransform):
    """Place the image on a larger mean-filled canvas (reference
    ``Expand.scala``)."""

    def __init__(self, means: Sequence[float] = (123, 117, 104),
                 max_ratio: float = 4.0, seed: Optional[int] = None):
        self.means = means
        self.max_ratio = max_ratio
        self._rng = random.Random(seed)

    def apply(self, img):
        h, w, c = img.shape
        ratio = self._rng.uniform(1.0, self.max_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        canvas = np.empty((nh, nw, c), np.float32)
        canvas[:] = np.asarray(self.means, np.float32)[:c]
        y0 = self._rng.randint(0, nh - h)
        x0 = self._rng.randint(0, nw - w)
        canvas[y0:y0 + h, x0:x0 + w] = img
        return canvas


class ChannelNormalize(ImageTransform):
    def __init__(self, mean: Sequence[float], std: Sequence[float] = (1, 1, 1)):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class ChannelOrder(ImageTransform):
    """BGR↔RGB swap (reference ``ChannelOrder.scala``)."""

    def apply(self, img):
        return np.ascontiguousarray(np.asarray(img)[..., ::-1])


class MatToFloats(ImageTransform):
    """uint8 HWC → float32 (reference ``MatToFloats.scala``)."""

    def apply(self, img):
        return np.asarray(img, np.float32)


class PixelBytesToMat(ImageTransform):
    """Decode encoded image bytes (jpg/png) → HWC array (reference
    ``PixelBytesToMat.scala``/``BytesToMat``)."""

    def apply(self, data):
        buf = np.frombuffer(bytes(data), np.uint8)
        if cv2 is None:
            raise RuntimeError("cv2 unavailable: cannot decode image bytes")
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if img is None:
            raise ValueError("undecodable image bytes")
        return img


class RandomPreprocessing(ImageTransform):
    """Apply the wrapped transform with probability p (reference
    ``RandomPreprocessing``)."""

    def __init__(self, transform: ImageTransform, prob: float = 0.5,
                 seed: Optional[int] = None):
        self.transform = transform
        self.prob = prob
        self._rng = random.Random(seed)

    def apply(self, img):
        if self._rng.random() < self.prob:
            return self.transform.apply(img)
        return img


RandomTransformer = RandomPreprocessing  # reference alias


class ImageSetToSample(ImageTransform):
    """Finalize: float32 HWC contiguous (the model-feed record; reference
    ``ImageSetToSample.scala``). Conv layers are NHWC, so no transpose."""

    def apply(self, img):
        return np.ascontiguousarray(np.asarray(img, np.float32))


class VFlip(ImageTransform):
    """Vertical flip (reference ``ImageMirror``'s vertical mode)."""

    def apply(self, img):
        return np.ascontiguousarray(np.asarray(img)[::-1])


Mirror = HFlip  # reference alias (``ImageMirror.scala``)


class Filler(ImageTransform):
    """Fill a normalized-coordinate sub-rectangle with a constant (reference
    ``ImageFiller.scala`` — occlusion augmentation)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0):
        if not (0 <= start_x <= end_x <= 1 and 0 <= start_y <= end_y <= 1):
            raise ValueError("filler coords must satisfy "
                             "0 <= start <= end <= 1")
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def apply(self, img):
        img = np.array(img, np.float32, copy=True)
        h, w = img.shape[:2]
        x0, y0, x1, y1 = self.box
        img[int(y0 * h):int(y1 * h), int(x0 * w):int(x1 * w)] = self.value
        return img


class ChannelScaledNormalizer(ImageTransform):
    """Per-channel mean subtract + single global scale (reference
    ``ImageChannelScaledNormalizer.scala``)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 scale: float = 1.0):
        self.mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self.scale = scale

    def apply(self, img):
        return (np.asarray(img, np.float32) - self.mean) * self.scale


class PixelNormalizer(ImageTransform):
    """Subtract a full per-pixel mean image (reference
    ``ImagePixelNormalizer.scala`` — e.g. the ImageNet mean image)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply(self, img):
        img = np.asarray(img, np.float32)
        if img.shape != self.means.shape:
            raise ValueError(f"mean image shape {self.means.shape} != image "
                             f"shape {img.shape}")
        return img - self.means


class RandomResize(ImageTransform):
    """Resize to a size drawn uniformly from [min, max] (reference
    ``ImageRandomResize.scala``)."""

    def __init__(self, min_size: int, max_size: int,
                 seed: Optional[int] = None):
        self.min_size, self.max_size = min_size, max_size
        self._rng = random.Random(seed)

    def apply(self, img):
        size = self._rng.randint(self.min_size, self.max_size)
        return Resize(size, size).apply(img)


class RandomAspectScale(ImageTransform):
    """Scale the short side to a randomly chosen length, capped by
    ``max_size`` on the long side (reference ``RandomAspectScale``)."""

    def __init__(self, scales: Sequence[int], max_size: int = 1000,
                 seed: Optional[int] = None):
        self.scales = list(scales)
        self.max_size = max_size
        self._rng = random.Random(seed)

    def apply(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        target = self._rng.choice(self.scales)
        scale = target / min(h, w)
        if round(scale * float(np.max((h, w)))) > self.max_size:
            scale = self.max_size / float(np.max((h, w)))
        return Resize(int(round(h * scale)),
                      int(round(w * scale))).apply(img)


class Grayscale(ImageTransform):
    """RGB → single-channel luma, kept 3-channel for shape stability."""

    def apply(self, img):
        img = np.asarray(img, np.float32)
        luma = img @ np.asarray([0.299, 0.587, 0.114], np.float32)
        return np.repeat(luma[..., None], img.shape[-1], axis=-1)
