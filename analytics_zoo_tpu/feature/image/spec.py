"""Declarative preprocessing specs — the serializable half of the image
transform chains.

The reference ships per-model preprocessing inside its pretrained artifacts
(``ImageClassificationConfig.scala``, ``ObjectDetectionConfig.scala``: each
variant names its resize/normalize chain). The TPU bundle format stores the
same information as a JSON list of ``{"op": name, ...kwargs}`` steps;
:func:`build_preprocessing` turns a spec back into a runnable
``Preprocessing`` chain. Only deterministic inference-time ops belong in a
spec — training augmentations (random crops/flips) are code, not artifact
metadata.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from .transforms import (AspectScale, CenterCrop, ChannelNormalize,
                         ChannelOrder, Grayscale, ImageSetToSample,
                         MatToFloats, Resize)

SPEC_OPS: Dict[str, type] = {
    "resize": Resize,
    "aspect_scale": AspectScale,
    "center_crop": CenterCrop,
    "channel_normalize": ChannelNormalize,
    "channel_order": ChannelOrder,
    "mat_to_floats": MatToFloats,
    "grayscale": Grayscale,
    "to_sample": ImageSetToSample,
}


def build_preprocessing(spec: Sequence[Dict[str, Any]]):
    """``[{"op": "resize", "height": 224, "width": 224}, ...]`` → chained
    ``Preprocessing``. Returns None for an empty/None spec."""
    if not spec:
        return None
    chain = None
    for step in spec:
        step = dict(step)
        op = step.pop("op")
        if op not in SPEC_OPS:
            raise ValueError(f"unknown preprocessing op {op!r} in bundle "
                             f"spec; supported: {sorted(SPEC_OPS)}")
        t = SPEC_OPS[op](**step)
        chain = t if chain is None else (chain >> t)
    return chain


def classification_spec(height: int, width: int, mean: Sequence[float],
                        std: Sequence[float]) -> List[Dict[str, Any]]:
    """The standard classifier chain (resize → normalize → sample)."""
    return [{"op": "resize", "height": height, "width": width},
            {"op": "channel_normalize", "mean": list(mean),
             "std": list(std)},
            {"op": "to_sample"}]
