"""Box-aware detection augmentation (reference: the SSD train pipeline
``models/image/objectdetection/ssd/RoiImageToSSDBatch.scala`` with BigDL's
roi-aware vision transforms — RandomSampler crop, expand, flip — plus
``feature/image/roi/RoiRecordToFeature.scala``).

Records are ``(image HWC, boxes [N, 4], labels [N])`` with boxes in
normalized corner form ``[x0, y0, x1, y1]`` in ``[0, 1]`` — the same
convention the anchor machinery in ``models/image/objectdetection`` uses,
so these chain straight into ``ObjectDetector.encode_batch``. All ops are
host-side numpy (cheap per-record bookkeeping); the heavy lifting stays in
the device step.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import numpy as np

from ..preprocessing import Preprocessing

Record = Tuple[np.ndarray, np.ndarray, np.ndarray]


def _unpack(record: Any) -> Record:
    img, boxes, labels = record
    return (np.asarray(img), np.asarray(boxes, np.float32),
            np.asarray(labels))


class RandomHFlipWithBoxes(Preprocessing):
    """Horizontal flip of image + boxes with probability ``p``."""

    def __init__(self, p: float = 0.5, seed: Optional[int] = None):
        self.p = p
        self.rs = np.random.RandomState(seed)

    def apply(self, record: Any) -> Record:
        img, boxes, labels = _unpack(record)
        if self.rs.rand() >= self.p:
            return img, boxes, labels
        img = img[:, ::-1]
        if len(boxes):
            boxes = boxes.copy()
            x0 = boxes[:, 0].copy()
            boxes[:, 0] = 1.0 - boxes[:, 2]
            boxes[:, 2] = 1.0 - x0
        return np.ascontiguousarray(img), boxes, labels


class ExpandWithBoxes(Preprocessing):
    """Zoom-out: place the image on a larger filled canvas (reference/SSD
    ``Expand``). Teaches the detector small objects."""

    def __init__(self, max_ratio: float = 4.0, fill=0.0, p: float = 0.5,
                 seed: Optional[int] = None):
        self.max_ratio = max_ratio
        self.fill = fill
        self.p = p
        self.rs = np.random.RandomState(seed)

    def apply(self, record: Any) -> Record:
        img, boxes, labels = _unpack(record)
        if self.rs.rand() >= self.p:
            return img, boxes, labels
        h, w = img.shape[:2]
        ratio = self.rs.uniform(1.0, self.max_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        top = self.rs.randint(0, nh - h + 1)
        left = self.rs.randint(0, nw - w + 1)
        canvas = np.full((nh, nw) + img.shape[2:], self.fill, img.dtype)
        canvas[top:top + h, left:left + w] = img
        if len(boxes):
            boxes = boxes.copy()
            boxes[:, [0, 2]] = (boxes[:, [0, 2]] * w + left) / nw
            boxes[:, [1, 3]] = (boxes[:, [1, 3]] * h + top) / nh
        return canvas, boxes, labels


def _iou_with_crop(boxes: np.ndarray, crop: np.ndarray) -> np.ndarray:
    ix0 = np.maximum(boxes[:, 0], crop[0])
    iy0 = np.maximum(boxes[:, 1], crop[1])
    ix1 = np.minimum(boxes[:, 2], crop[2])
    iy1 = np.minimum(boxes[:, 3], crop[3])
    inter = np.clip(ix1 - ix0, 0, None) * np.clip(iy1 - iy0, 0, None)
    area_b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    area_c = (crop[2] - crop[0]) * (crop[3] - crop[1])
    return inter / np.clip(area_b + area_c - inter, 1e-9, None)


class RandomSampleCrop(Preprocessing):
    """SSD RandomSampler: pick a crop whose IoU with at least one ground-
    truth box satisfies a randomly chosen constraint, keep the boxes whose
    centers fall inside, clip and renormalize them. ``None`` in
    ``min_ious`` means "keep the whole image" for that draw."""

    def __init__(self, min_ious: Sequence[Optional[float]] =
                 (None, 0.1, 0.3, 0.5, 0.7, 0.9),
                 max_trials: int = 50, min_scale: float = 0.3,
                 seed: Optional[int] = None):
        self.min_ious = tuple(min_ious)
        self.max_trials = max_trials
        self.min_scale = min_scale
        self.rs = np.random.RandomState(seed)

    def apply(self, record: Any) -> Record:
        img, boxes, labels = _unpack(record)
        min_iou = self.min_ious[self.rs.randint(len(self.min_ious))]
        if min_iou is None or not len(boxes):
            return img, boxes, labels
        h, w = img.shape[:2]
        for _ in range(self.max_trials):
            cw = self.rs.uniform(self.min_scale, 1.0)
            ch = self.rs.uniform(self.min_scale, 1.0)
            if not 0.5 <= cw / ch <= 2.0:  # aspect-ratio guard (SSD paper)
                continue
            cx0 = self.rs.uniform(0, 1.0 - cw)
            cy0 = self.rs.uniform(0, 1.0 - ch)
            crop = np.array([cx0, cy0, cx0 + cw, cy0 + ch], np.float32)
            if _iou_with_crop(boxes, crop).max() < min_iou:
                continue
            centers = (boxes[:, :2] + boxes[:, 2:]) / 2
            keep = ((centers[:, 0] > crop[0]) & (centers[:, 0] < crop[2])
                    & (centers[:, 1] > crop[1]) & (centers[:, 1] < crop[3]))
            if not keep.any():
                continue
            px0, py0 = int(crop[0] * w), int(crop[1] * h)
            px1, py1 = int(crop[2] * w), int(crop[3] * h)
            out = np.ascontiguousarray(img[py0:py1, px0:px1])
            kept = boxes[keep].copy()
            kept[:, [0, 2]] = (np.clip(kept[:, [0, 2]], crop[0], crop[2])
                               - crop[0]) / (crop[2] - crop[0])
            kept[:, [1, 3]] = (np.clip(kept[:, [1, 3]], crop[1], crop[3])
                               - crop[1]) / (crop[3] - crop[1])
            return out, kept, labels[keep]
        return img, boxes, labels


class ResizeWithBoxes(Preprocessing):
    """Resize the image; normalized boxes are scale-invariant so they pass
    through unchanged. Terminal op before batching for the static-shape
    device step."""

    def __init__(self, height: int, width: int):
        self.height = height
        self.width = width

    def apply(self, record: Any) -> Record:
        img, boxes, labels = _unpack(record)
        from .transforms import Resize  # shares Resize's no-cv2 fallback
        return Resize(self.height, self.width).apply(img), boxes, labels
