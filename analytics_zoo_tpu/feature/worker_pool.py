"""Multiprocess shared-memory transform workers — the host data plane's
answer to the GIL.

The reference keeps ingest ahead of the engine with cached-RDD iterators and
per-core replica threads (``FeatureSet.scala:230``); a JVM thread pool
parallelizes *Scala* transforms for free. The Python equivalent does not
exist: a ``ThreadPoolExecutor`` only helps transforms that release the GIL
(PIL, numpy decoders) — a pure-Python ``Preprocessing`` chain serializes on
the interpreter lock no matter how many threads it is given. This module is
the way past it:

- workers are **forked** processes, so the source feature arrays and the
  (arbitrary, closure-capturing, unpicklable) transform chain are inherited
  by address-space copy — nothing is pickled per task but a small index
  array;
- each worker applies the chain to its record range and writes the stacked
  result straight into a preallocated ``multiprocessing.shared_memory``
  slab (``MAP_SHARED`` pages created BEFORE the fork, so parent and child
  numpy views address the same physical memory);
- the consumer gets **zero-copy numpy views** into the slab — results never
  transit a pipe.

The fleet plumbing (claim/done ledger, death sweep + respawn, transient-task
retries, teardown) lives in :class:`WorkerPoolBase` so other forked worker
fleets — the XShard ETL pool in ``xshard/engine.py`` — reuse the exact same
self-healing protocol with their own task payloads.

Slot ownership contract: a view yielded by :meth:`TransformWorkerPool.
map_index_batches` is valid until ``slots - 1`` further batches have been
drawn (the slot is then handed back to a worker). Consumers that forward
batches into a DeviceFeed satisfy this by construction as long as
``data.shm_slots`` exceeds the feed's prefetch depth + 2.

Workers must not touch jax — they are forked from a process with a live
XLA runtime and only ever run numpy/pure-Python transform code.
"""
from __future__ import annotations

import atexit
import itertools
import logging
import multiprocessing as mp
import os
import queue as queue_mod
import signal
import threading
import time
import traceback
import warnings
from multiprocessing import shared_memory
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..common import faults
from ..common import metrics as _metrics
from ..common.utils import time_it

_ALIGN = 128  # slab leaf alignment (cache-line / vector friendly)

logger = logging.getLogger(__name__)

#: task latency is observed INSIDE the forked child — the shared-memory
#: metric slab (created before the fork) makes it visible to the parent's
#: exposition, the proof-of-life for the registry's fork-safety
_M_TASK = _metrics.histogram(
    "worker.task_seconds",
    "Transform-worker task latency (observed in the forked child).")
_M_RESPAWN = _metrics.counter(
    "worker.respawn_total",
    "Transform workers respawned after dying mid-task (SIGKILL/OOM).")


class TransformWorkerError(RuntimeError):
    """A transform raised inside a worker process; carries the worker-side
    traceback so the failure reads as if it happened in the consumer."""


def fork_available() -> bool:
    return "fork" in mp.get_all_start_methods()


def default_workers() -> int:
    cfg = int(os.environ.get("ZOO_TPU_DATA_NUM_WORKERS", "0") or 0)
    if cfg > 0:
        return cfg
    return max(1, min(4, os.cpu_count() or 1))


# -- record-tree plumbing (mirrors featureset's ArrayTree convention) --------


def _index_tree(tree, i: int):
    if isinstance(tree, tuple):
        return tuple(t[i] for t in tree)
    if isinstance(tree, dict):
        return {k: v[i] for k, v in tree.items()}
    return tree[i]


def _record_leaves(record) -> List[np.ndarray]:
    if isinstance(record, tuple):
        return [np.asarray(r) for r in record]
    if isinstance(record, dict):
        return [np.asarray(record[k]) for k in record]
    return [np.asarray(record)]


class TreeSpec:
    """Shape/dtype/structure of one transformed record: the slab layout."""

    def __init__(self, record):
        if isinstance(record, tuple):
            self.kind, self.keys = "tuple", len(record)
        elif isinstance(record, dict):
            self.kind, self.keys = "dict", list(record)
        else:
            self.kind, self.keys = "array", None
        leaves = _record_leaves(record)
        self.shapes = [tuple(l.shape) for l in leaves]
        self.dtypes = [l.dtype for l in leaves]
        for dt in self.dtypes:
            if dt.hasobject:
                raise ValueError(
                    "shared-memory transform workers need numeric record "
                    "leaves; an object-dtype output cannot live in a slab "
                    "(use transform_mode='thread' or 'loop')")

    def _leaf_blocks(self, rows: int):
        """Leaf-major slab layout: per leaf one contiguous ``rows × record``
        block, block starts aligned to ``_ALIGN``."""
        offset = 0
        for shape, dtype in zip(self.shapes, self.dtypes):
            offset = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            yield offset, shape, dtype
            offset += nbytes * rows
        yield offset, None, None  # total size sentinel

    def slab_bytes(self, rows: int) -> int:
        return max(1, list(self._leaf_blocks(rows))[-1][0])

    def slab_views(self, shm, rows: int) -> List[np.ndarray]:
        """Numpy views over one slab: one ``[rows, *leaf_shape]`` array per
        leaf at its aligned block offset."""
        return [np.ndarray((rows,) + shape, dtype=dtype, buffer=shm.buf,
                           offset=offset)
                for offset, shape, dtype in self._leaf_blocks(rows)
                if shape is not None]

    def tree(self, views: Sequence[np.ndarray]):
        if self.kind == "tuple":
            return tuple(views)
        if self.kind == "dict":
            return {k: v for k, v in zip(self.keys, views)}
        return views[0]

    def slice(self, views: Sequence[np.ndarray], n: int):
        return self.tree([v[:n] for v in views])


def _write_record(views: Sequence[np.ndarray], row: int, record) -> None:
    for view, leaf in zip(views, _record_leaves(record)):
        view[row] = leaf


# -- worker side -------------------------------------------------------------


def _worker_main(wid, features, transform, slot_views, task_q,
                 result_q) -> None:
    """Forked worker loop. Everything in ``args`` arrived by fork
    inheritance (no pickling): the source feature tree, the transform
    chain, and numpy views over the MAP_SHARED slabs.

    Protocol on ``result_q`` (a SimpleQueue — its ``put`` is a
    SYNCHRONOUS locked pipe write, so a message that returned is
    delivered even if the worker is SIGKILLed the next instruction; an
    mp.Queue's feeder thread could lose it):

    - ``("claim", tid, wid)`` before touching a task — the parent's
      death ledger: if this worker dies, the parent knows exactly which
      task to resubmit to the respawned replacement;
    - ``("done", tid, rows, err)`` on completion or error.
    """
    from ..utils.trace import set_thread_label
    set_thread_label(f"worker-{wid}")
    while True:
        task = task_q.get()
        if task is None:
            return
        task_id, (slot, row0, idx) = task
        result_q.put(("claim", task_id, wid))
        try:
            # chaos sites: a hard self-SIGKILL mid-batch (pool self-healing
            # must respawn + resubmit) and a transient task failure (task
            # retry budget must absorb it)
            if faults.inject("worker.kill"):
                os.kill(os.getpid(), signal.SIGKILL)
            faults.inject("worker.task")
            t0 = time.perf_counter()
            # the time_it span lands in any active trace session via the
            # child-side spool, pid-tagged — worker activity is visible on
            # the same Perfetto timeline as the consumer threads
            with time_it("worker.task"):
                views = slot_views[slot]
                for j, i in enumerate(idx):
                    rec = transform.apply(_index_tree(features, int(i)))
                    _write_record(views, row0 + j, rec)
            _M_TASK.observe(time.perf_counter() - t0)
            result_q.put(("done", task_id, len(idx), None))
        except BaseException:
            result_q.put(("done", task_id, 0, traceback.format_exc()))


# -- parent side -------------------------------------------------------------


class WorkerPoolBase:
    """Generic parent-side plumbing for a fixed fleet of forked workers.

    Subclasses provide ``_spawn_worker`` (the Process target + inherited
    state) and a task payload convention; the base owns everything that
    makes the fleet survivable — the SimpleQueue claim/done ledger, the
    death sweep with the ``data.worker_respawns`` budget, per-task error
    retries (``data.task_retries``), ordered collection, and teardown.
    The wire protocol is ``(tid, payload)`` on the task queue and
    ``("claim", tid, wid)`` / ``("done", tid, result, err)`` back.
    """

    _live: "Dict[int, WorkerPoolBase]" = {}
    _kind = "worker"  # noun used in error/log messages
    _error_cls: type = RuntimeError
    _respawn_metric = _M_RESPAWN

    def _init_pool(self, num_workers: int) -> None:
        """Create queues, fork the fleet, and arm the ledgers. Subclass
        ``__init__`` must have staged every attribute ``_spawn_worker``
        reads (slab views, inherited state) before calling this."""
        if not fork_available():
            raise RuntimeError(
                f"{type(self).__name__} requires the fork start method "
                f"(POSIX); use the thread transform mode instead")
        from ..common.config import global_config
        cfg = global_config()
        self.num_workers = int(num_workers)
        self._ctx = mp.get_context("fork")
        self._task_q = self._ctx.SimpleQueue()
        # SimpleQueue, NOT mp.Queue: workers put results with a synchronous
        # locked pipe write — a SIGKILLed child cannot strand a message in
        # an unflushed feeder thread, so the parent's claim/done ledger
        # stays exact through hard kills
        self._result_q = self._ctx.SimpleQueue()
        self._procs: List[mp.Process] = []
        for wid in range(self.num_workers):
            self._procs.append(self._spawn_worker(wid))
        self._task_counter = itertools.count()
        self._outstanding: set = set()
        self._results: Dict[int, Tuple[Any, Optional[str]]] = {}
        self._tasks: Dict[int, Any] = {}
        self._claimed: Dict[int, int] = {}  # tid -> wid (death ledger)
        self._retried: Dict[int, int] = {}  # tid -> error-retry count
        self._task_retries = int(cfg.get("data.task_retries") or 0)
        self._respawns_left = int(cfg.get("data.worker_respawns") or 0)
        self._closed = False
        self._lock = threading.Lock()
        WorkerPoolBase._live[id(self)] = self

    def _spawn_worker(self, wid: int) -> mp.Process:
        raise NotImplementedError

    def _fork_process(self, wid: int, target, args) -> mp.Process:
        with warnings.catch_warnings():
            # jax warns on fork of its multithreaded parent; the children
            # never touch jax (numpy/pandas-only task loops), so the
            # warning is noise here
            warnings.simplefilter("ignore")
            p = self._ctx.Process(
                target=target, args=args, daemon=True,
                name=f"zoo-{self._kind}-worker-{wid}")
            p.start()
        return p

    # -- task plumbing -------------------------------------------------------

    def _submit_payload(self, payload) -> int:
        tid = next(self._task_counter)
        self._outstanding.add(tid)
        self._tasks[tid] = payload  # kept for resubmission
        self._task_q.put((tid, payload))
        return tid

    def _resubmit(self, tid: int) -> None:
        self._task_q.put((tid, self._tasks[tid]))

    def _result_get(self, timeout: float):
        """``SimpleQueue.get`` with a timeout (single consumer thread —
        the poll/recv pair cannot interleave with another reader)."""
        if not self._result_q._reader.poll(timeout):
            raise queue_mod.Empty
        return self._result_q.get()

    def _check_workers(self) -> None:
        """Death sweep: a child that exited nonzero (SIGKILL, OOM, abort)
        is respawned — fork inherits the same state and slab views — and
        whatever task it had claimed is resubmitted, so the consumer never
        hangs on a result that can no longer arrive. Once the respawn
        budget (``data.worker_respawns``) is spent, the death surfaces
        promptly as the pool's error class instead."""
        for wid, p in enumerate(self._procs):
            if p.is_alive() or p.exitcode in (0, None):
                continue
            lost = [tid for tid, w in self._claimed.items() if w == wid]
            if self._respawns_left <= 0:
                raise self._error_cls(
                    f"{self._kind} worker died with exit code {p.exitcode} "
                    f"(killed? OOM?) and the respawn budget is exhausted; "
                    f"raise data.worker_respawns to self-heal") from None
            self._respawns_left -= 1
            self._respawn_metric.inc()
            logger.warning(
                "%s worker %d died with exit code %s; respawning "
                "(%d respawns left) and resubmitting %d lost task(s)",
                self._kind, wid, p.exitcode, self._respawns_left, len(lost))
            self._procs[wid] = self._spawn_worker(wid)
            for tid in lost:
                self._claimed.pop(tid, None)
                # only a task still outstanding can be lost; a 'done' that
                # beat the death into the pipe wins (put is synchronous)
                if tid in self._outstanding and tid not in self._results:
                    self._resubmit(tid)

    def _pump(self, timeout: float) -> bool:
        """Drain one protocol message (or run the death sweep on a quiet
        queue). Returns True when a message was processed."""
        try:
            msg = self._result_get(timeout)
        except queue_mod.Empty:
            self._check_workers()
            return False
        if msg[0] == "claim":
            _, tid, wid = msg
            self._claimed[tid] = wid
            return True
        _, tid, result, err = msg
        self._claimed.pop(tid, None)
        if err is not None and self._retried.get(tid, 0) < self._task_retries:
            # transient-task resilience: burn one retry and re-run the
            # task (same slot rows — a failed attempt's partial writes are
            # simply overwritten)
            self._retried[tid] = self._retried.get(tid, 0) + 1
            logger.warning(
                "%s task %d failed (retry %d/%d):\n%s", self._kind, tid,
                self._retried[tid], self._task_retries, err)
            self._resubmit(tid)
            return True
        self._outstanding.discard(tid)
        self._results[tid] = (result, err)
        self._tasks.pop(tid, None)
        self._retried.pop(tid, None)
        return True

    def _collect(self, tid: int, timeout: float = 300.0):
        """Block until task ``tid`` finished; returns its result payload.
        Polls in short slices so a dead child is noticed (and healed or
        surfaced) within ~0.2s, not only when the whole queue goes
        quiet."""
        deadline = time.monotonic() + timeout
        while tid not in self._results:
            if not self._pump(timeout=0.2):
                if time.monotonic() > deadline:
                    raise self._error_cls(
                        f"timed out waiting for a {self._kind} "
                        f"worker") from None
        result, err = self._results.pop(tid)
        if err is not None:
            raise self._error_cls(
                f"{self._kind} raised inside a worker process:\n" + err)
        return result

    def _drain_outstanding(self) -> None:
        """Wait out tasks abandoned by a closed consumer generator, so
        their slots are genuinely free before new tasks reuse them."""
        for tid in sorted(self._outstanding):
            try:
                self._collect(tid)
            except self._error_cls:
                pass  # an abandoned task's error has no consumer left

    # -- lifecycle -----------------------------------------------------------

    def _release_resources(self) -> None:
        """Subclass hook: free slabs/files owned by the pool."""

    def close(self, unlink: bool = True) -> None:
        """Stop workers and release resources. Safe to call repeatedly.
        With ``unlink=False`` shared segments stay mapped (a caller
        keeping zero-copy views alive unlinks later)."""
        if getattr(self, "_closed", True):
            return
        self._closed = True
        WorkerPoolBase._live.pop(id(self), None)
        try:
            for _ in self._procs:
                self._task_q.put(None)
        except Exception:
            pass
        for p in self._procs:
            p.join(timeout=2)
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            if p.is_alive():
                p.join(timeout=2)
            if p.is_alive():
                p.kill()
                p.join(timeout=2)
        close_q = getattr(self._result_q, "close", None)
        if close_q is not None:  # SimpleQueue.close (3.9+): release pipes
            close_q()
        if unlink:
            self._release_resources()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TransformWorkerPool(WorkerPoolBase):
    """Fixed fleet of forked transform workers over shared-memory slabs.

    ``rows`` is the slab height (max records per task — the batch size for
    streaming use, the dataset size for one-shot :func:`transform_all`
    use); ``slots`` is how many independent slabs cycle through the
    workers (the pipeline depth).
    """

    _kind = "transform"
    _error_cls = TransformWorkerError
    _respawn_metric = _M_RESPAWN

    def __init__(self, features, transform, rows: int,
                 slots: int = 4, num_workers: Optional[int] = None,
                 sample_record=None):
        self._closed = True  # armed by _init_pool; keeps __del__ safe
        if not fork_available():
            raise RuntimeError(
                "TransformWorkerPool requires the fork start method "
                "(POSIX); use the thread transform mode instead")
        if sample_record is None:
            sample_record = transform.apply(_index_tree(features, 0))
        self.spec = TreeSpec(sample_record)
        self.rows = int(rows)
        self.slots = max(1, int(slots))
        slab_bytes = self.spec.slab_bytes(self.rows)
        self._shms: List[shared_memory.SharedMemory] = []
        self._slot_views: List[List[np.ndarray]] = []
        for _ in range(self.slots):
            shm = shared_memory.SharedMemory(create=True, size=slab_bytes)
            self._shms.append(shm)
            self._slot_views.append(self.spec.slab_views(shm, self.rows))
        self._features = features
        self._transform = transform
        self._init_pool(int(num_workers) if num_workers
                        else default_workers())

    def _spawn_worker(self, wid: int) -> mp.Process:
        return self._fork_process(
            wid, _worker_main,
            (wid, self._features, self._transform, self._slot_views,
             self._task_q, self._result_q))

    def _submit(self, slot: int, row0: int, idx: np.ndarray) -> int:
        idx = np.ascontiguousarray(idx, dtype=np.int64)
        return self._submit_payload((slot, row0, idx))

    # -- high-level consumers ------------------------------------------------

    def map_index_batches(self, idx_iter: Iterator[np.ndarray]
                          ) -> Iterator[Tuple[np.ndarray, Any]]:
        """Order-preserving pipelined map: yields ``(idx, view_tree)`` per
        input index batch, keeping up to ``slots`` batches in flight.
        The yielded tree is a zero-copy slab view valid until ``slots - 1``
        further batches are drawn."""
        if not self._lock.acquire(blocking=False):
            # a blocking wait here would DEADLOCK when the owner is a
            # suspended generator on this same thread (train iterator
            # paused mid-validation) — refuse loudly instead; callers that
            # need concurrent streams use one pool per stream
            raise RuntimeError(
                "TransformWorkerPool is already streaming another batch "
                "sequence; use a separate pool per concurrent iterator")
        try:
            self._drain_outstanding()
            it = iter(idx_iter)
            inflight: Dict[int, Tuple[int, np.ndarray]] = {}
            next_seq = 0

            def submit_one():
                nonlocal next_seq
                idx = next(it)  # propagates StopIteration to the caller
                if len(idx) > self.rows:
                    raise ValueError(
                        f"index batch of {len(idx)} exceeds the pool's "
                        f"slab height {self.rows}")
                seq = next_seq
                tid = self._submit(seq % self.slots, 0, idx)
                inflight[seq] = (tid, idx)
                next_seq += 1

            for _ in range(self.slots):
                try:
                    submit_one()
                except StopIteration:
                    break
            yield_seq = 0
            while yield_seq < next_seq:
                tid, idx = inflight.pop(yield_seq)
                n = self._collect(tid)
                yield idx, self.spec.slice(
                    self._slot_views[yield_seq % self.slots], n)
                # resumed: the consumer released the oldest view — its slot
                # may take the next task
                try:
                    submit_one()
                except StopIteration:
                    pass
                yield_seq += 1
        finally:
            self._lock.release()

    def transform_rows(self, indices: np.ndarray, slot: int = 0,
                       chunk: Optional[int] = None) -> int:
        """One-shot scatter: transform ``indices`` into slab ``slot`` rows
        ``0..len(indices)`` using every worker (range-chunked). Blocks
        until complete; returns rows written."""
        if not self._lock.acquire(blocking=False):
            raise RuntimeError(
                "TransformWorkerPool is already streaming another batch "
                "sequence; use a separate pool per concurrent consumer")
        try:
            self._drain_outstanding()
            n = len(indices)
            if n > self.rows:
                raise ValueError(f"{n} rows exceed slab height {self.rows}")
            if chunk is None:
                chunk = max(1, -(-n // (self.num_workers * 4)))
            tids = [self._submit(slot, r0, indices[r0:r0 + chunk])
                    for r0 in range(0, n, chunk)]
            for tid in tids:
                self._collect(tid)
            return n
        finally:
            self._lock.release()

    def slot_tree(self, slot: int = 0, n: Optional[int] = None):
        return self.spec.slice(self._slot_views[slot],
                               self.rows if n is None else n)

    # -- lifecycle -----------------------------------------------------------

    def _release_resources(self) -> None:
        self.release_slabs()

    def release_slabs(self) -> None:
        self._slot_views = []
        for shm in self._shms:
            try:
                shm.close()
            except BufferError:
                pass  # a consumer still holds views; the unlink below
                # still frees the NAME — memory goes when the views do
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        self._shms = []

    def detach_slabs(self) -> List[shared_memory.SharedMemory]:
        """Hand slab ownership to the caller (used by transform_all to keep
        zero-copy result arrays alive past the pool)."""
        shms, self._shms = self._shms, []
        return shms


@atexit.register
def _close_live_pools() -> None:
    # interpreter exit must not strand worker processes or /dev/shm segments
    for pool in list(WorkerPoolBase._live.values()):
        try:
            pool.close()
        except Exception:
            pass


class SlabKeepAlive:
    """Owns unlinked shared-memory mappings backing zero-copy result
    arrays: the segments' names are already gone from /dev/shm (crash-safe
    — no leak even on SIGKILL), the pages free when the last view dies."""

    def __init__(self, shms: List[shared_memory.SharedMemory]):
        self._shms = shms
        for shm in shms:
            try:
                shm.unlink()
            except FileNotFoundError:
                pass

    def __del__(self):
        for shm in self._shms:
            try:
                shm.close()
            except Exception:
                pass  # views may still be exported; pages free with them


def transform_all(features, size: int, transform,
                  num_workers: Optional[int] = None
                  ) -> Tuple[Any, SlabKeepAlive]:
    """Eagerly transform ``size`` records across forked workers into ONE
    full-dataset shared slab; returns ``(stacked_tree, keepalive)`` where
    the tree's arrays are zero-copy views into the slab (peak memory = one
    transformed copy, not records-list + stacked copy)."""
    pool = TransformWorkerPool(features, transform, rows=size, slots=1,
                               num_workers=num_workers)
    try:
        pool.transform_rows(np.arange(size, dtype=np.int64))
        tree = pool.slot_tree(0, size)
        keepalive = SlabKeepAlive(pool.detach_slabs())
    finally:
        pool.close()
    return tree, keepalive
