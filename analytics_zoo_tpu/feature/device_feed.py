"""Double-buffered host→device feed.

The reference hides host→engine latency behind cached-RDD iterators and
per-core replica threads; on TPU the equivalent is overlapping ``device_put``
(async dispatch) with the previous step's compute. ``DeviceFeed`` keeps
``prefetch`` batches in flight, each already sharded over the mesh's data
axis, so the TPU never waits on the host (SURVEY.md §7 hard part (c)).
"""
from __future__ import annotations

import collections
from typing import Any, Iterator, Optional

from jax.sharding import Mesh

from ..common.config import global_config
from .preprocessing import Preprocessing
from ..parallel.mesh import shard_batch


class DeviceFeed:
    def __init__(self, host_iterator: Iterator[Any], mesh: Mesh,
                 prefetch: Optional[int] = None):
        self._it = host_iterator
        self._mesh = mesh
        depth = prefetch if prefetch is not None else global_config().get("data.prefetch")
        self._depth = max(1, int(depth))
        self._buffer: collections.deque = collections.deque()

    def __iter__(self):
        return self

    def __next__(self):
        while len(self._buffer) < self._depth:
            try:
                batch = next(self._it)
            except StopIteration:
                break
            self._buffer.append(shard_batch(self._mesh, batch))
        if not self._buffer:
            raise StopIteration
        return self._buffer.popleft()
