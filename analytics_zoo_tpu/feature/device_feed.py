"""Double-buffered host→device feed.

The reference hides host→engine latency behind cached-RDD iterators and
per-core replica threads; on TPU the equivalent is overlapping host-side work
(shuffle gather, transforms) and ``device_put`` (async dispatch) with the
previous step's compute. ``DeviceFeed`` runs a background producer thread
that keeps ``prefetch`` batches in flight, each already sharded over the
mesh's data axis, so the TPU never waits on the host (SURVEY.md §7 hard
part (c)).

The feed is shape-agnostic: the host iterator may be endless (train) or
finite (eval/predict — the sentinel becomes ``StopIteration``), and
``shard_fn`` decides what of each item lands on device. Two helpers below
cover the evaluation contract: :func:`masked_eval_batches` turns
``FeatureSet.eval_iterator``'s ``(x, y, valid)`` stream into
``((x, y, mask), meta...)`` items with a host-computed float mask, and
:func:`shard_payload` shards only the leading payload of such an item while
per-batch metadata (valid counts) rides along host-side.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterator, List, Optional

import numpy as np
from jax.sharding import Mesh

from ..common import faults
from ..common import metrics as _metrics
from ..common import profiler as _profiler
from ..common.config import global_config
from ..parallel.mesh import shard_batch

_SENTINEL = object()

#: accumulated consumer time blocked waiting on the producer — the train
#: loop's "feed stall": nonzero growth here means the host data plane, not
#: the device, is the bottleneck
_M_STALL = _metrics.counter(
    "train.feed_stall_seconds_total",
    "Seconds the DeviceFeed consumer spent blocked on the host producer.")


def masked_eval_batches(it: Iterator[Any], batch_size: int,
                        with_labels: bool = True) -> Iterator[Any]:
    """Adapt an ``eval_iterator`` stream (``(x, y, valid)``) to feed items.

    Yields ``((x, y, mask), valid)`` (or ``((x, mask), valid)`` without
    labels): the payload a jitted masked eval step consumes plus the valid
    count as host-side metadata. The mask marks the real rows of padded
    tail batches, so pad rows contribute nothing on device.
    """
    # masks are content-constant per valid count: the arange is built once
    # and each distinct mask is cached, so the common full-batch case reuses
    # ONE array for the whole pass instead of allocating arange+mask per
    # batch (tail batches add at most a few distinct entries)
    positions = np.arange(batch_size)
    masks: dict = {batch_size: np.ones(batch_size, np.float32)}
    for x, y, valid in it:
        mask = masks.get(valid)
        if mask is None:
            mask = (positions < valid).astype(np.float32)
            masks[valid] = mask
        if with_labels:
            yield (x, y, mask), valid
        else:
            yield (x, mask), valid


def shard_payload(mesh: Mesh, item: Any) -> Any:
    """Shard function for ``(payload, meta...)`` feed items: the payload
    pytree is sharded over the mesh's data axis, everything after it stays
    host-side untouched (per-batch valid counts, record ids, ...)."""
    payload, *meta = item
    return (shard_batch(mesh, payload), *meta)


def _put_until_stopped(q: "queue.Queue", stop: threading.Event,
                       item: Any) -> bool:
    """Blocking put that aborts when ``stop`` is set. True if delivered."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _produce(it: Iterator[Any], mesh: Mesh, q: "queue.Queue",
             stop: threading.Event, errbox: List[BaseException],
             shard_fn) -> None:
    # module-level on purpose: the thread must NOT hold a reference to the
    # DeviceFeed, or an abandoned feed could never be garbage-collected and
    # its __del__-triggered stop would never fire
    try:
        for batch in it:
            # chaos site: a firing injection models the data plane dying
            # mid-epoch — it must surface on the CONSUMER thread (errbox),
            # where the estimator's elastic retry can catch it
            faults.inject("feed.produce")
            if not _put_until_stopped(q, stop, shard_fn(mesh, batch)):
                return
    except BaseException as e:  # surfaced on the consumer side
        errbox.append(e)
    finally:
        _put_until_stopped(q, stop, _SENTINEL)


class DeviceFeed:
    """Iterate device-resident sharded batches from a host iterator.

    A daemon producer thread pulls from ``host_iterator``, shards each batch
    onto the mesh (``device_put`` dispatches asynchronously), and parks it in
    a bounded queue of depth ``prefetch`` — so host gather/decode for batch
    N+1..N+k overlaps the consumer's compute on batch N. The producer stops
    at the end of the host iterator or when the feed is ``close()``d or
    garbage-collected; a producer-side exception is re-raised on the consumer
    thread at the point of ``next()``.
    """

    def __init__(self, host_iterator: Iterator[Any], mesh: Mesh,
                 prefetch: Optional[int] = None, shard_fn=None,
                 profile_loop: Optional[str] = None):
        # profile_loop: attribute consumer stalls to that loop's host_input
        # phase (profiler). The train loop does NOT set it — it times its
        # own next() so the phase lands inside the step window instead of
        # being double-counted.
        self._profile_loop = profile_loop
        depth = prefetch if prefetch is not None \
            else global_config().get("data.prefetch")
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._errbox: List[BaseException] = []
        self._thread = threading.Thread(
            target=_produce,
            args=(host_iterator, mesh, self._queue, self._stop, self._errbox,
                  shard_fn if shard_fn is not None else shard_batch),
            daemon=True, name="device-feed")
        self._thread.start()

    def __iter__(self):
        return self

    def __enter__(self) -> "DeviceFeed":
        return self

    def __exit__(self, *exc) -> None:
        # eval/predict passes routinely abandon a feed mid-stream (early
        # break, consumer exception): the context form guarantees the
        # producer thread stops and prefetched device buffers release
        self.close()

    def __next__(self):
        if self._stop.is_set():  # already exhausted or closed
            raise StopIteration
        t0 = time.perf_counter()
        item = self._queue.get()
        dt = time.perf_counter() - t0
        _M_STALL.inc(dt)
        if self._profile_loop is not None:
            _profiler.record_phase(self._profile_loop, "host_input", dt,
                                   start=t0)
        if item is _SENTINEL:
            self._stop.set()
            if self._errbox:
                raise self._errbox[0]
            raise StopIteration
        return item

    def _drain(self) -> None:
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def close(self) -> None:
        """Stop the producer; safe to call more than once."""
        self._stop.set()
        self._drain()  # unblock a producer waiting on a full queue
        self._thread.join(timeout=5)
        # a producer blocked in put() may have delivered one last batch
        # between the drain and the stop check; release it deterministically
        self._drain()

    def __del__(self):
        try:
            self._stop.set()
        except Exception:
            pass
