"""TextSet / TextFeature pipeline (reference ``feature/text/*.scala``:
``TextSet:247``, ``Tokenizer``, ``Normalizer``, ``WordIndexer``,
``SequenceShaper``, ``TextFeatureToSample``; Q&A ``Relations`` in
``feature/common/Relations.scala``).

Host-side text prep: tokenize → normalize → word-index → shape → arrays; the
resulting fixed-length index matrices lower into a FeatureSet for the device
feed. The word index is built once (frequency-ranked, ``remove_topN`` /
``max_words_num`` contract) and persists as JSON."""
from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..featureset import FeatureSet
from ...common import file_io


@dataclass
class Relation:
    """Q&A relation (reference ``Relation``): id1 relates to id2 w/ label."""
    id1: str
    id2: str
    label: int


def read_relations(path: str) -> List[Relation]:
    """CSV ``id1,id2,label`` (with or without header)."""
    rels = []
    with file_io.fopen(path) as f:
        for line in f:
            parts = line.strip().split(",")
            if len(parts) != 3 or parts[2].lower() == "label":
                continue
            rels.append(Relation(parts[0], parts[1], int(parts[2])))
    return rels


class TextFeature:
    """One text record flowing through the pipeline (reference
    ``TextFeature.scala``)."""

    def __init__(self, text: str, label: Optional[int] = None,
                 uri: Optional[str] = None):
        self.text = text
        self.label = label
        self.uri = uri
        self.tokens: Optional[List[str]] = None
        self.indices: Optional[np.ndarray] = None

    def get_sample(self) -> Tuple[np.ndarray, Optional[float]]:
        if self.indices is None:
            raise ValueError("run word2idx/shape_sequence first")
        return (np.asarray(self.indices, np.float32),
                None if self.label is None else float(self.label))


class TextSet:
    def __init__(self, features: List[TextFeature],
                 word_index: Optional[Dict[str, int]] = None):
        self.features = features
        self.word_index = word_index

    # -- factories ------------------------------------------------------------

    @staticmethod
    def from_texts(texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None) -> "LocalTextSet":
        feats = [TextFeature(t, None if labels is None else int(labels[i]))
                 for i, t in enumerate(texts)]
        return LocalTextSet(feats)

    @staticmethod
    def read(path: str, one_based_label: bool = False) -> "LocalTextSet":
        """Read a dir of class-named subdirs of .txt files (reference
        ``TextSet.read``); labels follow alphabetical class order."""
        feats = []
        classes = sorted(d for d in file_io.listdir(path)
                         if file_io.isdir(file_io.join(path, d)))
        base = 1 if one_based_label else 0
        for ci, cls in enumerate(classes):
            cdir = file_io.join(path, cls)
            for fname in sorted(file_io.listdir(cdir)):
                fpath = file_io.join(cdir, fname)
                if file_io.isdir(fpath):
                    continue
                with file_io.fopen(fpath, errors="ignore") as f:
                    feats.append(TextFeature(f.read(), ci + base, uri=fpath))
        return LocalTextSet(feats)

    @staticmethod
    def from_relation_pairs(relations: Sequence[Relation],
                            corpus1: Dict[str, str],
                            corpus2: Dict[str, str],
                            text1_length: Optional[int] = None,
                            text2_length: Optional[int] = None
                            ) -> "LocalTextSet":
        """(text1, text2, label) records for pairwise ranking (reference
        ``TextSet.fromRelationPairs`` feeding KNRM).

        With ``text1_length``/``text2_length`` the full pipeline runs here:
        both corpora share one word index, each side is shaped to its own
        length, and the returned records carry the concatenated
        ``[text1_length + text2_length]`` index arrays KNRM consumes — call
        ``to_featureset`` directly. Without lengths, records hold the raw
        concatenated text and the normal pipeline ops apply to the joint
        token sequence."""
        if text1_length is None or text2_length is None:
            feats = [TextFeature(corpus1[r.id1] + "\n" + corpus2[r.id2],
                                 r.label, uri=f"{r.id1}:{r.id2}")
                     for r in relations]
            return LocalTextSet(feats)
        # per-side pipeline with a shared word index over both corpora;
        # each unique corpus entry is indexed ONCE (queries repeat across
        # hundreds of relations in ranking datasets)
        both = TextSet.from_texts(
            list(corpus1.values()) + list(corpus2.values()))
        both.tokenize().normalize().word2idx()
        wi = both.get_word_index()

        def index_corpus(corpus: Dict[str, str], length: int
                         ) -> Dict[str, np.ndarray]:
            ids = list(corpus)
            ts = TextSet.from_texts([corpus[i] for i in ids])
            ts.tokenize().normalize().word2idx(existing_map=wi)
            ts.shape_sequence(length)
            return {i: f.indices for i, f in zip(ids, ts.features)}

        idx1 = index_corpus(corpus1, text1_length)
        idx2 = index_corpus(corpus2, text2_length)
        feats = []
        for r in relations:
            tf = TextFeature(corpus1[r.id1] + "\n" + corpus2[r.id2], r.label,
                             uri=f"{r.id1}:{r.id2}")
            tf.indices = np.concatenate([idx1[r.id1], idx2[r.id2]])
            feats.append(tf)
        out = LocalTextSet(feats)
        out.word_index = wi
        return out

    # -- pipeline ops (each returns self-type with updated features) ----------

    def tokenize(self) -> "TextSet":
        for f in self.features:
            f.tokens = re.findall(r"[\w']+", f.text)
        return self

    def normalize(self) -> "TextSet":
        for f in self.features:
            if f.tokens is None:
                raise ValueError("tokenize first")
            f.tokens = [t.lower() for t in f.tokens if t.strip()]
        return self

    def word2idx(self, remove_top_n: int = 0,
                 max_words_num: int = -1,
                 existing_map: Optional[Dict[str, int]] = None) -> "TextSet":
        """Build (or reuse) the frequency-ranked word index and map tokens.
        Index 0 is reserved for padding/unknown (reference starts at 1)."""
        if existing_map is not None:
            self.word_index = dict(existing_map)
        if self.word_index is None:
            counts: Dict[str, int] = {}
            for f in self.features:
                for t in (f.tokens or []):
                    counts[t] = counts.get(t, 0) + 1
            ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
            ranked = ranked[remove_top_n:]
            if max_words_num > 0:
                ranked = ranked[:max_words_num]
            self.word_index = {w: i + 1 for i, (w, _) in enumerate(ranked)}
        wi = self.word_index
        for f in self.features:
            f.indices = np.asarray(
                [wi.get(t, 0) for t in (f.tokens or [])], np.int64)
        return self

    def shape_sequence(self, length: int, trunc_mode: str = "pre",
                       pad_element: int = 0) -> "TextSet":
        """Pad/truncate index arrays to a fixed length (reference
        ``SequenceShaper``: trunc_mode pre|post)."""
        for f in self.features:
            idx = f.indices
            if idx is None:
                raise ValueError("word2idx first")
            if len(idx) > length:
                idx = idx[-length:] if trunc_mode == "pre" else idx[:length]
            elif len(idx) < length:
                idx = np.concatenate(
                    [idx, np.full(length - len(idx), pad_element, idx.dtype)])
            f.indices = idx
        return self

    def generate_sample(self) -> "TextSet":
        return self  # samples materialize in to_featureset

    # -- word index persistence ----------------------------------------------

    def get_word_index(self) -> Optional[Dict[str, int]]:
        return self.word_index

    def save_word_index(self, path: str) -> None:
        with file_io.fopen(path, "w") as f:
            f.write(json.dumps(self.word_index))

    def load_word_index(self, path: str) -> "TextSet":
        with file_io.fopen(path) as f:
            self.word_index = json.loads(f.read())
        return self

    # -- lowering -------------------------------------------------------------

    def to_featureset(self, **kwargs) -> FeatureSet:
        xs, ys = [], []
        for f in self.features:
            x, y = f.get_sample()
            xs.append(x)
            ys.append(y)
        feats = np.stack(xs)
        n_missing = sum(1 for y in ys if y is None)
        if 0 < n_missing < len(ys):
            raise ValueError(
                f"{n_missing}/{len(ys)} records have no label; labels must "
                "be all present or all absent")
        labels = None if n_missing else np.asarray(ys, np.float32)
        return FeatureSet.from_ndarrays(feats, labels, **kwargs)

    def __len__(self) -> int:
        return len(self.features)


class LocalTextSet(TextSet):
    """Single-host text collection (reference ``LocalTextSet:630``)."""


class DistributedTextSet(TextSet):
    """Sharded text collection (reference ``DistributedTextSet:712``);
    per-host sharding applies in the lowered FeatureSet."""

    def to_featureset(self, **kwargs) -> FeatureSet:
        kwargs.setdefault("shard", True)
        return super().to_featureset(**kwargs)
