from .text_set import (  # noqa: F401
    DistributedTextSet, LocalTextSet, Relation, TextFeature, TextSet,
    read_relations)
