"""FeatureSet — the train/eval dataset abstraction.

Re-imagines the reference's ``FeatureSet`` (``zoo/.../feature/FeatureSet.scala:655``)
for a TPU host: instead of cached Spark RDD partitions feeding JVM model
replicas, a FeatureSet owns host-resident (or disk-spilled) arrays, shards them
per process (multi-host) and yields numpy minibatches — endless + reshuffled
per epoch for training, bounded for evaluation, exactly the
``CachedDistributedFeatureSet`` iterator contract. Cache tiers mirror the
reference's ``DRAM`` / ``DISK_n`` / ``PMEM`` memory types (``FeatureSet.scala:564,643``):
``DRAM`` keeps arrays in host RAM, ``DISK`` spills to ``np.memmap``.
Sub-epoch slicing (``numOfSlice``, ``DistributedFeatureSet.numOfSlice`` at
``FeatureSet.scala:110``) lets huge epochs checkpoint/validate mid-epoch.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from enum import Enum
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple, Union

import numpy as np

from ..common.config import global_config
from ..common.context import get_context
from .preprocessing import Preprocessing

ArrayTree = Union[np.ndarray, Tuple[np.ndarray, ...], Dict[str, np.ndarray]]


class MemoryType(Enum):
    DRAM = "dram"
    DISK = "disk"


class HostDataset:
    """Marker base for host-side datasets that satisfy the iterator
    contract the Estimator/Keras surfaces consume (``train_iterator`` /
    ``eval_iterator`` / ``num_batches`` / ``slice_boundaries`` /
    ``num_slices`` / ``size``). ``isinstance(x, HostDataset)`` is the
    "already a dataset, don't wrap it" check."""


def _normalize(tree):
    """Lists of arrays (the Keras multi-input convention) become tuples."""
    if isinstance(tree, list):
        return tuple(tree)
    return tree


def _tree_map(fn, tree: ArrayTree) -> ArrayTree:
    if isinstance(tree, tuple):
        return tuple(fn(t) for t in tree)
    if isinstance(tree, dict):
        return {k: fn(v) for k, v in tree.items()}
    return fn(tree)


def _tree_leaves(tree: ArrayTree):
    if isinstance(tree, tuple):
        return list(tree)
    if isinstance(tree, dict):
        return list(tree.values())
    return [tree]


def _tree_map2(fn, tree: ArrayTree, other: ArrayTree) -> ArrayTree:
    """Map a binary fn over two same-structured trees (array, out-buffer)."""
    if isinstance(tree, tuple):
        return tuple(fn(t, o) for t, o in zip(tree, other))
    if isinstance(tree, dict):
        return {k: fn(v, other[k]) for k, v in tree.items()}
    return fn(tree, other)


def _alloc_batch_like(record: ArrayTree, rows: int) -> ArrayTree:
    """Preallocate a ``[rows, *record_shape]`` output tree for one record."""
    mk = lambda a: np.empty((rows,) + np.asarray(a).shape,
                            np.asarray(a).dtype)
    return _tree_map(mk, record)


def column_matrix(df, cols) -> np.ndarray:
    """DataFrame columns → ``[n, d]`` float32 matrix; array-valued cells
    stack, scalar columns contribute one dimension each (``(n, 1)`` for a
    single scalar column). Shared by NNFrames and XShard lowering."""
    if isinstance(cols, str):
        cols = [cols]
    parts = []
    for c in cols:
        col = df[c].to_numpy()
        if len(col) and isinstance(col[0], (list, tuple, np.ndarray)):
            parts.append(np.stack([np.asarray(v, np.float32) for v in col]))
        else:
            parts.append(col.astype(np.float32)[:, None])
    out = np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    return np.ascontiguousarray(out, dtype=np.float32)


def _spill_to_disk(arr: np.ndarray, directory: str, name: str) -> np.ndarray:
    path = os.path.join(directory, f"{name}.mmap")
    mm = np.memmap(path, dtype=arr.dtype, mode="w+", shape=arr.shape)
    mm[:] = arr[:]
    mm.flush()
    return np.memmap(path, dtype=arr.dtype, mode="r", shape=arr.shape)


class FeatureSet(HostDataset):
    """In-memory / disk-cached dataset of (features, labels) array trees.

    ``features`` and ``labels`` are ndarrays or tuples/dicts of ndarrays whose
    leading dimension is the record axis. ``labels`` may be None (inference).
    """

    def __init__(self,
                 features: ArrayTree,
                 labels: Optional[ArrayTree] = None,
                 memory_type: MemoryType = MemoryType.DRAM,
                 shuffle: bool = True,
                 num_slices: int = 1,
                 cache_dir: Optional[str] = None,
                 shard: bool = True,
                 seed: int = 0):
        features = _normalize(features)
        labels = _normalize(labels)
        n = _tree_leaves(features)[0].shape[0]
        for leaf in _tree_leaves(features) + (
                _tree_leaves(labels) if labels is not None else []):
            if leaf.shape[0] != n:
                raise ValueError("all arrays must share the leading record axis")
        ctx = get_context()
        if shard and ctx.process_count > 1:
            # Per-host shard (the TFDataFeatureSet shard_index contract,
            # reference tfpark/TFDataFeatureSet.scala:120-160).
            idx = np.arange(ctx.process_index, n, ctx.process_count)
            features = _tree_map(lambda a: a[idx], features)
            if labels is not None:
                labels = _tree_map(lambda a: a[idx], labels)
            n = len(idx)
        if memory_type == MemoryType.DISK:
            directory = cache_dir or tempfile.mkdtemp(prefix="zoo_featureset_")
            os.makedirs(directory, exist_ok=True)
            counter = [0]

            def spill(a):
                counter[0] += 1
                return _spill_to_disk(np.asarray(a), directory, f"arr{counter[0]}")

            features = _tree_map(spill, features)
            if labels is not None:
                labels = _tree_map(spill, labels)
        self.features = features
        self.labels = labels
        self.size = n
        self.memory_type = memory_type
        self.shuffle = shuffle
        self.num_slices = max(1, num_slices)
        self._rng = np.random.default_rng(seed)
        self._rings: Dict[int, list] = {}  # per-batch-size staging rings

    # -- constructors (reference TFDataset.from_* family) ---------------------

    @classmethod
    def from_ndarrays(cls, features: ArrayTree, labels: Optional[ArrayTree] = None,
                      **kwargs) -> "FeatureSet":
        to_np = lambda a: np.asarray(a)
        features = _tree_map(to_np, _normalize(features))
        if labels is not None:
            labels = _tree_map(to_np, _normalize(labels))
        return cls(features, labels, **kwargs)

    @classmethod
    def from_slab_views(cls, features: ArrayTree,
                        labels: Optional[ArrayTree] = None,
                        keepalive=None, **kwargs) -> "FeatureSet":
        """Wrap shared-memory views WITHOUT copying (the XShard zero-copy
        handoff): ``features``/``labels`` are numpy views into segments
        written by ETL workers, ``keepalive`` owns the unlinked mappings
        so the pages outlive the producing engine. ``shard`` defaults
        off — the producer already laid out exactly this host's rows."""
        kwargs.setdefault("shard", False)
        fs = cls(features, labels, **kwargs)
        fs._shm_keepalive = keepalive
        return fs

    @classmethod
    def from_dataframe(cls, df, feature_cols: Sequence[str],
                       label_cols: Optional[Sequence[str]] = None,
                       stack: bool = False, **kwargs) -> "FeatureSet":
        """Build from a pandas DataFrame (the NNFrames/DataFrameDataset path).

        ``stack=False`` (default) keeps each feature column a separate model
        input; ``stack=True`` assembles them into one ``[B, K]`` float matrix
        (the reference's VectorAssembler-style tabular contract, ``(B, 1)``
        for a single column)."""
        if stack:
            feats: Any = column_matrix(df, feature_cols)
        else:
            feats = tuple(np.asarray(df[c].to_numpy()) for c in feature_cols)
            if len(feats) == 1:
                feats = feats[0]
        labels = None
        if label_cols:
            labels = tuple(np.asarray(df[c].to_numpy()) for c in label_cols)
            if len(labels) == 1:
                labels = labels[0]
        return cls(feats, labels, **kwargs)

    @classmethod
    def from_generator(cls, gen: Callable[[], Iterator[Any]], size_hint: int,
                       transform: Optional[Preprocessing] = None,
                       streaming: bool = False, **kwargs):
        """Record generator ingest (the PythonLoaderFeatureSet role).

        Default: materialize up to ``size_hint`` records as cached host
        arrays. ``streaming=True`` returns a :class:`StreamingFeatureSet`
        that re-invokes ``gen`` every epoch and assembles batches in a
        background prefetch thread — nothing is ever fully materialized, so
        datasets larger than host RAM stream through."""
        if streaming:
            return StreamingFeatureSet(gen, size_hint, transform=transform,
                                       **kwargs)
        from .preprocessing import stack_records
        records = []
        for i, r in enumerate(gen()):
            if transform is not None:
                r = transform.apply(r)
            records.append(r)
            if i + 1 >= size_hint:
                break
        if not records:
            raise ValueError("generator yielded no records")
        if isinstance(records[0], tuple) and len(records[0]) == 2:
            xs = stack_records([r[0] for r in records])
            ys = stack_records([r[1] for r in records])
            return cls(xs, ys, **kwargs)
        return cls(stack_records(records), None, **kwargs)

    @classmethod
    def from_queue(cls, backend, journal_dir: str, epoch_records: int,
                   **kwargs):
        """Streaming ingest off a queue backend (FileQueue / RedisQueue
        instance, or a ``dir://``/``redis://`` src string): a bounded-
        buffer dataset with watermark/epoch release semantics and exact
        ``data_state`` resume.  Returns a
        :class:`~analytics_zoo_tpu.online.stream.QueueFeatureSet`; see
        docs/online.md for the ingest model."""
        from ..online.stream import QueueFeatureSet
        return QueueFeatureSet(backend, journal_dir, epoch_records,
                               **kwargs)

    @classmethod
    def from_tfrecord(cls, paths: Union[str, Sequence[str]],
                      parser: Callable[[Dict[str, Any]],
                                       Union[Tuple[Any, Any], Any]],
                      size_hint: Optional[int] = None,
                      streaming: bool = False, verify_crc: bool = True,
                      **kwargs):
        """TFRecord ``tf.train.Example`` ingest (reference
        ``tf_dataset.py:458`` TFRecord path). ``parser(example_dict)`` maps a
        decoded example to ``(features, label)`` (or features only). Records
        are read through the native C++ indexer when available."""
        from .tfrecord import read_examples

        def gen():
            for ex in read_examples(paths, verify_crc=verify_crc):
                yield parser(ex)

        if size_hint is None:
            from .tfrecord import count_records
            size_hint = count_records(paths, verify_crc)
        return cls.from_generator(gen, size_hint, streaming=streaming,
                                  **kwargs)

    @classmethod
    def from_strings(cls, strings: Sequence[Union[str, bytes]],
                     labels: Optional[ArrayTree] = None,
                     transform: Optional[Preprocessing] = None,
                     **kwargs) -> "FeatureSet":
        """String/bytes records (reference ``TFDataset.from_string_rdd``,
        ``tf_dataset.py:553``): held as an object array; a per-record
        ``transform`` (tokenizer, image decoder) converts them to numeric
        arrays — required before the device feed."""
        arr = np.asarray(list(strings), dtype=object)
        fs = cls(arr, labels, **kwargs)
        if transform is not None:
            fs = fs.transform(transform)
        return fs

    from_bytes = from_strings

    # -- transforms -----------------------------------------------------------

    def transform(self, preprocessing: Preprocessing,
                  num_workers: Optional[int] = None,
                  mode: Optional[str] = None,
                  lazy: bool = False,
                  cache: bool = False,
                  cache_dir: Optional[str] = None):
        """Apply a record transform to features (reference
        ``FeatureSet.transform``).

        Throughput tiers (the reference's whole FeatureSet design exists so
        ingest never bottlenecks the chips, ``FeatureSet.scala:230``):
        - a :class:`~.preprocessing.BatchPreprocessing` transforms the whole
          stacked array tree in ONE vectorized call — no per-record Python;
        - ``mode="mp"`` (or ``num_workers > 1`` under the default
          ``data.transform_mode = "auto"``) runs records through forked
          worker processes writing shared-memory slabs — the only tier that
          beats the GIL for pure-Python transforms;
        - ``mode="thread"`` uses a thread pool (decoders like PIL/numpy
          that release the GIL);
        - ``mode="loop"`` is the plain per-record loop — the parity
          reference every other tier is held bit-identical to.

        ``lazy=True`` defers the transform into the iterators (nothing is
        materialized up front; batch N+1 transforms while batch N is
        consumed) and returns a :class:`LazyTransformFeatureSet`;
        ``cache=True`` adds the one-shot memmap replay cache on top.
        ``num_workers``/``mode`` default from the ``data.num_workers`` /
        ``data.transform_mode`` config keys.
        """
        if lazy:
            return LazyTransformFeatureSet(
                self, preprocessing, num_workers=num_workers, mode=mode,
                cache=cache, cache_dir=cache_dir)
        from .preprocessing import stack_records
        engine, nw = resolve_transform_engine(preprocessing, num_workers,
                                              mode)
        keepalive = None
        if engine == "batched":
            stacked = preprocessing.apply_batch(
                _tree_map(lambda a: a, self.features))
        elif engine == "mp":
            from .worker_pool import transform_all
            stacked, keepalive = transform_all(
                self.features, self.size, preprocessing, num_workers=nw)
        else:
            # probe record 0 → preallocate the FULL output tree → fill it
            # chunk by chunk: peak extra memory is one chunk of records,
            # not a full per-record Python list next to its stacked copy
            feats = self.features
            first = preprocessing.apply(_index_tree(feats, 0))
            stacked = _alloc_batch_like(first, self.size)
            stack_records([first],
                          out=_tree_map(lambda a: a[0:1], stacked))
            chunk = 512
            if engine == "thread":
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(nw) as pool:
                    for start in range(1, self.size, chunk):
                        stop = min(start + chunk, self.size)
                        recs = list(pool.map(
                            lambda i: preprocessing.apply(
                                _index_tree(feats, i)),
                            range(start, stop)))
                        stack_records(recs, out=_tree_map(
                            lambda a: a[start:stop], stacked))
            else:  # "loop" — the per-record parity reference
                for start in range(1, self.size, chunk):
                    stop = min(start + chunk, self.size)
                    recs = [preprocessing.apply(_index_tree(feats, i))
                            for i in range(start, stop)]
                    stack_records(recs, out=_tree_map(
                        lambda a: a[start:stop], stacked))
        fs = FeatureSet.__new__(FeatureSet)
        fs.features = stacked
        fs.labels = self.labels
        fs.size = self.size
        fs.memory_type = self.memory_type
        fs.shuffle = self.shuffle
        fs.num_slices = self.num_slices
        fs._rng = self._rng
        fs._rings = {}
        fs._shm_keepalive = keepalive  # zero-copy mp results live here
        return fs

    # -- iterators (the FeatureSet contract) ----------------------------------

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self.size // batch_size
        return (self.size + batch_size - 1) // batch_size

    def _gather(self, idx: np.ndarray, out=None
                ) -> Tuple[ArrayTree, Optional[ArrayTree]]:
        """Batch gather. With ``out`` (an ``(x_tree, y_tree)`` staging
        pair) rows land in the caller's preallocated buffers via
        ``np.take(..., out=...)`` — zero per-batch allocation."""
        if out is None:
            # take into an explicit fresh ndarray: a plain np.take would
            # preserve the np.memmap subclass of DISK-tier sources
            take = lambda a: np.take(
                a, idx, axis=0,
                out=np.empty((len(idx),) + a.shape[1:], a.dtype))
            x = _tree_map(take, self.features)
            y = (_tree_map(take, self.labels)
                 if self.labels is not None else None)
            return x, y
        ox, oy = out
        x = _tree_map2(lambda a, o: np.take(a, idx, axis=0, out=o),
                       self.features, ox)
        y = (_tree_map2(lambda a, o: np.take(a, idx, axis=0, out=o),
                        self.labels, oy)
             if self.labels is not None else None)
        return x, y

    def _staging_ring(self, batch_size: int):
        """Ring of reused ``(x, y)`` staging trees for ``train_iterator``
        (``data.staging_slots`` config; 0 disables reuse). OWNERSHIP: a
        yielded batch is overwritten after ``staging_slots`` further
        batches are drawn — consumers that buffer more than that (or whose
        backend aliases host memory into device arrays without a per-step
        sync) must copy or leave the knob at 0."""
        depth = int(global_config().get("data.staging_slots"))
        if depth <= 0:
            return None
        ring = self._rings.get(batch_size)
        if ring is None:
            alloc = lambda tree: _tree_map(
                lambda a: np.empty((batch_size,) + a.shape[1:], a.dtype),
                tree)
            ring = [(alloc(self.features),
                     alloc(self.labels) if self.labels is not None else None)
                    for _ in range(max(2, depth))]
            self._rings[batch_size] = ring
        return ring

    def train_iterator(self, batch_size: int, skip_batches: int = 0
                       ) -> Iterator[Tuple[ArrayTree, Optional[ArrayTree]]]:
        """Endless iterator; reshuffles every epoch; drops the remainder so
        every step sees a full, static-shaped batch (XLA-friendly).

        ``skip_batches`` fast-forwards within the FIRST epoch only — the
        checkpoint-resume path replays the restored epoch's permutation and
        skips the batches already trained on."""
        ring = self._staging_ring(batch_size)
        drawn = 0
        while True:
            order = (self._rng.permutation(self.size) if self.shuffle
                     else np.arange(self.size))
            first = skip_batches * batch_size
            skip_batches = 0
            for start in range(first, self.size - batch_size + 1, batch_size):
                out = None
                if ring is not None:
                    out = ring[drawn % len(ring)]
                    drawn += 1
                yield self._gather(order[start:start + batch_size], out=out)

    # -- checkpointable iteration state (SURVEY §7 step 3: resume must replay
    # -- the SAME data order an uninterrupted run would have seen) ------------

    def data_state(self) -> str:
        """Serialized shuffle-RNG state; JSON (PCG64 state holds 128-bit
        ints, which JSON carries exactly and numpy cannot)."""
        import json
        return json.dumps(self._rng.bit_generator.state)

    def set_data_state(self, state_json: str) -> None:
        import json
        rng = np.random.default_rng()
        rng.bit_generator.state = json.loads(state_json)
        self._rng = rng

    def eval_iterator(self, batch_size: int, pad_remainder: bool = False
                      ) -> Iterator[Tuple[ArrayTree, Optional[ArrayTree], int]]:
        """Bounded iterator; yields ``(x, y, valid_count)``. With
        ``pad_remainder`` the tail batch is padded to full size (static shapes)
        and ``valid_count`` marks the real records."""
        for start in range(0, self.size, batch_size):
            idx = np.arange(start, min(start + batch_size, self.size))
            valid = len(idx)
            if valid < batch_size:
                if not pad_remainder:
                    x, y = self._gather(idx)
                    yield x, y, valid
                    continue
                idx = np.concatenate([idx, np.full(batch_size - valid, idx[-1])])
            x, y = self._gather(idx)
            yield x, y, valid

    def slice_boundaries(self, batch_size: int) -> Sequence[int]:
        """Iteration counts at which each sub-epoch slice ends (numOfSlice)."""
        per_epoch = self.num_batches(batch_size)
        per_slice = max(1, per_epoch // self.num_slices)
        bounds = [per_slice * i for i in range(1, self.num_slices)]
        bounds.append(per_epoch)
        return bounds


def _index_tree(tree: ArrayTree, i: int):
    if isinstance(tree, tuple):
        return tuple(t[i] for t in tree)
    if isinstance(tree, dict):
        return {k: v[i] for k, v in tree.items()}
    return tree[i]


def resolve_transform_engine(preprocessing, num_workers: Optional[int],
                             mode: Optional[str]) -> Tuple[str, int]:
    """Pick the transform execution tier: ``batched`` (vectorized, beats
    everything), else ``mp`` / ``thread`` / ``loop`` per the explicit
    ``mode`` or the ``data.transform_mode`` config ("auto" = mp when
    ``num_workers > 1`` and fork exists, thread when mp is unavailable,
    loop otherwise). Returns ``(engine, num_workers)``."""
    if getattr(preprocessing, "batched", False):
        return "batched", 0
    cfg = global_config()
    if mode is None or mode == "":
        mode = str(cfg.get("data.transform_mode") or "auto")
    if num_workers is None:
        num_workers = int(cfg.get("data.num_workers"))
    from .worker_pool import default_workers, fork_available
    if mode == "auto":
        if num_workers and num_workers > 1:
            mode = "mp" if fork_available() else "thread"
        else:
            mode = "loop"
    if mode == "mp":
        if not fork_available():
            mode = "thread"
        if not num_workers or num_workers < 1:
            num_workers = default_workers()
    if mode == "thread" and (not num_workers or num_workers < 2):
        mode = "loop"
    if mode not in ("mp", "thread", "loop"):
        raise ValueError(f"unknown transform mode {mode!r} "
                         f"(want auto|mp|thread|loop)")
    return mode, int(num_workers or 0)


class LazyTransformFeatureSet(HostDataset):
    """``FeatureSet.transform(..., lazy=True)``: the transform rides inside
    the iterators instead of materializing a second full dataset copy up
    front — gather→transform→stack for batch N+1 runs while batch N is on
    device (the whole lazy iterator executes on the DeviceFeed's producer
    thread, and the ``mp`` engine additionally pipelines ``data.shm_slots``
    batches across forked shared-memory workers, off the consumer's GIL).

    Bit-for-bit parity with the eager ``transform(...)``-then-iterate
    path is part of the contract (including padded eval tails); shuffle
    order draws from the SAME base RNG stream, so ``data_state`` resume
    snapshots work unchanged.

    ``cache=True`` adds a one-shot replay cache on the ``MemoryType.DISK``
    memmap machinery: each record's transformed value is written at its
    record position the first time it is produced; once every record is
    covered the transform never runs again and batches replay as pure
    ``np.take`` gathers from the memmap.

    mp-engine slot ownership: a yielded batch is a zero-copy slab view,
    valid until ``data.shm_slots - 1`` further batches are drawn.
    """

    def __init__(self, base: FeatureSet, preprocessing: Preprocessing,
                 num_workers: Optional[int] = None,
                 mode: Optional[str] = None,
                 cache: bool = False, cache_dir: Optional[str] = None):
        self.base = base
        self.transform_fn = preprocessing
        self._num_workers = num_workers
        self._mode = mode
        self._cache_on = bool(cache) or bool(cache_dir)
        self._cache_dir = cache_dir
        self._cache_tree = None
        self._covered: Optional[np.ndarray] = None
        self._all_covered = False
        self._free_pools: Dict[int, list] = {}  # batch_size -> idle pools
        self._all_pools: list = []
        self._pool_lock = threading.Lock()
        self._src_staging: Dict[int, ArrayTree] = {}
        self._probe_record = None
        self.stats = {"engine": None, "batches": 0, "gather_s": 0.0,
                      "transform_s": 0.0, "cache_s": 0.0, "cache_hits": 0}

    # -- contract delegation --------------------------------------------------

    @property
    def size(self) -> int:
        return self.base.size

    @property
    def labels(self):
        return self.base.labels

    @property
    def shuffle(self) -> bool:
        return self.base.shuffle

    @property
    def num_slices(self) -> int:
        return self.base.num_slices

    @property
    def memory_type(self) -> MemoryType:
        return self.base.memory_type

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        return self.base.num_batches(batch_size, drop_remainder)

    def slice_boundaries(self, batch_size: int) -> Sequence[int]:
        return self.base.slice_boundaries(batch_size)

    def data_state(self) -> str:
        return self.base.data_state()

    def set_data_state(self, state_json: str) -> None:
        self.base.set_data_state(state_json)

    # -- engine ---------------------------------------------------------------

    def _probe(self):
        if self._probe_record is None:
            p = self.transform_fn
            rec0 = _index_tree(self.base.features, 0)
            self._probe_record = (
                p.apply(rec0) if not getattr(p, "batched", False)
                else _index_tree(p.apply_batch(
                    _tree_map(lambda a: a[0:1], self.base.features)), 0))
        return self._probe_record

    def _checkout_pool(self, batch_size: int, num_workers: int):
        """Claim an idle pool for this batch size, or fork a fresh one —
        each concurrent iterator (e.g. a train iterator suspended while a
        mid-epoch validation pass streams the same set) gets exclusive use
        of its pool; :meth:`_checkin_pool` returns it for reuse."""
        with self._pool_lock:
            free = self._free_pools.setdefault(batch_size, [])
            if free:
                return free.pop()
        from .worker_pool import TransformWorkerPool
        slots = max(2, int(global_config().get("data.shm_slots")))
        pool = TransformWorkerPool(
            self.base.features, self.transform_fn, rows=batch_size,
            slots=slots, num_workers=num_workers,
            sample_record=self._probe())
        with self._pool_lock:
            self._all_pools.append(pool)
        return pool

    def _checkin_pool(self, batch_size: int, pool) -> None:
        with self._pool_lock:
            self._free_pools.setdefault(batch_size, []).append(pool)

    def _gather_src(self, idx: np.ndarray, batch_size: int) -> ArrayTree:
        """Source-record gather into ONE reused staging tree — provably
        safe reuse: the transform engines consume it synchronously before
        the next gather."""
        if len(idx) != batch_size:
            return _tree_map(lambda a: np.take(a, idx, axis=0),
                             self.base.features)
        st = self._src_staging.get(batch_size)
        if st is None:
            st = _tree_map(
                lambda a: np.empty((batch_size,) + a.shape[1:], a.dtype),
                self.base.features)
            self._src_staging[batch_size] = st
        return _tree_map2(lambda a, o: np.take(a, idx, axis=0, out=o),
                          self.base.features, st)

    def _stack_transformed(self, idx: np.ndarray, batch_size: int,
                           engine: str, nw: int, thread_pool) -> ArrayTree:
        """loop/thread/batched engines: transform the records of ``idx``
        into a freshly stacked tree (fresh output: the consumer may keep
        or alias it — only the SOURCE staging is reused)."""
        from .preprocessing import stack_records
        p = self.transform_fn
        t0 = time.perf_counter()
        if engine == "batched":
            src = _tree_map(lambda a: np.take(a, idx, axis=0),
                            self.base.features)
            self.stats["gather_s"] += time.perf_counter() - t0
            t1 = time.perf_counter()
            out = p.apply_batch(src)
            self.stats["transform_s"] += time.perf_counter() - t1
            return out
        src = self._gather_src(idx, batch_size)
        self.stats["gather_s"] += time.perf_counter() - t0
        t1 = time.perf_counter()
        n = len(idx)
        if thread_pool is not None:
            recs = list(thread_pool.map(
                lambda j: p.apply(_index_tree(src, j)), range(n)))
        else:
            recs = [p.apply(_index_tree(src, j)) for j in range(n)]
        out = _alloc_batch_like(recs[0], n)
        stack_records(recs, out=out)
        self.stats["transform_s"] += time.perf_counter() - t1
        return out

    def _transformed_batches(self, idx_stream: Iterator[np.ndarray],
                             batch_size: int
                             ) -> Iterator[Tuple[np.ndarray, ArrayTree]]:
        """Order-preserving ``(idx, transformed_x)`` stream for a stream of
        index batches — the single engine core under both iterators."""
        engine, nw = resolve_transform_engine(self.transform_fn,
                                              self._num_workers, self._mode)
        self.stats["engine"] = engine
        if self._cache_on:
            yield from self._cached_batches(idx_stream, batch_size, engine,
                                            nw)
            return
        if engine == "mp":
            pool = self._checkout_pool(batch_size, nw)
            gen = pool.map_index_batches(idx_stream)
            try:
                t0 = time.perf_counter()
                for idx, view in gen:
                    self.stats["transform_s"] += time.perf_counter() - t0
                    self.stats["batches"] += 1
                    yield idx, view
                    t0 = time.perf_counter()
            finally:
                gen.close()  # release the pool's stream lock NOW, not at GC
                self._checkin_pool(batch_size, pool)
            return
        thread_pool = None
        try:
            if engine == "thread":
                from concurrent.futures import ThreadPoolExecutor
                thread_pool = ThreadPoolExecutor(
                    nw, thread_name_prefix="zoo-transform")
            for idx in idx_stream:
                self.stats["batches"] += 1
                yield idx, self._stack_transformed(idx, batch_size, engine,
                                                   nw, thread_pool)
        finally:
            if thread_pool is not None:
                thread_pool.shutdown(wait=False)

    # -- one-shot memmap replay cache ----------------------------------------

    def _init_cache(self) -> None:
        if self._cache_tree is not None:
            return
        directory = (self._cache_dir
                     or str(global_config().get("data.cache_dir") or "")
                     or tempfile.mkdtemp(prefix="zoo_lazycache_"))
        os.makedirs(directory, exist_ok=True)
        rec0 = self._probe()
        n = self.base.size
        counter = [0]

        def mk(a):
            a = np.asarray(a)
            counter[0] += 1
            path = os.path.join(directory, f"t{counter[0]}.mmap")
            return np.memmap(path, dtype=a.dtype, mode="w+",
                             shape=(n,) + a.shape)

        self._cache_tree = _tree_map(mk, rec0)
        self._covered = np.zeros(n, bool)

    def _cached_batches(self, idx_stream, batch_size: int, engine: str,
                        nw: int):
        self._init_cache()
        cov, cache = self._covered, self._cache_tree
        thread_pool = None
        if engine == "thread" and not self._all_covered:
            from concurrent.futures import ThreadPoolExecutor
            thread_pool = ThreadPoolExecutor(
                nw, thread_name_prefix="zoo-transform")
        try:
            for idx in idx_stream:
                self.stats["batches"] += 1
                if not self._all_covered:
                    uniq = np.unique(idx)
                    need = uniq[~cov[uniq]]
                    self.stats["cache_hits"] += len(uniq) - len(need)
                    if len(need):
                        t0 = time.perf_counter()
                        scatter = lambda mm, src: mm.__setitem__(need, src)
                        if engine == "mp":
                            pool = self._checkout_pool(batch_size, nw)
                            try:
                                pool.transform_rows(need)
                                # scatter BEFORE checkin: the slot views
                                # belong to the pool
                                _tree_map2(scatter, cache,
                                           pool.slot_tree(0, len(need)))
                            finally:
                                self._checkin_pool(batch_size, pool)
                        else:
                            _tree_map2(scatter, cache,
                                       self._stack_transformed(
                                           need, batch_size, engine, nw,
                                           thread_pool))
                        cov[need] = True
                        self.stats["transform_s"] += time.perf_counter() - t0
                        if cov.all():
                            self._all_covered = True
                            _tree_map(lambda mm: mm.flush(), cache)
                t0 = time.perf_counter()
                x = _tree_map(
                    lambda mm: np.take(
                        mm, idx, axis=0,
                        out=np.empty((len(idx),) + mm.shape[1:], mm.dtype)),
                    cache)
                self.stats["cache_s"] += time.perf_counter() - t0
                yield idx, x
        finally:
            if thread_pool is not None:
                thread_pool.shutdown(wait=False)

    # -- iterators ------------------------------------------------------------

    def _gather_labels(self, idx: np.ndarray) -> Optional[ArrayTree]:
        if self.base.labels is None:
            return None
        return _tree_map(
            lambda a: np.take(a, idx, axis=0,
                              out=np.empty((len(idx),) + a.shape[1:],
                                           a.dtype)),
            self.base.labels)

    def train_iterator(self, batch_size: int, skip_batches: int = 0
                       ) -> Iterator[Tuple[ArrayTree, Optional[ArrayTree]]]:
        base = self.base

        def idx_stream():
            skip = skip_batches
            while True:
                order = (base._rng.permutation(base.size) if base.shuffle
                         else np.arange(base.size))
                first = skip * batch_size
                skip = 0
                for start in range(first, base.size - batch_size + 1,
                                   batch_size):
                    yield order[start:start + batch_size]

        for idx, x in self._transformed_batches(idx_stream(), batch_size):
            yield x, self._gather_labels(idx)

    def eval_iterator(self, batch_size: int, pad_remainder: bool = False
                      ) -> Iterator[Tuple[ArrayTree, Optional[ArrayTree],
                                          int]]:
        base = self.base

        def idx_stream():
            for start in range(0, base.size, batch_size):
                idx = np.arange(start, min(start + batch_size, base.size))
                if len(idx) < batch_size and pad_remainder:
                    idx = np.concatenate(
                        [idx, np.full(batch_size - len(idx), idx[-1])])
                yield idx

        for idx, x in self._transformed_batches(idx_stream(), batch_size):
            valid = min(batch_size, base.size - int(idx[0]))
            yield x, self._gather_labels(idx), valid

    # -- lifecycle ------------------------------------------------------------

    def prepare(self, batch_size: int) -> None:
        """Warm the heavy one-time setup OUTSIDE the consumer's timed /
        overlapped loop: probes the transform output spec, forks the
        worker pool and maps its slabs (mp), creates the memmap cache
        files. The Estimator calls this before its first batch."""
        engine, nw = resolve_transform_engine(self.transform_fn,
                                              self._num_workers, self._mode)
        self._probe()
        if self._cache_on:
            self._init_cache()
        if engine == "mp":
            self._checkin_pool(batch_size,
                               self._checkout_pool(batch_size, nw))

    def close(self) -> None:
        """Shut down worker processes and release shared-memory slabs and
        staging; the cache memmaps (if any) stay valid on disk."""
        with self._pool_lock:
            pools, self._all_pools = self._all_pools, []
            self._free_pools.clear()
        for pool in pools:
            pool.close()
        self._src_staging.clear()

    def __enter__(self) -> "LazyTransformFeatureSet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class StreamingFeatureSet(HostDataset):
    """Generator-backed dataset that is never fully materialized.

    Implements the same iterator contract the Estimator consumes
    (``train_iterator``/``num_batches``/``slice_boundaries``/``num_slices``)
    but pulls records lazily from a user generator, transforming and
    stacking them into batches in a background thread so the host→device
    feed overlaps with user-code record production (the reference's
    Jep/PythonLoaderFeatureSet streaming role,
    ``pyzoo/zoo/feature/common.py`` FeatureSet.python_loader path).

    Multi-host: records are round-robined across processes by index, the
    same interleaving the materialized FeatureSet uses.
    """

    def __init__(self, gen_factory: Callable[[], Iterator[Any]], size: int,
                 transform: Optional[Preprocessing] = None,
                 prefetch_batches: int = 4, shard: bool = True):
        self.gen_factory = gen_factory
        self.size_total = int(size)
        self.transform_fn = transform
        self.prefetch = max(1, prefetch_batches)
        ctx = get_context()
        self._nproc = ctx.process_count if shard else 1
        self._pindex = ctx.process_index if shard else 0
        self.size = self.size_total // self._nproc
        self.num_slices = 1
        self.shuffle = False  # order is whatever the generator produces

    # -- contract -------------------------------------------------------------

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self.size // batch_size
        return (self.size + batch_size - 1) // batch_size

    def slice_boundaries(self, batch_size: int) -> Sequence[int]:
        return [self.num_batches(batch_size)]

    def _record_stream(self) -> Iterator[Any]:
        for i, rec in enumerate(self.gen_factory()):
            if self._nproc > 1 and i % self._nproc != self._pindex:
                continue
            if self.transform_fn is not None:
                rec = self.transform_fn.apply(rec)
            yield rec

    def _batch_stream(self, batch_size: int) -> Iterator[Tuple[Any, Any]]:
        from .preprocessing import stack_records
        buf: list = []
        for rec in self._record_stream():
            buf.append(rec)
            if len(buf) == batch_size:
                if isinstance(buf[0], tuple) and len(buf[0]) == 2:
                    yield (stack_records([r[0] for r in buf]),
                           stack_records([r[1] for r in buf]))
                else:
                    yield stack_records(buf), None
                buf.clear()
        # remainder dropped: training wants static shapes (XLA)

    def train_iterator(self, batch_size: int, skip_batches: int = 0
                       ) -> Iterator[Tuple[Any, Any]]:
        """Endless; restarts the generator each epoch. Batch assembly runs in
        a daemon thread with a bounded queue so user record production
        overlaps device compute."""
        import queue as queue_mod
        import threading

        def endless():
            skip = skip_batches
            while True:
                n = 0
                for batch in self._batch_stream(batch_size):
                    if skip and n < skip:
                        n += 1
                        continue
                    yield batch
                skip = 0  # fast-forward applies to the resumed epoch only

        from .device_feed import _put_until_stopped

        q: "queue_mod.Queue" = queue_mod.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        class _Error:
            def __init__(self, exc):
                self.exc = exc

        def producer():
            try:
                for batch in endless():
                    if not _put_until_stopped(q, stop, batch):
                        return
            except BaseException as e:  # surface generator errors to consumer
                _put_until_stopped(q, stop, _Error(e))

        t = threading.Thread(target=producer, daemon=True,
                             name="streaming-featureset")
        t.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, _Error):
                    raise item.exc
                yield item
        finally:
            stop.set()

    def eval_iterator(self, batch_size: int, pad_remainder: bool = False
                      ) -> Iterator[Tuple[Any, Any, int]]:
        from .preprocessing import stack_records
        buf: list = []

        def flush():
            if isinstance(buf[0], tuple) and len(buf[0]) == 2:
                x = stack_records([r[0] for r in buf])
                y = stack_records([r[1] for r in buf])
            else:
                x, y = stack_records(buf), None
            return x, y

        for rec in self._record_stream():
            buf.append(rec)
            if len(buf) == batch_size:
                x, y = flush()
                yield x, y, batch_size
                buf.clear()
        if buf:
            valid = len(buf)
            if pad_remainder:
                buf.extend([buf[-1]] * (batch_size - valid))
            x, y = flush()
            yield x, y, valid
