"""FeatureSet — the train/eval dataset abstraction.

Re-imagines the reference's ``FeatureSet`` (``zoo/.../feature/FeatureSet.scala:655``)
for a TPU host: instead of cached Spark RDD partitions feeding JVM model
replicas, a FeatureSet owns host-resident (or disk-spilled) arrays, shards them
per process (multi-host) and yields numpy minibatches — endless + reshuffled
per epoch for training, bounded for evaluation, exactly the
``CachedDistributedFeatureSet`` iterator contract. Cache tiers mirror the
reference's ``DRAM`` / ``DISK_n`` / ``PMEM`` memory types (``FeatureSet.scala:564,643``):
``DRAM`` keeps arrays in host RAM, ``DISK`` spills to ``np.memmap``.
Sub-epoch slicing (``numOfSlice``, ``DistributedFeatureSet.numOfSlice`` at
``FeatureSet.scala:110``) lets huge epochs checkpoint/validate mid-epoch.
"""
from __future__ import annotations

import os
import tempfile
from enum import Enum
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple, Union

import numpy as np

from ..common.context import get_context
from .preprocessing import Preprocessing

ArrayTree = Union[np.ndarray, Tuple[np.ndarray, ...], Dict[str, np.ndarray]]


class MemoryType(Enum):
    DRAM = "dram"
    DISK = "disk"


def _normalize(tree):
    """Lists of arrays (the Keras multi-input convention) become tuples."""
    if isinstance(tree, list):
        return tuple(tree)
    return tree


def _tree_map(fn, tree: ArrayTree) -> ArrayTree:
    if isinstance(tree, tuple):
        return tuple(fn(t) for t in tree)
    if isinstance(tree, dict):
        return {k: fn(v) for k, v in tree.items()}
    return fn(tree)


def _tree_leaves(tree: ArrayTree):
    if isinstance(tree, tuple):
        return list(tree)
    if isinstance(tree, dict):
        return list(tree.values())
    return [tree]


def column_matrix(df, cols) -> np.ndarray:
    """DataFrame columns → ``[n, d]`` float32 matrix; array-valued cells
    stack, scalar columns contribute one dimension each (``(n, 1)`` for a
    single scalar column). Shared by NNFrames and XShard lowering."""
    if isinstance(cols, str):
        cols = [cols]
    parts = []
    for c in cols:
        col = df[c].to_numpy()
        if len(col) and isinstance(col[0], (list, tuple, np.ndarray)):
            parts.append(np.stack([np.asarray(v, np.float32) for v in col]))
        else:
            parts.append(col.astype(np.float32)[:, None])
    out = np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    return np.ascontiguousarray(out, dtype=np.float32)


def _spill_to_disk(arr: np.ndarray, directory: str, name: str) -> np.ndarray:
    path = os.path.join(directory, f"{name}.mmap")
    mm = np.memmap(path, dtype=arr.dtype, mode="w+", shape=arr.shape)
    mm[:] = arr[:]
    mm.flush()
    return np.memmap(path, dtype=arr.dtype, mode="r", shape=arr.shape)


class FeatureSet:
    """In-memory / disk-cached dataset of (features, labels) array trees.

    ``features`` and ``labels`` are ndarrays or tuples/dicts of ndarrays whose
    leading dimension is the record axis. ``labels`` may be None (inference).
    """

    def __init__(self,
                 features: ArrayTree,
                 labels: Optional[ArrayTree] = None,
                 memory_type: MemoryType = MemoryType.DRAM,
                 shuffle: bool = True,
                 num_slices: int = 1,
                 cache_dir: Optional[str] = None,
                 shard: bool = True,
                 seed: int = 0):
        features = _normalize(features)
        labels = _normalize(labels)
        n = _tree_leaves(features)[0].shape[0]
        for leaf in _tree_leaves(features) + (
                _tree_leaves(labels) if labels is not None else []):
            if leaf.shape[0] != n:
                raise ValueError("all arrays must share the leading record axis")
        ctx = get_context()
        if shard and ctx.process_count > 1:
            # Per-host shard (the TFDataFeatureSet shard_index contract,
            # reference tfpark/TFDataFeatureSet.scala:120-160).
            idx = np.arange(ctx.process_index, n, ctx.process_count)
            features = _tree_map(lambda a: a[idx], features)
            if labels is not None:
                labels = _tree_map(lambda a: a[idx], labels)
            n = len(idx)
        if memory_type == MemoryType.DISK:
            directory = cache_dir or tempfile.mkdtemp(prefix="zoo_featureset_")
            os.makedirs(directory, exist_ok=True)
            counter = [0]

            def spill(a):
                counter[0] += 1
                return _spill_to_disk(np.asarray(a), directory, f"arr{counter[0]}")

            features = _tree_map(spill, features)
            if labels is not None:
                labels = _tree_map(spill, labels)
        self.features = features
        self.labels = labels
        self.size = n
        self.memory_type = memory_type
        self.shuffle = shuffle
        self.num_slices = max(1, num_slices)
        self._rng = np.random.default_rng(seed)

    # -- constructors (reference TFDataset.from_* family) ---------------------

    @classmethod
    def from_ndarrays(cls, features: ArrayTree, labels: Optional[ArrayTree] = None,
                      **kwargs) -> "FeatureSet":
        to_np = lambda a: np.asarray(a)
        features = _tree_map(to_np, _normalize(features))
        if labels is not None:
            labels = _tree_map(to_np, _normalize(labels))
        return cls(features, labels, **kwargs)

    @classmethod
    def from_dataframe(cls, df, feature_cols: Sequence[str],
                       label_cols: Optional[Sequence[str]] = None,
                       stack: bool = False, **kwargs) -> "FeatureSet":
        """Build from a pandas DataFrame (the NNFrames/DataFrameDataset path).

        ``stack=False`` (default) keeps each feature column a separate model
        input; ``stack=True`` assembles them into one ``[B, K]`` float matrix
        (the reference's VectorAssembler-style tabular contract, ``(B, 1)``
        for a single column)."""
        if stack:
            feats: Any = column_matrix(df, feature_cols)
        else:
            feats = tuple(np.asarray(df[c].to_numpy()) for c in feature_cols)
            if len(feats) == 1:
                feats = feats[0]
        labels = None
        if label_cols:
            labels = tuple(np.asarray(df[c].to_numpy()) for c in label_cols)
            if len(labels) == 1:
                labels = labels[0]
        return cls(feats, labels, **kwargs)

    @classmethod
    def from_generator(cls, gen: Callable[[], Iterator[Any]], size_hint: int,
                       transform: Optional[Preprocessing] = None,
                       streaming: bool = False, **kwargs):
        """Record generator ingest (the PythonLoaderFeatureSet role).

        Default: materialize up to ``size_hint`` records as cached host
        arrays. ``streaming=True`` returns a :class:`StreamingFeatureSet`
        that re-invokes ``gen`` every epoch and assembles batches in a
        background prefetch thread — nothing is ever fully materialized, so
        datasets larger than host RAM stream through."""
        if streaming:
            return StreamingFeatureSet(gen, size_hint, transform=transform,
                                       **kwargs)
        from .preprocessing import stack_records
        records = []
        for i, r in enumerate(gen()):
            if transform is not None:
                r = transform.apply(r)
            records.append(r)
            if i + 1 >= size_hint:
                break
        if not records:
            raise ValueError("generator yielded no records")
        if isinstance(records[0], tuple) and len(records[0]) == 2:
            xs = stack_records([r[0] for r in records])
            ys = stack_records([r[1] for r in records])
            return cls(xs, ys, **kwargs)
        return cls(stack_records(records), None, **kwargs)

    @classmethod
    def from_tfrecord(cls, paths: Union[str, Sequence[str]],
                      parser: Callable[[Dict[str, Any]],
                                       Union[Tuple[Any, Any], Any]],
                      size_hint: Optional[int] = None,
                      streaming: bool = False, verify_crc: bool = True,
                      **kwargs):
        """TFRecord ``tf.train.Example`` ingest (reference
        ``tf_dataset.py:458`` TFRecord path). ``parser(example_dict)`` maps a
        decoded example to ``(features, label)`` (or features only). Records
        are read through the native C++ indexer when available."""
        from .tfrecord import read_examples

        def gen():
            for ex in read_examples(paths, verify_crc=verify_crc):
                yield parser(ex)

        if size_hint is None:
            from .tfrecord import open_tfrecord
            size_hint = 0
            for p in ([paths] if isinstance(paths, str) else paths):
                r = open_tfrecord(p, verify_crc)
                size_hint += len(r)
                r.close()
        return cls.from_generator(gen, size_hint, streaming=streaming,
                                  **kwargs)

    @classmethod
    def from_strings(cls, strings: Sequence[Union[str, bytes]],
                     labels: Optional[ArrayTree] = None,
                     transform: Optional[Preprocessing] = None,
                     **kwargs) -> "FeatureSet":
        """String/bytes records (reference ``TFDataset.from_string_rdd``,
        ``tf_dataset.py:553``): held as an object array; a per-record
        ``transform`` (tokenizer, image decoder) converts them to numeric
        arrays — required before the device feed."""
        arr = np.asarray(list(strings), dtype=object)
        fs = cls(arr, labels, **kwargs)
        if transform is not None:
            fs = fs.transform(transform)
        return fs

    from_bytes = from_strings

    # -- transforms -----------------------------------------------------------

    def transform(self, preprocessing: Preprocessing,
                  num_workers: int = 0) -> "FeatureSet":
        """Eagerly apply a record transform to features (reference
        ``FeatureSet.transform``).

        Throughput tiers (the reference's whole FeatureSet design exists so
        ingest never bottlenecks the chips, ``FeatureSet.scala:230``):
        - a :class:`~.preprocessing.BatchPreprocessing` transforms the whole
          stacked array tree in ONE vectorized call — no per-record Python;
        - otherwise records run through a thread pool when ``num_workers>0``
          (decoders like PIL/numpy release the GIL), else a plain loop.
        """
        from .preprocessing import stack_records
        feats = _tree_map(lambda a: a, self.features)
        if getattr(preprocessing, "batched", False):
            stacked = preprocessing.apply_batch(feats)
        else:
            indices = range(self.size)
            if num_workers and num_workers > 1:
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(num_workers) as pool:
                    records = list(pool.map(
                        lambda i: preprocessing.apply(_index_tree(feats, i)),
                        indices))
            else:
                records = [preprocessing.apply(_index_tree(feats, i))
                           for i in indices]
            stacked = stack_records(records)
        fs = FeatureSet.__new__(FeatureSet)
        fs.features = stacked
        fs.labels = self.labels
        fs.size = self.size
        fs.memory_type = self.memory_type
        fs.shuffle = self.shuffle
        fs.num_slices = self.num_slices
        fs._rng = self._rng
        return fs

    # -- iterators (the FeatureSet contract) ----------------------------------

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self.size // batch_size
        return (self.size + batch_size - 1) // batch_size

    def _gather(self, idx: np.ndarray) -> Tuple[ArrayTree, Optional[ArrayTree]]:
        x = _tree_map(lambda a: np.asarray(a[idx]), self.features)
        y = (_tree_map(lambda a: np.asarray(a[idx]), self.labels)
             if self.labels is not None else None)
        return x, y

    def train_iterator(self, batch_size: int, skip_batches: int = 0
                       ) -> Iterator[Tuple[ArrayTree, Optional[ArrayTree]]]:
        """Endless iterator; reshuffles every epoch; drops the remainder so
        every step sees a full, static-shaped batch (XLA-friendly).

        ``skip_batches`` fast-forwards within the FIRST epoch only — the
        checkpoint-resume path replays the restored epoch's permutation and
        skips the batches already trained on."""
        while True:
            order = (self._rng.permutation(self.size) if self.shuffle
                     else np.arange(self.size))
            first = skip_batches * batch_size
            skip_batches = 0
            for start in range(first, self.size - batch_size + 1, batch_size):
                yield self._gather(order[start:start + batch_size])

    # -- checkpointable iteration state (SURVEY §7 step 3: resume must replay
    # -- the SAME data order an uninterrupted run would have seen) ------------

    def data_state(self) -> str:
        """Serialized shuffle-RNG state; JSON (PCG64 state holds 128-bit
        ints, which JSON carries exactly and numpy cannot)."""
        import json
        return json.dumps(self._rng.bit_generator.state)

    def set_data_state(self, state_json: str) -> None:
        import json
        rng = np.random.default_rng()
        rng.bit_generator.state = json.loads(state_json)
        self._rng = rng

    def eval_iterator(self, batch_size: int, pad_remainder: bool = False
                      ) -> Iterator[Tuple[ArrayTree, Optional[ArrayTree], int]]:
        """Bounded iterator; yields ``(x, y, valid_count)``. With
        ``pad_remainder`` the tail batch is padded to full size (static shapes)
        and ``valid_count`` marks the real records."""
        for start in range(0, self.size, batch_size):
            idx = np.arange(start, min(start + batch_size, self.size))
            valid = len(idx)
            if valid < batch_size:
                if not pad_remainder:
                    x, y = self._gather(idx)
                    yield x, y, valid
                    continue
                idx = np.concatenate([idx, np.full(batch_size - valid, idx[-1])])
            x, y = self._gather(idx)
            yield x, y, valid

    def slice_boundaries(self, batch_size: int) -> Sequence[int]:
        """Iteration counts at which each sub-epoch slice ends (numOfSlice)."""
        per_epoch = self.num_batches(batch_size)
        per_slice = max(1, per_epoch // self.num_slices)
        bounds = [per_slice * i for i in range(1, self.num_slices)]
        bounds.append(per_epoch)
        return bounds


def _index_tree(tree: ArrayTree, i: int):
    if isinstance(tree, tuple):
        return tuple(t[i] for t in tree)
    if isinstance(tree, dict):
        return {k: v[i] for k, v in tree.items()}
    return tree[i]


class StreamingFeatureSet:
    """Generator-backed dataset that is never fully materialized.

    Implements the same iterator contract the Estimator consumes
    (``train_iterator``/``num_batches``/``slice_boundaries``/``num_slices``)
    but pulls records lazily from a user generator, transforming and
    stacking them into batches in a background thread so the host→device
    feed overlaps with user-code record production (the reference's
    Jep/PythonLoaderFeatureSet streaming role,
    ``pyzoo/zoo/feature/common.py`` FeatureSet.python_loader path).

    Multi-host: records are round-robined across processes by index, the
    same interleaving the materialized FeatureSet uses.
    """

    def __init__(self, gen_factory: Callable[[], Iterator[Any]], size: int,
                 transform: Optional[Preprocessing] = None,
                 prefetch_batches: int = 4, shard: bool = True):
        self.gen_factory = gen_factory
        self.size_total = int(size)
        self.transform_fn = transform
        self.prefetch = max(1, prefetch_batches)
        ctx = get_context()
        self._nproc = ctx.process_count if shard else 1
        self._pindex = ctx.process_index if shard else 0
        self.size = self.size_total // self._nproc
        self.num_slices = 1
        self.shuffle = False  # order is whatever the generator produces

    # -- contract -------------------------------------------------------------

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self.size // batch_size
        return (self.size + batch_size - 1) // batch_size

    def slice_boundaries(self, batch_size: int) -> Sequence[int]:
        return [self.num_batches(batch_size)]

    def _record_stream(self) -> Iterator[Any]:
        for i, rec in enumerate(self.gen_factory()):
            if self._nproc > 1 and i % self._nproc != self._pindex:
                continue
            if self.transform_fn is not None:
                rec = self.transform_fn.apply(rec)
            yield rec

    def _batch_stream(self, batch_size: int) -> Iterator[Tuple[Any, Any]]:
        from .preprocessing import stack_records
        buf: list = []
        for rec in self._record_stream():
            buf.append(rec)
            if len(buf) == batch_size:
                if isinstance(buf[0], tuple) and len(buf[0]) == 2:
                    yield (stack_records([r[0] for r in buf]),
                           stack_records([r[1] for r in buf]))
                else:
                    yield stack_records(buf), None
                buf.clear()
        # remainder dropped: training wants static shapes (XLA)

    def train_iterator(self, batch_size: int, skip_batches: int = 0
                       ) -> Iterator[Tuple[Any, Any]]:
        """Endless; restarts the generator each epoch. Batch assembly runs in
        a daemon thread with a bounded queue so user record production
        overlaps device compute."""
        import queue as queue_mod
        import threading

        def endless():
            skip = skip_batches
            while True:
                n = 0
                for batch in self._batch_stream(batch_size):
                    if skip and n < skip:
                        n += 1
                        continue
                    yield batch
                skip = 0  # fast-forward applies to the resumed epoch only

        from .device_feed import _put_until_stopped

        q: "queue_mod.Queue" = queue_mod.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        class _Error:
            def __init__(self, exc):
                self.exc = exc

        def producer():
            try:
                for batch in endless():
                    if not _put_until_stopped(q, stop, batch):
                        return
            except BaseException as e:  # surface generator errors to consumer
                _put_until_stopped(q, stop, _Error(e))

        t = threading.Thread(target=producer, daemon=True,
                             name="streaming-featureset")
        t.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, _Error):
                    raise item.exc
                yield item
        finally:
            stop.set()

    def eval_iterator(self, batch_size: int, pad_remainder: bool = False
                      ) -> Iterator[Tuple[Any, Any, int]]:
        from .preprocessing import stack_records
        buf: list = []

        def flush():
            if isinstance(buf[0], tuple) and len(buf[0]) == 2:
                x = stack_records([r[0] for r in buf])
                y = stack_records([r[1] for r in buf])
            else:
                x, y = stack_records(buf), None
            return x, y

        for rec in self._record_stream():
            buf.append(rec)
            if len(buf) == batch_size:
                x, y = flush()
                yield x, y, batch_size
                buf.clear()
        if buf:
            valid = len(buf)
            if pad_remainder:
                buf.extend([buf[-1]] * (batch_size - valid))
            x, y = flush()
            yield x, y, valid
