"""3-D image (volume) preprocessing — medical-imaging transforms (reference
``zoo/.../feature/image3d/``: ``Affine.scala:44``, ``Rotation.scala:36``,
``Cropper.scala:49,75,108``).

Volumes are numpy ``[D, H, W]`` or ``[D, H, W, 1]`` arrays. The affine path
is fully vectorized: a destination→source coordinate map (avoids resampling
holes, same convention as the reference) plus trilinear interpolation — one
numpy gather for the whole volume instead of the reference's per-voxel loop.
All ops are ``Preprocessing``, so they chain with ``>>`` into FeatureSet /
ImageSet pipelines.
"""
from __future__ import annotations

import math
import random
from typing import Optional, Sequence

import numpy as np

from .preprocessing import Preprocessing


class ImageProcessing3D(Preprocessing):
    """Base: apply(volume [D,H,W] or [D,H,W,1]) -> transformed volume."""

    def apply(self, volume):
        vol = np.asarray(volume)
        squeeze = False
        if vol.ndim == 4:
            if vol.shape[-1] != 1:
                raise ValueError(
                    f"3D transforms support single-channel volumes, got "
                    f"shape {vol.shape}")
            vol = vol[..., 0]
            squeeze = True
        if vol.ndim != 3:
            raise ValueError(f"expected [D,H,W](,1) volume, got {vol.shape}")
        out = self.transform_volume(vol)
        return out[..., None] if squeeze else out

    def transform_volume(self, vol: np.ndarray) -> np.ndarray:
        raise NotImplementedError


def _trilinear_sample(src: np.ndarray, coords: np.ndarray,
                      clamp_mode: str, pad_val: float) -> np.ndarray:
    """Sample ``src [D,H,W]`` at fractional ``coords [3, N]`` (z,y,x)."""
    d, h, w = src.shape
    z, y, x = coords
    if clamp_mode == "clamp":
        z = np.clip(z, 0, d - 1)
        y = np.clip(y, 0, h - 1)
        x = np.clip(x, 0, w - 1)
    z0 = np.floor(z).astype(np.int64)
    y0 = np.floor(y).astype(np.int64)
    x0 = np.floor(x).astype(np.int64)
    z1, y1, x1 = z0 + 1, y0 + 1, x0 + 1
    fz, fy, fx = z - z0, y - y0, x - x0

    def gather(zi, yi, xi):
        inside = ((zi >= 0) & (zi < d) & (yi >= 0) & (yi < h)
                  & (xi >= 0) & (xi < w))
        vals = src[np.clip(zi, 0, d - 1), np.clip(yi, 0, h - 1),
                   np.clip(xi, 0, w - 1)].astype(np.float64)
        if clamp_mode != "clamp":
            vals = np.where(inside, vals, pad_val)
        return vals

    c000 = gather(z0, y0, x0)
    c001 = gather(z0, y0, x1)
    c010 = gather(z0, y1, x0)
    c011 = gather(z0, y1, x1)
    c100 = gather(z1, y0, x0)
    c101 = gather(z1, y0, x1)
    c110 = gather(z1, y1, x0)
    c111 = gather(z1, y1, x1)
    c00 = c000 * (1 - fx) + c001 * fx
    c01 = c010 * (1 - fx) + c011 * fx
    c10 = c100 * (1 - fx) + c101 * fx
    c11 = c110 * (1 - fx) + c111 * fx
    c0 = c00 * (1 - fy) + c01 * fy
    c1 = c10 * (1 - fy) + c11 * fy
    return (c0 * (1 - fz) + c1 * fz).astype(src.dtype, copy=False)


class AffineTransform3D(ImageProcessing3D):
    """Affine warp: for each destination voxel ``p``,
    ``dst(p) = src(mat @ (p - c) + c - translation)`` with ``c`` the volume
    center — destination→source mapping with trilinear interpolation
    (reference ``Affine.scala:44`` + ``Warp.scala``).

    ``clamp_mode``: "clamp" (edge-extend) or "padding" (fill ``pad_val``
    outside the source).
    """

    def __init__(self, mat, translation=(0.0, 0.0, 0.0),
                 clamp_mode: str = "clamp", pad_val: float = 0.0):
        self.mat = np.asarray(mat, dtype=np.float64).reshape(3, 3)
        self.translation = np.asarray(translation, dtype=np.float64)
        if clamp_mode not in ("clamp", "padding"):
            raise ValueError(f"unknown clamp_mode {clamp_mode!r}")
        if clamp_mode == "clamp" and pad_val != 0.0:
            raise ValueError("pad_val is only meaningful with "
                             "clamp_mode='padding'")
        self.clamp_mode = clamp_mode
        self.pad_val = pad_val

    def transform_volume(self, vol):
        d, h, w = vol.shape
        center = (np.asarray([d, h, w], dtype=np.float64) - 1.0) / 2.0
        grid = np.stack(np.meshgrid(np.arange(d), np.arange(h), np.arange(w),
                                    indexing="ij"), axis=0).reshape(3, -1)
        u = grid.astype(np.float64) - center[:, None]
        src_coords = (self.mat @ u + center[:, None]
                      - self.translation[:, None])
        out = _trilinear_sample(vol, src_coords, self.clamp_mode, self.pad_val)
        return out.reshape(d, h, w)


class Rotate3D(AffineTransform3D):
    """Rotation by (yaw, pitch, roll) — counterclockwise about the z, y, x
    axes respectively, composed ``yaw @ pitch @ roll`` exactly as the
    reference (``Rotation.scala:36-59``)."""

    def __init__(self, rotation_angles: Sequence[float],
                 clamp_mode: str = "clamp", pad_val: float = 0.0):
        yaw, pitch, roll = [float(a) for a in rotation_angles]
        roll_m = np.asarray([
            [1, 0, 0],
            [0, math.cos(roll), -math.sin(roll)],
            [0, math.sin(roll), math.cos(roll)]])
        pitch_m = np.asarray([
            [math.cos(pitch), 0, math.sin(pitch)],
            [0, 1, 0],
            [-math.sin(pitch), 0, math.cos(pitch)]])
        yaw_m = np.asarray([
            [math.cos(yaw), -math.sin(yaw), 0],
            [math.sin(yaw), math.cos(yaw), 0],
            [0, 0, 1]])
        super().__init__(yaw_m @ pitch_m @ roll_m, clamp_mode=clamp_mode,
                         pad_val=pad_val)


def _check_patch(vol_shape, patch) -> None:
    if any(p > s for p, s in zip(patch, vol_shape)):
        raise ValueError(f"crop patch {tuple(patch)} exceeds volume "
                         f"{tuple(vol_shape)}")


class Crop3D(ImageProcessing3D):
    """Fixed crop: ``start`` (z, y, x, 0-based) + ``patch_size`` (d, h, w)
    (reference ``Cropper.scala:49``)."""

    def __init__(self, start: Sequence[int], patch_size: Sequence[int]):
        self.start = [int(v) for v in start]
        self.patch = [int(v) for v in patch_size]

    def transform_volume(self, vol):
        (z, y, x), (pd, ph, pw) = self.start, self.patch
        if z < 0 or y < 0 or x < 0 or z + pd > vol.shape[0] \
                or y + ph > vol.shape[1] or x + pw > vol.shape[2]:
            raise ValueError(f"crop {self.start}+{self.patch} exceeds volume "
                             f"{vol.shape}")
        return vol[z:z + pd, y:y + ph, x:x + pw]


class RandomCrop3D(ImageProcessing3D):
    """Random patch of (depth, height, width) (``Cropper.scala:75``)."""

    def __init__(self, crop_depth: int, crop_height: int, crop_width: int):
        self.patch = (crop_depth, crop_height, crop_width)

    def transform_volume(self, vol):
        pd, ph, pw = self.patch
        _check_patch(vol.shape, self.patch)
        z = random.randint(0, vol.shape[0] - pd)
        y = random.randint(0, vol.shape[1] - ph)
        x = random.randint(0, vol.shape[2] - pw)
        return vol[z:z + pd, y:y + ph, x:x + pw]


class CenterCrop3D(ImageProcessing3D):
    """Center patch of (depth, height, width) (``Cropper.scala:108``)."""

    def __init__(self, crop_depth: int, crop_height: int, crop_width: int):
        self.patch = (crop_depth, crop_height, crop_width)

    def transform_volume(self, vol):
        pd, ph, pw = self.patch
        _check_patch(vol.shape, self.patch)
        z = (vol.shape[0] - pd) // 2
        y = (vol.shape[1] - ph) // 2
        x = (vol.shape[2] - pw) // 2
        return vol[z:z + pd, y:y + ph, x:x + pw]
