"""Composable preprocessing pipeline.

The reference's ``Preprocessing[A, B]`` transformers chain with ``->``
(``zoo/.../feature/common/*.scala``) and adapt raw records into model inputs
(``ArrayToTensor``, ``SeqToTensor``, ``TensorToSample``...). Here a
``Preprocessing`` is a pure record transform, chained with ``>>``; the batch
assembly path stacks transformed records into numpy minibatches (the
``MTSampleToMiniBatch`` role) that the device feed shards onto the mesh.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np


class Preprocessing:
    """A record-level transform; chain with ``>>`` (reference: ``->``)."""

    def apply(self, record: Any) -> Any:
        raise NotImplementedError

    def __rshift__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing(self, other)

    def __call__(self, records: Iterable[Any]) -> Iterator[Any]:
        return (self.apply(r) for r in records)


class BatchPreprocessing(Preprocessing):
    """A transform that operates on the WHOLE stacked array tree at once
    (``batched=True``): ``FeatureSet.transform`` calls ``apply_batch`` in a
    single vectorized numpy call instead of a per-record Python loop. The
    per-record ``apply`` still works (records get a temporary batch axis),
    so batched and record transforms chain freely."""

    batched = True

    def apply_batch(self, batch: Any) -> Any:
        raise NotImplementedError

    def apply(self, record: Any) -> Any:
        add = lambda a: np.asarray(a)[None]
        drop = lambda a: np.asarray(a)[0]
        batched = (tuple(add(r) for r in record) if isinstance(record, tuple)
                   else {k: add(v) for k, v in record.items()}
                   if isinstance(record, dict) else add(record))
        out = self.apply_batch(batched)
        return (tuple(drop(o) for o in out) if isinstance(out, tuple)
                else {k: drop(v) for k, v in out.items()}
                if isinstance(out, dict) else drop(out))


class BatchLambda(BatchPreprocessing):
    """Vectorized transform from a plain function over the stacked tree."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def apply_batch(self, batch: Any) -> Any:
        return self.fn(batch)


class ChainedPreprocessing(Preprocessing):
    def __init__(self, *stages: Preprocessing):
        flat = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages = tuple(flat)
        # a chain of all-batched stages is itself batched (stays vectorized)
        self.batched = all(getattr(s, "batched", False) for s in flat)

    def apply(self, record: Any) -> Any:
        for s in self.stages:
            record = s.apply(record)
        return record

    def apply_batch(self, batch: Any) -> Any:
        for s in self.stages:
            batch = s.apply_batch(batch)
        return batch


class Lambda(Preprocessing):
    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def apply(self, record: Any) -> Any:
        return self.fn(record)


class ArrayToTensor(Preprocessing):
    """Coerce (nested) python/numpy data to float32 ndarrays
    (reference ``ArrayToTensor``/``SeqToTensor``)."""

    def __init__(self, dtype=np.float32):
        self.dtype = dtype

    def apply(self, record: Any) -> Any:
        if isinstance(record, tuple):
            return tuple(np.asarray(r, dtype=self.dtype) for r in record)
        return np.asarray(record, dtype=self.dtype)


class FeatureLabelPreprocessing(Preprocessing):
    """Apply separate transforms to the feature and label of a (x, y) record
    (reference ``FeatureLabelPreprocessing``)."""

    def __init__(self, feature: Preprocessing, label: Preprocessing):
        self.feature = feature
        self.label = label

    def apply(self, record: Any) -> Any:
        x, y = record
        return self.feature.apply(x), self.label.apply(y)


def stack_records(records: Sequence[Any], out: Any = None) -> Any:
    """Stack a list of records (arrays, or tuples/dicts of arrays) into one
    batched record — the ``SampleToMiniBatch`` role.

    With ``out`` (a same-structured tree of ``[len(records), ...]``
    buffers) rows are written in place and ``out`` is returned: callers
    filling a preallocated output tree chunk by chunk avoid ever holding a
    full per-record Python list next to its stacked copy."""
    first = records[0]
    if out is None:
        if isinstance(first, tuple):
            return tuple(np.stack([r[i] for r in records])
                         for i in range(len(first)))
        if isinstance(first, dict):
            return {k: np.stack([r[k] for r in records]) for k in first}
        return np.stack(records)
    if isinstance(first, tuple):
        for j in range(len(first)):
            buf = out[j]
            for i, r in enumerate(records):
                buf[i] = r[j]
    elif isinstance(first, dict):
        for k in first:
            buf = out[k]
            for i, r in enumerate(records):
                buf[i] = r[k]
    else:
        for i, r in enumerate(records):
            out[i] = r
    return out
