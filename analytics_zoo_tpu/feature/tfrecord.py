"""TFRecord ingest (reference ``TFDataset.from_tfrecord_file``,
``pyzoo/zoo/tfpark/tf_dataset.py:458`` + the JVM TFRecord input formats).

Reading is two-tier:
- a native C++ indexer (``native/tfrecord_reader.cpp``) mmaps the file,
  CRC32C-validates framing, and serves zero-copy batched reads over ctypes;
- a pure-Python fallback (shares the masked-CRC implementation with the
  TensorBoard writer) when no compiler is available.

``tf.train.Example`` decoding uses the shared schema-driven protobuf wire
decoder — no tensorflow dependency anywhere.
"""
from __future__ import annotations

import ctypes
import struct
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from ..common import file_io
from ..utils.protowire import Field, parse
from ..utils.tensorboard import frame_record, masked_crc32c

# -- tf.train.Example schema (tensorflow/core/example/{example,feature}.proto)

_BYTES_LIST = {1: Field("value", "bytes", repeated=True)}
_FLOAT_LIST = {1: Field("value", "float32", repeated=True)}
_INT64_LIST = {1: Field("value", "int", repeated=True)}
_FEATURE = {
    1: Field("bytes_list", "message", schema=_BYTES_LIST),
    2: Field("float_list", "message", schema=_FLOAT_LIST),
    3: Field("int64_list", "message", schema=_INT64_LIST),
}
_FEATURE_ENTRY = {  # map<string, Feature> entry
    1: Field("key", "string"),
    2: Field("value", "message", schema=_FEATURE),
}
_FEATURES = {1: Field("feature", "message", repeated=True,
                      schema=_FEATURE_ENTRY)}
_EXAMPLE = {1: Field("features", "message", schema=_FEATURES)}


def parse_example(raw: bytes) -> Dict[str, Any]:
    """Serialized ``tf.train.Example`` → ``{name: ndarray | [bytes]}``."""
    ex = parse(raw, _EXAMPLE)
    out: Dict[str, Any] = {}
    for entry in (ex.get("features") or {}).get("feature", []):
        key = entry.get("key", "")
        feat = entry.get("value") or {}
        if feat.get("bytes_list") is not None:
            out[key] = list(feat["bytes_list"].get("value", []))
        elif feat.get("float_list") is not None:
            out[key] = np.asarray(feat["float_list"].get("value", []),
                                  dtype=np.float32)
        elif feat.get("int64_list") is not None:
            out[key] = np.asarray(feat["int64_list"].get("value", []),
                                  dtype=np.int64)
        else:
            out[key] = None
    return out


# -- Example encoding (for writers/tests; protobuf wire encode is tiny) -----


from ..utils.protowire import (  # noqa: E402
    encode_len_field as _len_field, encode_varint as _varint)


def encode_example(features: Dict[str, Any]) -> bytes:
    """``{name: bytes|[bytes]|float array|int array}`` → serialized Example."""
    entries = b""
    for key, value in features.items():
        if isinstance(value, (bytes, bytearray)):
            value = [bytes(value)]
        if isinstance(value, (list, tuple)) and value \
                and isinstance(value[0], (bytes, bytearray)):
            payload = b"".join(_len_field(1, bytes(v)) for v in value)
            feat = _len_field(1, payload)
        else:
            arr = np.asarray(value)
            if np.issubdtype(arr.dtype, np.floating):
                payload = _len_field(1, arr.astype("<f4").tobytes())
                feat = _len_field(2, payload)
            elif np.issubdtype(arr.dtype, np.integer):
                body = b"".join(_varint(int(v)) for v in arr.reshape(-1))
                payload = _len_field(1, body)
                feat = _len_field(3, payload)
            else:
                raise TypeError(f"unsupported feature dtype for '{key}': "
                                f"{arr.dtype}")
        entries += _len_field(1, _len_field(1, key.encode()) + _len_field(2, feat))
    return _len_field(1, entries)


class TFRecordWriter:
    """Write framed records (CRC32C), same framing as the event writer.

    Framing + checksums run in the native library when available (the CRC
    is the hot loop for large payloads); Python fallback otherwise.
    """

    def __init__(self, path: str):
        self._handle = None
        self._f = None
        lib = _NativeReader.lib()
        # the native writer is posix-only; scheme URIs (gs://...) stream
        # through the filesystem layer's python path instead
        if (lib is not None and hasattr(lib, "ztw_open")
                and not file_io.is_remote(path)):
            self._lib = lib
            self._handle = lib.ztw_open(file_io.local_path(path).encode())
        if self._handle is None:
            self._f = file_io.fopen(path, "wb")

    def write(self, record: bytes) -> None:
        if self._handle is not None:
            if self._lib.ztw_write(self._handle, record, len(record)) != 0:
                raise IOError("native TFRecord write failed (disk full?)")
            return
        if self._f is None:
            raise ValueError("write to a closed TFRecordWriter")
        self._f.write(frame_record(record))

    def write_example(self, features: Dict[str, Any]) -> None:
        self.write(encode_example(features))

    def flush(self) -> None:
        if self._handle is not None:
            if self._lib.ztw_flush(self._handle) != 0:
                raise IOError("TFRecord flush failed (disk full?)")
        elif self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._handle is not None:
            handle, self._handle = self._handle, None
            if self._lib.ztw_close(handle) != 0:
                raise IOError(
                    "TFRecord close failed — the file may be truncated")
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __del__(self):
        # refcount cleanup must not leak the native FILE* or its buffer
        try:
            self.close()
        except Exception:
            pass  # destructors must not raise; use close() to see errors

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- readers ----------------------------------------------------------------


class _NativeReader:
    """ctypes wrapper over native/tfrecord_reader.cpp."""

    _lib = None
    _lib_tried = False

    @classmethod
    def lib(cls):
        if not cls._lib_tried:
            cls._lib_tried = True
            from ..native import load_library
            lib = load_library("tfrecord_reader")
            if lib is not None:
                lib.ztr_open.restype = ctypes.c_void_p
                lib.ztr_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
                lib.ztr_count.restype = ctypes.c_long
                lib.ztr_count.argtypes = [ctypes.c_void_p]
                lib.ztr_error.restype = ctypes.c_int
                lib.ztr_error.argtypes = [ctypes.c_void_p]
                lib.ztr_record_len.restype = ctypes.c_long
                lib.ztr_record_len.argtypes = [ctypes.c_void_p, ctypes.c_long]
                lib.ztr_read.restype = ctypes.c_int
                lib.ztr_read.argtypes = [ctypes.c_void_p, ctypes.c_long,
                                         ctypes.c_char_p]
                lib.ztr_read_batch.restype = ctypes.c_int
                lib.ztr_read_batch.argtypes = [
                    ctypes.c_void_p, ctypes.c_long, ctypes.c_long,
                    ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
                lib.ztr_total_bytes.restype = ctypes.c_int64
                lib.ztr_total_bytes.argtypes = [ctypes.c_void_p, ctypes.c_long,
                                                ctypes.c_long]
                lib.ztr_close.argtypes = [ctypes.c_void_p]
                if hasattr(lib, "ztw_open"):  # writer half (newer builds)
                    lib.ztw_open.restype = ctypes.c_void_p
                    lib.ztw_open.argtypes = [ctypes.c_char_p]
                    lib.ztw_write.restype = ctypes.c_int
                    lib.ztw_write.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p,
                                              ctypes.c_uint64]
                    lib.ztw_flush.restype = ctypes.c_int
                    lib.ztw_flush.argtypes = [ctypes.c_void_p]
                    lib.ztw_close.restype = ctypes.c_int
                    lib.ztw_close.argtypes = [ctypes.c_void_p]
                cls._lib = lib
        return cls._lib

    def __init__(self, path: str, verify_crc: bool = True):
        lib = self.lib()
        assert lib is not None
        self._handle = lib.ztr_open(path.encode(), 2 if verify_crc else 1)
        if not self._handle:
            raise OSError(f"cannot open TFRecord file {path}")
        err = lib.ztr_error(self._handle)
        if err:
            n = lib.ztr_count(self._handle)
            kind = "truncated" if err == 1 else "CRC mismatch"
            lib.ztr_close(self._handle)
            self._handle = None
            raise IOError(f"corrupt TFRecord file {path}: {kind} after "
                          f"{n} records")

    def __len__(self):
        return self.lib().ztr_count(self._handle)

    def read(self, i: int) -> bytes:
        lib = self.lib()
        n = lib.ztr_record_len(self._handle, i)
        if n < 0:
            raise IndexError(i)
        buf = ctypes.create_string_buffer(n)
        lib.ztr_read(self._handle, i, buf)
        return buf.raw[:n]

    def read_batch(self, start: int, n: int) -> List[bytes]:
        lib = self.lib()
        total = lib.ztr_total_bytes(self._handle, start, n)
        if total < 0:
            raise IndexError((start, n))
        buf = ctypes.create_string_buffer(int(total))
        lens = (ctypes.c_int64 * n)()
        lib.ztr_read_batch(self._handle, start, n, buf, lens)
        out, pos = [], 0
        raw = buf.raw
        for i in range(n):
            out.append(raw[pos:pos + lens[i]])
            pos += lens[i]
        return out

    def close(self):
        if self._handle:
            self.lib().ztr_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _PythonReader:
    """Pure-Python fallback: reads the whole framing eagerly."""

    def __init__(self, path: str, verify_crc: bool = True):
        self._records: List[bytes] = []
        with file_io.fopen(path, "rb") as f:
            data = f.read()
        pos, size = 0, len(data)
        while pos + 12 <= size:
            (length,) = struct.unpack_from("<Q", data, pos)
            (hcrc,) = struct.unpack_from("<I", data, pos + 8)
            if verify_crc and hcrc != masked_crc32c(data[pos:pos + 8]):
                raise IOError(f"corrupt TFRecord file {path}: header CRC "
                              f"mismatch after {len(self._records)} records")
            if pos + 12 + length + 4 > size:
                raise IOError(f"corrupt TFRecord file {path}: truncated "
                              f"after {len(self._records)} records")
            payload = data[pos + 12:pos + 12 + length]
            (dcrc,) = struct.unpack_from("<I", data, pos + 12 + length)
            if verify_crc and dcrc != masked_crc32c(payload):
                raise IOError(f"corrupt TFRecord file {path}: payload CRC "
                              f"mismatch after {len(self._records)} records")
            self._records.append(payload)
            pos += 12 + length + 4
        if pos != size:
            raise IOError(f"corrupt TFRecord file {path}: trailing garbage")

    def __len__(self):
        return len(self._records)

    def read(self, i: int) -> bytes:
        return self._records[i]

    def read_batch(self, start: int, n: int) -> List[bytes]:
        return self._records[start:start + n]

    def close(self):
        self._records = []


def open_tfrecord(path: str, verify_crc: bool = True):
    """Open a TFRecord file with the native reader, falling back to Python.
    Remote URIs (gs://...) always use the Python reader over the filesystem
    layer — the mmap-based native reader needs a posix file."""
    if _NativeReader.lib() is not None and not file_io.is_remote(path):
        return _NativeReader(file_io.local_path(path), verify_crc)
    return _PythonReader(path, verify_crc)


def count_records(paths: Union[str, Sequence[str]],
                  verify_crc: bool = True) -> int:
    """Total record count across one or more files — one indexed pass
    through the reader (mmap-cheap on the native path), no payload
    decode. Used by ``FeatureSet.from_tfrecord`` to size its ingest."""
    total = 0
    for path in ([paths] if isinstance(paths, str) else paths):
        reader = open_tfrecord(path, verify_crc)
        try:
            total += len(reader)
        finally:
            reader.close()
    return total


def iter_tfrecords(paths: Union[str, Sequence[str]],
                   verify_crc: bool = True) -> Iterator[bytes]:
    """Iterate raw records across one or more files."""
    if isinstance(paths, str):
        paths = [paths]
    for path in paths:
        reader = open_tfrecord(path, verify_crc)
        try:
            n = len(reader)
            start = 0
            while start < n:
                cnt = min(1024, n - start)
                for rec in reader.read_batch(start, cnt):
                    yield rec
                start += cnt
        finally:
            reader.close()


def read_examples(paths: Union[str, Sequence[str]],
                  verify_crc: bool = True) -> Iterator[Dict[str, Any]]:
    for raw in iter_tfrecords(paths, verify_crc):
        yield parse_example(raw)
