from .featureset import (  # noqa: F401
    FeatureSet, HostDataset, LazyTransformFeatureSet, MemoryType,
    StreamingFeatureSet)
from .device_feed import DeviceFeed  # noqa: F401
from .preprocessing import (  # noqa: F401
    ArrayToTensor, BatchLambda, BatchPreprocessing, ChainedPreprocessing,
    FeatureLabelPreprocessing, Lambda, Preprocessing, stack_records)
from .worker_pool import (  # noqa: F401
    TransformWorkerError, TransformWorkerPool)
