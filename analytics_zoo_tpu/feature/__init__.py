from .featureset import FeatureSet, MemoryType  # noqa: F401
from .device_feed import DeviceFeed  # noqa: F401
from .preprocessing import (  # noqa: F401
    ArrayToTensor, ChainedPreprocessing, FeatureLabelPreprocessing, Lambda,
    Preprocessing, stack_records)
