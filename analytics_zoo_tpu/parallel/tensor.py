"""Tensor parallelism — Megatron-style layer sharding over ``mesh['model']``.

The reference's only parallelism is data-parallel allreduce (SURVEY §2.4:
TP "absent"); this is a new TPU-native capability. Design: GSPMD-style
declared shardings rather than hand-written collectives — the rules below
plug into ``Estimator(param_sharding_rules=...)`` / ``param_sharding`` and
annotate weight layouts, then XLA partitions every matmul and inserts the
single reduce over the model axis where the row-parallel projection brings
activations back (the Megatron f/g pattern, compiler-derived).

The canonical transformer block layout:

- **column-parallel** up-projection (``Dense`` into the hidden/FFN dim):
  kernel ``[in, out]`` sharded ``P(None, "model")`` — each device holds a
  slice of the output features, activations stay sharded, no comm.
- **row-parallel** down-projection (back to the residual width): kernel
  sharded ``P("model", None)`` — each device contracts its activation
  slice, XLA inserts one psum over ``model``.

Usage::

    rules = megatron_mlp_rules(up=("fc1", "up_proj"), down=("fc2",))
    est = Estimator(model, loss, opt, mesh=mesh,
                    param_sharding_rules=rules)
"""
from __future__ import annotations

from typing import Callable, Iterable, Sequence

from jax.sharding import PartitionSpec as P

from .mesh import MODEL_AXIS

Rule = Callable  # (path, leaf) -> Optional[PartitionSpec]


def _segments(path):
    return [str(getattr(p, "key", p)) for p in path]


def _matches(path, names) -> bool:
    # EXACT segment equality — substring matching over the joined path
    # would capture unrelated params (e.g. "proj" capturing "out_proj";
    # same convention as moe_sharding_rule)
    segs = _segments(path)
    return any(seg == n for seg in segs for n in names)


def column_parallel(layer_names: Iterable[str],
                    axis: str = MODEL_AXIS) -> Rule:
    """Shard the OUTPUT features of the named Dense/conv-style layers:
    kernel ``[..., in, out] -> P(..., axis)``, bias ``[out] -> P(axis)``."""
    names = tuple(layer_names)

    def rule(path, leaf):
        if not _matches(path, names):
            return None
        if leaf.ndim >= 2:
            return P(*([None] * (leaf.ndim - 1) + [axis]))
        if leaf.ndim == 1:
            return P(axis)
        return None

    return rule


def row_parallel(layer_names: Iterable[str],
                 axis: str = MODEL_AXIS) -> Rule:
    """Shard the INPUT features of the named layers: kernel
    ``[in, out] -> P(axis, None)``; bias replicated (it adds AFTER the
    psum XLA inserts for the contraction)."""
    names = tuple(layer_names)

    def rule(path, leaf):
        if not _matches(path, names):
            return None
        if leaf.ndim >= 2:
            return P(*([axis] + [None] * (leaf.ndim - 1)))
        return P()  # bias: replicated

    return rule


def vocab_parallel(layer_names: Iterable[str],
                   axis: str = MODEL_AXIS) -> Rule:
    """Shard embedding tables over the vocab axis: ``[vocab, dim] ->
    P(axis, None)`` (the dryrun's NCF-table layout, generalized)."""
    names = tuple(layer_names)

    def rule(path, leaf):
        if _matches(path, names) and leaf.ndim == 2:
            return P(axis, None)
        return None

    return rule


def megatron_mlp_rules(up: Sequence[str], down: Sequence[str],
                       embeddings: Sequence[str] = (),
                       axis: str = MODEL_AXIS) -> list:
    """The standard transformer-block tensor-parallel layout as a rule list
    for ``param_sharding_rules``: column-parallel ``up`` projections,
    row-parallel ``down`` projections, optional vocab-parallel embeddings.
    Unmatched parameters stay replicated (pure DP)."""
    rules = [column_parallel(up, axis), row_parallel(down, axis)]
    if embeddings:
        rules.append(vocab_parallel(embeddings, axis))
    return rules


def transformer_tp_rules(axis: str = None) -> list:
    """TransformerLM's Megatron block layout as a rule list: ``qkv`` and
    ``fc1`` column-parallel (the attention head dim and the FFN hidden dim
    shard over ``axis``), ``attn_out`` and ``fc2`` row-parallel (XLA inserts
    the single reduce bringing activations back to the residual). This is
    the f/g collective pair per block — one all-gather entering the sharded
    region forward, one reduce-scatter leaving it backward — derived by the
    SPMD partitioner from these declared layouts rather than hand-written.

    ``qkv`` column sharding splits the fused ``[d, 3d]`` projection on its
    output features, which the head reshape ``[b, s, H, hd]`` inherits, so
    attention (flash / fused short-seq kernel) runs on head-sharded inputs
    with no extra collective. Requires ``n_head % axis_size == 0``.
    """
    if axis is None:
        from ..common.config import global_config
        axis = global_config().get("parallel.tensor_axis")
    return [column_parallel(("qkv", "fc1"), axis),
            row_parallel(("attn_out", "fc2"), axis)]
