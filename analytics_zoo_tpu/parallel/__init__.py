from .mesh import (  # noqa: F401
    DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, SEQ_AXIS, data_sharding,
    global_batch_shapes, param_sharding, replicated, shard_batch)
from .ring_attention import (  # noqa: F401
    ring_attention, ring_self_attention, ulysses_attention)
from .moe import MoE, moe_sharding_rule  # noqa: F401
from .pipeline import (  # noqa: F401
    PIPE_AXIS, gpipe, pipeline_apply, stack_stage_params)
from .tensor import (  # noqa: F401
    column_parallel, megatron_mlp_rules, row_parallel, vocab_parallel)
