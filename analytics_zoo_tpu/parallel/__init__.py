from .mesh import (  # noqa: F401
    DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, SEQ_AXIS, data_sharding,
    embedding_axis, global_batch_shapes, param_sharding, replicated,
    shard_batch, vocab_sharding_rule)
from .embedding import (  # noqa: F401
    HostColdTier, ShardSpec, apply_dense_update, apply_row_update,
    cold_lookup, init_row_state, make_shard_spec, set_default_mesh,
    sharded_lookup, validate_ids)
from .ring_attention import (  # noqa: F401
    ring_attention, ring_context, ring_masked_context, ring_self_attention,
    ulysses_attention)
from .moe import MoE, moe_sharding_rule  # noqa: F401
from .pipeline import (  # noqa: F401
    PIPE_AXIS, bubble_fraction, gpipe, make_pipeline_loss,
    note_pipeline_build, pipeline_apply, stack_stage_params)
from .tensor import (  # noqa: F401
    column_parallel, megatron_mlp_rules, row_parallel, transformer_tp_rules,
    vocab_parallel)
