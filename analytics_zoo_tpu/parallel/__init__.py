from .mesh import (  # noqa: F401
    DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, SEQ_AXIS, data_sharding,
    global_batch_shapes, param_sharding, replicated, shard_batch)
