"""Mesh and sharding helpers — the distributed-communication layer.

The reference's comm backend is BigDL's ``AllReduceParameter`` over Spark's
BlockManager (reduce-scatter + allgather of gradient slices over TCP,
``docs/docs/wp-bigdl.md:140-160``). On TPU none of that machinery exists as
user code: shardings are *declared* here and XLA inserts the collectives
(psum/reduce-scatter/allgather over ICI). This module owns the naming
conventions and PartitionSpec construction the rest of the framework uses.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def data_sharding(mesh: Mesh, ndim: int, batch_axis: int = 0) -> NamedSharding:
    """Shard the batch axis over the data axis; replicate the rest.
    ``batch_axis`` > 0 supports step-stacked batches ``[k, B, ...]`` (the
    multi-step dispatch path) where the STEP axis leads and must stay
    replicated."""
    if ndim <= batch_axis:
        return NamedSharding(mesh, P())
    dims = [None] * ndim
    dims[batch_axis] = DATA_AXIS
    return NamedSharding(mesh, P(*dims))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch: Any, batch_axis: int = 0) -> Any:
    """Device-put a host batch pytree with the batch axis sharded over
    ``data``. This is the host→device edge of the input pipeline (the
    reference's FeatureSet-iterator → model-replica feed).

    Single-process: plain sharded ``device_put``. Multi-process (pod): each
    process holds only ITS rows (FeatureSet already per-host shards), so the
    local batch is assembled into the global array via
    ``make_array_from_process_local_data`` — the jit'd step then sees one
    logical global batch, XLA handles cross-host collectives.

    ``batch_axis=1`` handles step-stacked ``[k, B, ...]`` groups from the
    multi-step dispatch path."""
    multiprocess = jax.process_count() > 1

    def put(x):
        if x is None:  # unlabeled datasets yield (x, None)
            return None
        arr = np.asarray(x)
        sharding = data_sharding(mesh, arr.ndim, batch_axis)
        if multiprocess:
            return jax.make_array_from_process_local_data(sharding, arr)
        return jax.device_put(arr, sharding)
    return jax.tree_util.tree_map(put, batch, is_leaf=lambda x: x is None)


def param_sharding(mesh: Mesh, params: Any,
                   rules: Optional[Sequence] = None) -> Any:
    """Sharding pytree for parameters. Default: fully replicated (pure DP).
    ``rules`` is a list of ``(predicate(path, leaf) -> PartitionSpec|None)``
    applied in order — the hook tensor/expert parallel layouts plug into."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = []
    for path, leaf in flat:
        spec = None
        if rules:
            for rule in rules:
                spec = rule(path, leaf)
                if spec is not None:
                    break
        specs.append(NamedSharding(mesh, spec if spec is not None else P()))
    return jax.tree_util.tree_unflatten(treedef, specs)


def global_batch_shapes(batch: Any) -> Any:
    """ShapeDtypeStruct pytree for a host batch (for AOT lowering)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype),
        batch)
