"""Mesh and sharding helpers — the distributed-communication layer.

The reference's comm backend is BigDL's ``AllReduceParameter`` over Spark's
BlockManager (reduce-scatter + allgather of gradient slices over TCP,
``docs/docs/wp-bigdl.md:140-160``). On TPU none of that machinery exists as
user code: shardings are *declared* here and XLA inserts the collectives
(psum/reduce-scatter/allgather over ICI). This module owns the naming
conventions and PartitionSpec construction the rest of the framework uses.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def data_sharding(mesh: Mesh, ndim: int, batch_axis: int = 0) -> NamedSharding:
    """Shard the batch axis over the data axis; replicate the rest.
    ``batch_axis`` > 0 supports step-stacked batches ``[k, B, ...]`` (the
    multi-step dispatch path) where the STEP axis leads and must stay
    replicated."""
    if ndim <= batch_axis or DATA_AXIS not in mesh.axis_names:
        # pure model/pipe/seq meshes have no data axis: the batch is
        # replicated and the collectives partition the compute instead
        return NamedSharding(mesh, P())
    dims = [None] * ndim
    dims[batch_axis] = DATA_AXIS
    return NamedSharding(mesh, P(*dims))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch: Any, batch_axis: int = 0) -> Any:
    """Device-put a host batch pytree with the batch axis sharded over
    ``data``. This is the host→device edge of the input pipeline (the
    reference's FeatureSet-iterator → model-replica feed).

    Single-process: plain sharded ``device_put``. Multi-process (pod): each
    process holds only ITS rows (FeatureSet already per-host shards), so the
    local batch is assembled into the global array via
    ``make_array_from_process_local_data`` — the jit'd step then sees one
    logical global batch, XLA handles cross-host collectives.

    ``batch_axis=1`` handles step-stacked ``[k, B, ...]`` groups from the
    multi-step dispatch path."""
    multiprocess = jax.process_count() > 1

    def put(x):
        if x is None:  # unlabeled datasets yield (x, None)
            return None
        arr = np.asarray(x)
        sharding = data_sharding(mesh, arr.ndim, batch_axis)
        if multiprocess:
            return jax.make_array_from_process_local_data(sharding, arr)
        return jax.device_put(arr, sharding)
    return jax.tree_util.tree_map(put, batch, is_leaf=lambda x: x is None)


def param_sharding(mesh: Mesh, params: Any,
                   rules: Optional[Sequence] = None) -> Any:
    """Sharding pytree for parameters. Default: fully replicated (pure DP).
    ``rules`` is a list of ``(predicate(path, leaf) -> PartitionSpec|None)``
    applied in order — the hook tensor/expert parallel layouts plug into."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = []
    for path, leaf in flat:
        spec = None
        if rules:
            for rule in rules:
                spec = rule(path, leaf)
                if spec is not None:
                    break
        specs.append(NamedSharding(mesh, spec if spec is not None else P()))
    return jax.tree_util.tree_unflatten(treedef, specs)


def embedding_axis(mesh: Mesh) -> str:
    """The mesh axis vocab-sharded embedding tables partition over: the
    DATA axis when present, else the first axis. Sharding the vocab over
    the same axis the batch rides means every device requests rows for
    its own batch shard, so the sharded-lookup backward needs no
    cross-replica psum (parallel/embedding.py)."""
    return DATA_AXIS if DATA_AXIS in mesh.axis_names else mesh.axis_names[0]


def vocab_sharding_rule(tables):
    """``param_sharding`` rule for vocab-sharded embedding tables.

    ``tables`` maps ``(layer_name, param_key)`` to the mesh axis the
    vocab shards over. The rule matches any tree path containing that
    adjacent key pair — so it shards both the parameter itself
    (``params[layer][key]``) and its row-wise optimizer state
    (``opt["embed"][layer][key]["acc" | "mu" | "nu"]``) — and emits
    ``P(axis, None, ...)`` for rank >= 2 leaves (scalars like the lazy
    adam step count stay replicated)."""
    def _key(entry) -> str:
        return str(getattr(entry, "key", getattr(entry, "name", entry)))

    def rule(path, leaf):
        ndim = getattr(leaf, "ndim", 0)
        if ndim < 2:
            return None
        names = [_key(p) for p in path]
        for a, b in zip(names, names[1:]):
            axis = tables.get((a, b))
            if axis is not None:
                return P(axis, *([None] * (ndim - 1)))
        return None
    return rule


def global_batch_shapes(batch: Any) -> Any:
    """ShapeDtypeStruct pytree for a host batch (for AOT lowering)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype),
        batch)
