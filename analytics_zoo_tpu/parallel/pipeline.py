"""Pipeline parallelism: GPipe forward streaming + a 1F1B training schedule.

The reference has no pipeline parallelism (SURVEY §5); this completes the
mesh-axis set. TPU-native design: one stage per device along a ``pipe``
mesh axis, activations hop stage→stage via ``lax.ppermute`` inside a
``lax.scan`` over ticks — the classic SPMD pipeline from the scaling
playbook.

Two schedules:

- :func:`pipeline_apply` / :func:`gpipe` — the forward GPipe stream
  (``M + P - 1`` ticks, bubble ``(P-1)/(M+P-1)``), differentiable through
  scan+ppermute autodiff (grads hop backwards for free).
- :func:`make_pipeline_loss` — the TRAINING schedule: a single
  ``lax.scan`` over ``M + 2(P-1)`` ticks where every steady-state tick
  runs one microbatch forward AND one microbatch backward (1F1B). The
  backward recomputes the stage forward from a saved input (``jax.vjp``
  per tick), so in-flight activation storage is bounded by ``2P-1``
  microbatch inputs per stage — O(P), not O(M) — the 1F1B memory bound
  via recompute. Exposed as a ``jax.custom_vjp`` loss so it drops
  straight into ``Estimator.train``'s ``value_and_grad``.

Both schedules' tick bodies are zoolint hot-path policed: loop-free, no
host syncs, no densification.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..common import metrics as _metrics

PIPE_AXIS = "pipe"

_M_BUBBLE = _metrics.gauge(
    "parallel.pipeline_bubble_ratio",
    "Idle fraction of the compiled pipeline schedule: 2(P-1)/(M+2(P-1)) "
    "for the 1F1B training scan, (P-1)/(M+P-1) for the forward GPipe "
    "stream. Set when the pipelined step is built.")
_M_COLLECTIVE = _metrics.counter(
    "parallel.collective_bytes_total",
    "Estimated bytes moved by model-parallel collectives (pipeline "
    "ppermute hops, MoE all-to-all exchanges, ring-attention KV "
    "rotations), attributed at trace/build time per compiled step — the "
    "same static-attribution convention as embed.exchange_bytes_total.")


def note_collective_bytes(n: int) -> None:
    """Host-side hook: other parallel modules (MoE exchange, ring
    attention) account their per-step collective traffic here."""
    if n > 0:
        _M_COLLECTIVE.inc(int(n))


def bubble_fraction(n_stages: int, n_microbatches: int,
                    schedule: str = "1f1b") -> float:
    """Idle fraction of the pipeline schedule. The 1F1B training scan runs
    ``M + 2(P-1)`` ticks for ``M`` microbatch forwards+backwards; the
    forward-only stream runs ``M + P - 1``."""
    p, m = n_stages, n_microbatches
    if schedule == "1f1b":
        return 2 * (p - 1) / (m + 2 * (p - 1)) if m + 2 * (p - 1) else 0.0
    return (p - 1) / (m + p - 1) if m + p - 1 else 0.0


def note_pipeline_build(n_stages: int, n_microbatches: int,
                        micro_bytes: int = 0,
                        schedule: str = "1f1b") -> None:
    """Publish the schedule's bubble fraction (profiler gauge) and its
    per-step ppermute traffic estimate: every tick each device sends one
    microbatch activation around the forward ring, plus one cotangent
    around the backward ring under 1F1B."""
    _M_BUBBLE.set(bubble_fraction(n_stages, n_microbatches, schedule))
    if micro_bytes:
        ticks = (n_microbatches + 2 * (n_stages - 1) if schedule == "1f1b"
                 else n_microbatches + n_stages - 1)
        rings = 2 if schedule == "1f1b" else 1
        _M_COLLECTIVE.inc(int(ticks * rings * micro_bytes * n_stages))


def stack_stage_params(per_stage_params) -> Any:
    """[stage0_tree, stage1_tree, ...] → one tree with a leading stage axis
    (shard it over the ``pipe`` axis)."""
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves),
                                  *per_stage_params)


def _ring_perm(p: int):
    """Forward ring: stage i sends to stage i+1."""
    return [(i, (i + 1) % p) for i in range(p)]


def _ring_perm_rev(p: int):
    """Backward ring: stage i sends to stage i-1 (cotangent hops)."""
    return [(i, (i - 1) % p) for i in range(p)]


def _axis_size(axis_name: str) -> int:
    """Static size of a bound mesh axis from inside a shard_map body.
    ``lax.psum`` of a Python literal folds at trace time, so this is a
    plain int — usable for perm tables and scan lengths — on every JAX
    that can run shard_map (``lax.axis_size`` is newer than 0.4.x)."""
    try:
        return lax.axis_size(axis_name)
    except AttributeError:
        return lax.psum(1, axis_name)


def _vary(a, axis_name: str):
    """Make ``a`` device-varying over ``axis_name`` — scan carries under
    shard_map must already carry the varying-axis type the ppermute
    introduces (several JAX spellings, oldest fallback multiplies by a
    varying zero)."""
    try:
        return lax.pcast(a, (axis_name,), to="varying")
    except (AttributeError, TypeError):
        try:
            return lax.pvary(a, axis_name)  # older spelling
        except AttributeError:
            return a + jnp.zeros((), a.dtype) * lax.axis_index(axis_name)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array,
                   n_microbatches: int = 4,
                   axis_name: str = PIPE_AXIS) -> jax.Array:
    """Per-shard body: run ``x [batch, ...]`` through the stage pipeline.

    ``stage_params`` is this device's slice of the stage-stacked tree (a
    leading axis of size 1, from sharding the stage axis over ``pipe``).
    Every stage must preserve the activation SHAPE (classic GPipe constraint
    for the rotating buffer); project before/after the pipelined trunk if
    widths differ.
    """
    p = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    leaves = jax.tree_util.tree_leaves(stage_params)
    if leaves and leaves[0].shape[0] != 1:
        raise ValueError(
            f"pipeline_apply expects ONE stage per device; this shard holds "
            f"{leaves[0].shape[0]} stages — the stage count must equal the "
            f"'{axis_name}' mesh axis size")
    local_params = jax.tree_util.tree_map(lambda l: l[0], stage_params)
    m = n_microbatches
    batch = x.shape[0]
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by "
                         f"n_microbatches {m}")
    mb = batch // m
    micro = x.reshape(m, mb, *x.shape[1:])

    # the initial carry must already carry the device-varying type scan
    # requires under shard_map (the ppermute makes later carries varying);
    # derive it from the INPUT (times zero) so it inherits x's varying
    # axes too — under a combined mesh (dp x pp) x is data-varying, and a
    # carry missing that axis fails scan's vma check
    buf0 = _vary(micro[0] * 0, axis_name)
    out_acc0 = _vary(micro * 0, axis_name)
    perm = _ring_perm(p)

    def tick(carry, t):
        buf, out_acc = carry
        # stage 0 ingests microbatch t (while t < m); later stages consume
        # the activation that just hopped in from the previous stage
        feed = micro[jnp.minimum(t, m - 1)]
        inp = jnp.where(stage == 0, feed, buf)
        out = stage_fn(local_params, inp)
        # the LAST stage's output for tick t is microbatch t-(p-1)
        out_idx = t - (p - 1)
        is_valid = jnp.logical_and(stage == p - 1, out_idx >= 0)
        updated = lax.dynamic_update_index_in_dim(
            out_acc, out, jnp.maximum(out_idx, 0), 0)
        out_acc = jnp.where(is_valid, updated, out_acc)
        buf = lax.ppermute(out, axis_name, perm)
        return (buf, out_acc), None

    (_, out_acc), _ = lax.scan(tick, (buf0, out_acc0),
                               jnp.arange(m + p - 1))
    # every device returns the same logical result: broadcast the last
    # stage's accumulator around the ring so out_specs can be replicated
    out_acc = lax.psum(
        jnp.where(stage == p - 1, out_acc, jnp.zeros_like(out_acc)),
        axis_name)
    return out_acc.reshape(batch, *out_acc.shape[2:])


def gpipe(mesh, stage_fn: Callable, per_stage_params,
          n_microbatches: int = 4, axis_name: str = PIPE_AXIS):
    """Global entry: returns ``(stacked_params, fn)`` where ``fn(params, x)``
    runs the pipelined forward over ``mesh[axis_name]`` and is fully
    differentiable (use inside a loss under ``jax.grad``)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_stages = len(per_stage_params)
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if n_stages != axis_size:
        raise ValueError(f"{n_stages} stages but the '{axis_name}' mesh "
                         f"axis has {axis_size} devices (one stage each)")
    note_pipeline_build(n_stages, n_microbatches, schedule="gpipe")
    stacked = stack_stage_params(per_stage_params)
    fn = shard_map(
        partial(pipeline_apply, stage_fn, n_microbatches=n_microbatches,
                axis_name=axis_name),
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis_name), stacked),
                  P()),
        out_specs=P())
    return stacked, fn


# -- the 1F1B training schedule ----------------------------------------------


def _masked_add(acc, upd, keep):
    """acc + upd where ``keep`` (scalar bool), leafwise over trees."""
    return jax.tree_util.tree_map(
        lambda a, u: a + jnp.where(keep, u, jnp.zeros_like(u)), acc, upd)


def _tree_zeros(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def _tree_vary(tree, axis_name):
    return jax.tree_util.tree_map(lambda l: _vary(l, axis_name), tree)


def _pipe_fwd_body(stage_fn, head_loss_fn, n_microbatches, axis_name,
                   stacked, head, x, y):
    """Per-shard PRIMAL body: forward GPipe stream, per-microbatch head
    loss at the last stage, mean loss broadcast to every device."""
    p = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = n_microbatches
    mb = x.shape[0] // m
    micro_x = x.reshape(m, mb, *x.shape[1:])
    micro_y = y.reshape(m, mb, *y.shape[1:])
    perm = _ring_perm(p)
    buf0 = _vary(micro_x[0] * 0, axis_name)

    def tick(carry, t):
        buf, loss_acc = carry
        fwd_idx = t - stage
        valid_f = jnp.logical_and(fwd_idx >= 0, fwd_idx < m)
        feed = micro_x[jnp.clip(fwd_idx, 0, m - 1)]
        inp = jnp.where(stage == 0, feed, buf)
        out = stage_fn(stacked, inp)
        yb = micro_y[jnp.clip(fwd_idx, 0, m - 1)]
        lm_loss = head_loss_fn(head, out, yb) / m
        take = jnp.logical_and(stage == p - 1, valid_f)
        loss_acc = loss_acc + jnp.where(take, lm_loss, 0.0)
        buf = lax.ppermute(out, axis_name, perm)
        return (buf, loss_acc), None

    loss0 = _vary(jnp.zeros((), jnp.float32), axis_name)
    (_, loss_acc), _ = lax.scan(tick, (buf0, loss0), jnp.arange(m + p - 1))
    return lax.psum(jnp.where(stage == p - 1, loss_acc, 0.0), axis_name)


def _pipe_1f1b_body(stage_fn, head_loss_fn, n_microbatches, axis_name,
                    stacked, head, x, y, g):
    """Per-shard 1F1B body: one scan over ``M + 2(P-1)`` ticks; every tick
    runs one microbatch forward step AND one microbatch backward step
    (``jax.vjp`` recompute from the saved stage input). Stage ``s`` runs
    forward of microbatch ``t - s`` and backward of ``t - 2(P-1) + s`` —
    at the last stage the two indices coincide, so the head-loss cotangent
    computed from this tick's forward output seeds this tick's backward
    directly; upstream stages receive cotangents off the reverse ring.
    Activation inputs live in a rolling buffer of depth ``2P-1``: the 1F1B
    O(P) in-flight bound, independent of the microbatch count."""
    p = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = n_microbatches
    mb = x.shape[0] // m
    micro_x = x.reshape(m, mb, *x.shape[1:])
    micro_y = y.reshape(m, mb, *y.shape[1:])
    perm_f = _ring_perm(p)
    perm_b = _ring_perm_rev(p)
    depth = 2 * p - 1
    head_vg = jax.value_and_grad(
        lambda h, o, yb: head_loss_fn(h, o, yb) / m, argnums=(0, 1))

    fbuf0 = _vary(micro_x[0] * 0, axis_name)
    bbuf0 = _vary(micro_x[0] * 0, axis_name)
    abuf0 = _vary(jnp.zeros((depth, mb) + x.shape[1:], x.dtype), axis_name)
    dx0 = _vary(micro_x * 0, axis_name)
    dp0 = _tree_vary(_tree_zeros(stacked), axis_name)
    dh0 = _tree_vary(_tree_zeros(head), axis_name)
    loss0 = _vary(jnp.zeros((), jnp.float32), axis_name)

    def tick(carry, t):
        fbuf, bbuf, abuf, dp_acc, dh_acc, dx_buf, loss_acc = carry
        is_last = stage == p - 1
        # -- forward micro-step -------------------------------------------
        fwd_idx = t - stage
        valid_f = jnp.logical_and(fwd_idx >= 0, fwd_idx < m)
        feed = micro_x[jnp.clip(fwd_idx, 0, m - 1)]
        inp = jnp.where(stage == 0, feed, fbuf)
        out = stage_fn(stacked, inp)
        abuf = jnp.where(
            valid_f,
            lax.dynamic_update_index_in_dim(abuf, inp, fwd_idx % depth, 0),
            abuf)
        # head loss + its cotangent for the microbatch the last stage just
        # finished (fwd_idx == bwd_idx there, so it feeds backward now)
        yb = micro_y[jnp.clip(fwd_idx, 0, m - 1)]
        lm_loss, (dhead, dout) = head_vg(head, out, yb)
        take = jnp.logical_and(is_last, valid_f)
        loss_acc = loss_acc + jnp.where(take, lm_loss, 0.0)
        dh_acc = _masked_add(dh_acc, dhead, take)
        # -- backward micro-step ------------------------------------------
        bwd_idx = t - 2 * (p - 1) + stage
        valid_b = jnp.logical_and(bwd_idx >= 0, bwd_idx < m)
        x_saved = lax.dynamic_index_in_dim(
            abuf, jnp.clip(bwd_idx, 0, m - 1) % depth, 0, keepdims=False)
        cot = jnp.where(is_last, dout.astype(x.dtype),
                        bbuf).astype(x.dtype)
        _, stage_vjp = jax.vjp(stage_fn, stacked, x_saved)
        dp, dx = stage_vjp(cot.astype(out.dtype))
        dp_acc = _masked_add(dp_acc, dp, valid_b)
        dx_buf = jnp.where(
            jnp.logical_and(valid_b, stage == 0),
            lax.dynamic_update_index_in_dim(
                dx_buf, dx.astype(x.dtype), jnp.clip(bwd_idx, 0, m - 1), 0),
            dx_buf)
        fbuf = lax.ppermute(out, axis_name, perm_f)
        bbuf = lax.ppermute(dx.astype(x.dtype), axis_name, perm_b)
        return (fbuf, bbuf, abuf, dp_acc, dh_acc, dx_buf, loss_acc), None

    carry0 = (fbuf0, bbuf0, abuf0, dp0, dh0, dx0, loss0)
    (_, _, _, dp_acc, dh_acc, dx_buf, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(m + 2 * (p - 1)))
    # grads of replicated args must come back axis-invariant: the head
    # grads live only on the last stage, dx only on stage 0 — psum the
    # masked values around the ring; stage-sharded dp stays per-stage
    dh_acc = jax.tree_util.tree_map(
        lambda l: lax.psum(jnp.where(stage == p - 1, l, jnp.zeros_like(l)),
                           axis_name), dh_acc)
    dx = lax.psum(
        jnp.where(stage == 0, dx_buf, jnp.zeros_like(dx_buf)), axis_name)
    loss = lax.psum(jnp.where(stage == p - 1, loss_acc, 0.0), axis_name)
    gs = g.astype(jnp.float32)
    dp_acc = jax.tree_util.tree_map(lambda l: l * gs.astype(l.dtype), dp_acc)
    dh_acc = jax.tree_util.tree_map(lambda l: l * gs.astype(l.dtype), dh_acc)
    dx = (dx.reshape(x.shape) * gs.astype(dx.dtype)
          if jnp.issubdtype(x.dtype, jnp.floating)
          else dx.reshape(x.shape))
    return dp_acc, dh_acc, dx, loss


def make_pipeline_loss(stage_fn: Callable, head_loss_fn: Callable, mesh,
                       n_microbatches: int = 4,
                       axis_name: str = PIPE_AXIS) -> Callable:
    """Build the pipelined training loss ``loss(stacked, head, x, y)``.

    - ``stage_fn(local_stacked, x) -> x`` applies this device's stage
      slice (leading local stage axis of 1 retained) to one microbatch,
      preserving the activation shape.
    - ``head_loss_fn(head_params, trunk_out, y_micro) -> scalar`` applies
      the post-trunk head (final norm / logits / objective) to one
      microbatch.

    The primal runs the forward GPipe stream; the custom VJP runs the
    1F1B scan (:func:`_pipe_1f1b_body`), returning stage-sharded grads
    for ``stacked``, replicated grads for ``head``, and the input
    cotangent for ``x`` (so the embedding upstream of the pipelined trunk
    trains normally). Integer ``y`` gets a ``float0`` zero cotangent.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]

    def specs(stacked, head):
        return (jax.tree_util.tree_map(lambda _: P(axis_name), stacked),
                jax.tree_util.tree_map(lambda _: P(), head), P(), P())

    @jax.custom_vjp
    def ploss(stacked, head, x, y):
        fwd = shard_map(
            partial(_pipe_fwd_body, stage_fn, head_loss_fn, n_microbatches,
                    axis_name),
            mesh=mesh, in_specs=specs(stacked, head), out_specs=P())
        return fwd(stacked, head, x, y)

    def ploss_fwd(stacked, head, x, y):
        return ploss(stacked, head, x, y), (stacked, head, x, y)

    def ploss_bwd(res, g):
        stacked, head, x, y = res
        bwd = shard_map(
            partial(_pipe_1f1b_body, stage_fn, head_loss_fn, n_microbatches,
                    axis_name),
            mesh=mesh,
            in_specs=specs(stacked, head) + (P(),),
            out_specs=(jax.tree_util.tree_map(lambda _: P(axis_name),
                                              stacked),
                       jax.tree_util.tree_map(lambda _: P(), head),
                       P(), P()))
        dstacked, dhead, dx, _ = bwd(stacked, head, x, y,
                                     jnp.asarray(g, jnp.float32))
        if not jnp.issubdtype(x.dtype, jnp.floating):
            dx = np.zeros(x.shape, jax.dtypes.float0)
        dy = np.zeros(y.shape, jax.dtypes.float0) \
            if not jnp.issubdtype(y.dtype, jnp.floating) \
            else jnp.zeros_like(y)
        return dstacked, dhead, dx, dy

    ploss.defvjp(ploss_fwd, ploss_bwd)

    def loss_fn(stacked, head, x, y):
        leaves = jax.tree_util.tree_leaves(stacked)
        if leaves and leaves[0].shape[0] != axis_size:
            raise ValueError(
                f"stacked params carry {leaves[0].shape[0]} stages but the "
                f"'{axis_name}' mesh axis has {axis_size} devices")
        if x.shape[0] % n_microbatches:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by n_microbatches "
                f"{n_microbatches}")
        return ploss(stacked, head, x, y)

    return loss_fn
