"""Pipeline parallelism (GPipe-style microbatch streaming).

The reference has no pipeline parallelism (SURVEY §5); this completes the
mesh-axis set. TPU-native design: one stage per device along a ``pipe``
mesh axis, activations hop stage→stage via ``lax.ppermute`` inside a
``lax.scan`` over ticks — the classic SPMD pipeline from the scaling
playbook. With ``M`` microbatches and ``P`` stages the schedule runs
``M + P - 1`` ticks; bubble fraction ``(P-1)/(M+P-1)`` shrinks as M grows.

Differentiable end to end: scan + ppermute autodiff gives the reverse
pipeline (grads hop backwards) for free — no hand-written backward schedule.

Usage (under ``shard_map`` over the ``pipe`` axis, stage-stacked params
sharded on their leading axis)::

    fn = shard_map(partial(pipeline_apply, stage_fn, n_microbatches=M),
                   mesh=mesh,
                   in_specs=(P("pipe"), P(None)), out_specs=P(None))
    y = fn(stacked_params, x)   # x: [batch, d]; y: [batch, d_out]
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

PIPE_AXIS = "pipe"


def stack_stage_params(per_stage_params) -> Any:
    """[stage0_tree, stage1_tree, ...] → one tree with a leading stage axis
    (shard it over the ``pipe`` axis)."""
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves),
                                  *per_stage_params)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array,
                   n_microbatches: int = 4,
                   axis_name: str = PIPE_AXIS) -> jax.Array:
    """Per-shard body: run ``x [batch, ...]`` through the stage pipeline.

    ``stage_params`` is this device's slice of the stage-stacked tree (a
    leading axis of size 1, from sharding the stage axis over ``pipe``).
    Every stage must preserve the activation SHAPE (classic GPipe constraint
    for the rotating buffer); project before/after the pipelined trunk if
    widths differ.
    """
    p = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    leaves = jax.tree_util.tree_leaves(stage_params)
    if leaves and leaves[0].shape[0] != 1:
        raise ValueError(
            f"pipeline_apply expects ONE stage per device; this shard holds "
            f"{leaves[0].shape[0]} stages — the stage count must equal the "
            f"'{axis_name}' mesh axis size")
    local_params = jax.tree_util.tree_map(lambda l: l[0], stage_params)
    m = n_microbatches
    batch = x.shape[0]
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by "
                         f"n_microbatches {m}")
    mb = batch // m
    micro = x.reshape(m, mb, *x.shape[1:])

    # probe the output shape (same as input by contract); the initial carry
    # must already carry the device-varying type scan requires under
    # shard_map (the ppermute makes later carries varying)
    def _vary(a):
        try:
            return lax.pcast(a, (axis_name,), to="varying")
        except (AttributeError, TypeError):
            try:
                return lax.pvary(a, axis_name)  # older spelling
            except AttributeError:  # oldest: multiply by a varying zero
                return a + jnp.zeros((), a.dtype) * lax.axis_index(axis_name)
    # derive the initial carry from the INPUT (times zero) so it inherits
    # x's varying axes too — under a combined mesh (dp x pp) x is
    # data-varying, and a carry missing that axis fails scan's vma check
    buf0 = _vary(micro[0] * 0)
    out_acc0 = _vary(micro * 0)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        buf, out_acc = carry
        # stage 0 ingests microbatch t (while t < m); later stages consume
        # the activation that just hopped in from the previous stage
        feed = micro[jnp.minimum(t, m - 1)]
        inp = jnp.where(stage == 0, feed, buf)
        out = stage_fn(local_params, inp)
        # the LAST stage's output for tick t is microbatch t-(p-1)
        out_idx = t - (p - 1)
        is_valid = jnp.logical_and(stage == p - 1, out_idx >= 0)
        updated = lax.dynamic_update_index_in_dim(
            out_acc, out, jnp.maximum(out_idx, 0), 0)
        out_acc = jnp.where(is_valid, updated, out_acc)
        buf = lax.ppermute(out, axis_name, perm)
        return (buf, out_acc), None

    (_, out_acc), _ = lax.scan(tick, (buf0, out_acc0),
                               jnp.arange(m + p - 1))
    # every device returns the same logical result: broadcast the last
    # stage's accumulator around the ring so out_specs can be replicated
    out_acc = lax.psum(
        jnp.where(stage == p - 1, out_acc, jnp.zeros_like(out_acc)),
        axis_name)
    return out_acc.reshape(batch, *out_acc.shape[2:])


def gpipe(mesh, stage_fn: Callable, per_stage_params,
          n_microbatches: int = 4, axis_name: str = PIPE_AXIS):
    """Global entry: returns ``(stacked_params, fn)`` where ``fn(params, x)``
    runs the pipelined forward over ``mesh[axis_name]`` and is fully
    differentiable (use inside a loss under ``jax.grad``)."""
    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n_stages = len(per_stage_params)
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if n_stages != axis_size:
        raise ValueError(f"{n_stages} stages but the '{axis_name}' mesh "
                         f"axis has {axis_size} devices (one stage each)")
    stacked = stack_stage_params(per_stage_params)
    fn = shard_map(
        partial(pipeline_apply, stage_fn, n_microbatches=n_microbatches,
                axis_name=axis_name),
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis_name), stacked),
                  P()),
        out_specs=P())
    return stacked, fn
