"""Mixture-of-Experts with expert parallelism.

The reference has no MoE (SURVEY §5: no expert parallelism anywhere); this
is new TPU-native capability completing the mesh-axis set (dp/tp/sp/ep).

Design: top-1 ("switch") routing with DENSE dispatch — per-token gate
probabilities become a one-hot combine matrix and expert computation is one
batched einsum over [experts, capacity, d]. No gather/scatter with dynamic
shapes, so XLA tiles everything onto the MXU and the `expert` mesh axis
shards the expert dimension of both the parameters and the dispatched
tokens; the all-to-all that moves tokens to their experts is the einsum's
collective, inserted by XLA from the shardings.

``MoE`` is a Keras-engine layer (drop into Sequential/Model); pass
``param_sharding_rules=[moe_sharding_rule]`` to the Estimator to place the
expert axis on the mesh.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..keras import initializers
from ..keras.engine import AUX_LOSS_KEY, Layer

EXPERT_AXIS = "expert"


class MoE(Layer):
    """Switch-style MoE feed-forward block: ``y = combine(expert_ffn(
    dispatch(x)))`` with a load-balancing auxiliary loss published through
    the ``AUX_LOSS_KEY`` state contract (the Estimator adds it to the
    objective with a fixed weight).

    Input ``[batch, seq, d]`` (or ``[batch, d]``); each token routes to its
    top-1 expert, subject to ``capacity_factor`` (tokens over capacity are
    passed through the residual path untouched).
    """

    def __init__(self, num_experts: int, hidden_dim: int,
                 capacity_factor: float = 1.25,
                 aux_loss_weight: float = 1e-2,
                 group_size: int = 4096,
                 activation: str = "relu",
                 init: str = "glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        self.num_experts = num_experts
        self.hidden_dim = hidden_dim
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        # routing happens within fixed-size token GROUPS so the dispatch
        # one-hot stays linear in the token count (a single global group
        # would be O(tokens^2) memory)
        self.group_size = group_size
        self.activation = activation
        self.init = initializers.get(init)

    def build(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2, k3 = jax.random.split(rng, 3)
        params = {
            "gate": self.init(k1, (d, self.num_experts)),
            # expert-major parameter blocks: axis 0 shards over `expert`
            "w_in": self.init(k2, (self.num_experts, d, self.hidden_dim)),
            "b_in": jnp.zeros((self.num_experts, self.hidden_dim)),
            "w_out": self.init(k3, (self.num_experts, self.hidden_dim, d)),
            "b_out": jnp.zeros((self.num_experts, d)),
        }
        # the load-balance loss travels through state under the generic
        # `__aux_loss__` contract: the Estimator adds it to the objective
        return params, {AUX_LOSS_KEY: jnp.zeros((), jnp.float32)}

    def call(self, params, state, inputs, *, training=False, rng=None):
        from ..keras.layers.core import get_activation
        act = get_activation(self.activation)
        squeeze = inputs.ndim == 2
        x = inputs[:, None, :] if squeeze else inputs
        b, s, d = x.shape
        n_tok = b * s
        e = self.num_experts

        flat = x.reshape(n_tok, d)
        gsz = min(self.group_size, n_tok)
        pad = (-n_tok) % gsz
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad, d), flat.dtype)])
        g = flat.shape[0] // gsz
        grouped = flat.reshape(g, gsz, d)
        cap = max(1, int(self.capacity_factor * gsz / e))

        # alignment pad rows must neither consume expert capacity nor
        # count in the balance statistics
        valid = (jnp.arange(g * gsz) < n_tok).reshape(g, gsz)

        logits = jnp.einsum("gtd,de->gte", grouped,
                            params["gate"].astype(flat.dtype)
                            ).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)            # [g, t, e]
        expert_idx = jnp.argmax(probs, axis=-1)            # [g, t]
        gate = jnp.max(probs, axis=-1)                     # [g, t]

        onehot = (jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
                  * valid.astype(jnp.float32)[..., None])
        # position of each token within its expert's per-group queue
        pos = (jnp.cumsum(onehot, axis=1) - 1.0) * onehot  # [g, t, e]
        pos_in_expert = jnp.sum(pos, axis=-1).astype(jnp.int32)
        keep = pos_in_expert < cap                         # capacity mask

        # dispatch tensor [g, t, e, cap]: one-hot over (expert, slot)
        slot_onehot = jax.nn.one_hot(pos_in_expert, cap, dtype=flat.dtype)
        dispatch = (onehot.astype(flat.dtype)[..., None]
                    * slot_onehot[..., None, :]
                    * keep.astype(flat.dtype)[..., None, None])
        # expert inputs [g, e, cap, d] — the contraction over tokens is
        # where XLA inserts the all-to-all under expert sharding
        xin = jnp.einsum("gtec,gtd->gecd", dispatch, grouped)
        h = act(jnp.einsum("gecd,edh->gech", xin,
                           params["w_in"].astype(flat.dtype))
                + params["b_in"].astype(flat.dtype)[None, :, None, :])
        out = (jnp.einsum("gech,ehd->gecd", h,
                          params["w_out"].astype(flat.dtype))
               + params["b_out"].astype(flat.dtype)[None, :, None, :])
        # combine back to tokens, weighted by the gate probability
        combined = jnp.einsum("gtec,gecd->gtd", dispatch, out)
        combined = combined * gate.astype(flat.dtype)[..., None]
        # dropped tokens (over capacity) ride the residual path
        y = jnp.where(keep[..., None], combined, grouped)
        y = y.reshape(-1, d)[:n_tok].reshape(b, s, d)

        # switch-transformer load-balance loss: e * Σ_e (frac_tokens_e *
        # frac_probs_e), averaged over groups; the Estimator consumes it
        # from state via the `__aux_loss__` contract
        denom = jnp.maximum(jnp.sum(valid, axis=1, keepdims=True), 1.0)
        frac_tokens = jnp.sum(onehot, axis=1) / denom      # [g, e]
        vprobs = probs * valid.astype(probs.dtype)[..., None]
        frac_probs = jnp.sum(vprobs, axis=1) / denom
        aux = e * jnp.mean(jnp.sum(frac_tokens * frac_probs, axis=-1))
        new_state = {AUX_LOSS_KEY: (aux * self.aux_loss_weight
                                    ).astype(jnp.float32)}
        return (y[:, 0, :] if squeeze else y), new_state

    def compute_output_shape(self, input_shape):
        return input_shape


def moe_sharding_rule(path, leaf):
    """Estimator ``param_sharding_rules`` entry: shard expert-major MoE
    parameter blocks over the ``expert`` mesh axis. Matches the LEAF key
    exactly — substring matching over the joined path would capture
    unrelated params whose names merely contain e.g. ``w_out``."""
    from jax.sharding import PartitionSpec as P
    leaf_key = str(getattr(path[-1], "key", path[-1])) if path else ""
    if leaf_key in ("w_in", "w_out", "b_in", "b_out") and leaf.ndim >= 2:
        return P(EXPERT_AXIS, *([None] * (leaf.ndim - 1)))
    return None
