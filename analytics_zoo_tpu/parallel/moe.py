"""Mixture-of-Experts with expert parallelism.

The reference has no MoE (SURVEY §5: no expert parallelism anywhere); this
is new TPU-native capability completing the mesh-axis set (dp/tp/sp/ep).

Design: top-k routing (k=1 "switch", k=2 GShard-style) with DENSE
dispatch — per-token gate probabilities become a one-hot combine matrix
and expert computation is ONE batched einsum over [experts, capacity, d]
regardless of k (per-choice dispatch tensors occupy disjoint capacity
slots and sum into a single dispatch). No gather/scatter with dynamic
shapes, so XLA tiles everything onto the MXU and the `expert` mesh axis
shards the expert dimension of both the parameters and the dispatched
tokens; the all-to-all that moves tokens to their experts is the einsum's
collective, inserted by XLA from the shardings.

``MoE`` is a Keras-engine layer (drop into Sequential/Model); pass
``param_sharding_rules=[moe_sharding_rule]`` to the Estimator to place the
expert axis on the mesh.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common import metrics as _metrics
from ..keras import initializers
from ..keras.engine import AUX_LOSS_KEY, MOE_DROP_KEY, Layer

EXPERT_AXIS = "expert"

_M_DROPPED = _metrics.counter(
    "parallel.moe_dropped_tokens_total",
    "Tokens whose every top-k expert choice overflowed capacity and rode "
    "the residual path untouched. MoE layers accumulate the count in "
    "model state under the __moe_dropped__ contract; the Estimator "
    "drains it here at its per-epoch sync point — capacity-factor "
    "dropping is never silent.")


def drain_drop_counter(total: int, seen: int) -> int:
    """Host-side hook for the Estimator's per-epoch drain: publish the
    delta between the state-accumulated drop ``total`` and the last
    drained value, returning the new high-water mark."""
    if total > seen:
        _M_DROPPED.inc(int(total - seen))
        return int(total)
    return int(seen)


def _expert_exchange(xin, w_in, b_in, w_out, b_out, act, axis_name):
    """Per-shard expert FFN via the explicit fixed-size exchange — the
    PR 7 embedding-exchange shape (route → local compute → reverse): token
    groups arrive sharded over the expert axis, one ``all_to_all`` swaps
    the sharding from groups to experts (every device sends each peer its
    capacity slots for that peer's experts — fixed-size, so shapes stay
    static and no host sync is needed), each device runs ONLY its local
    experts' FFN, and the reverse ``all_to_all`` sends results home. The
    per-slot arithmetic is identical to the dense einsum path, so the two
    are bit-compatible."""
    routed = lax.all_to_all(xin, axis_name, split_axis=1, concat_axis=0,
                            tiled=True)
    h = act(jnp.einsum("gecd,edh->gech", routed, w_in)
            + b_in[None, :, None, :])
    out = (jnp.einsum("gech,ehd->gecd", h, w_out)
           + b_out[None, :, None, :])
    return lax.all_to_all(out, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)


def _exchange_mesh(g: int, e: int, mode: str):
    """Static routing decision: the mesh to run the explicit all-to-all
    exchange over, or None for the dense-dispatch path. ``alltoall``
    demands it (raising when shapes can't ride the exchange); ``auto``
    falls back to dense when no expert-axis mesh is active or the group/
    expert counts don't divide over it."""
    if mode == "dense":
        return None
    from .embedding import default_mesh
    mesh = default_mesh()
    has_axis = mesh is not None and EXPERT_AXIS in mesh.axis_names
    n = (dict(zip(mesh.axis_names, mesh.devices.shape))[EXPERT_AXIS]
         if has_axis else 0)
    ok = has_axis and n > 0 and g % n == 0 and e % n == 0
    if mode == "alltoall" and not ok:
        raise ValueError(
            f"moe exchange='alltoall' needs a mesh with an '{EXPERT_AXIS}' "
            f"axis whose size divides groups ({g}) and experts ({e}); "
            f"active mesh: {None if mesh is None else mesh.axis_names}")
    return mesh if ok else None


class MoE(Layer):
    """Switch-style MoE feed-forward block: ``y = combine(expert_ffn(
    dispatch(x)))`` with a load-balancing auxiliary loss published through
    the ``AUX_LOSS_KEY`` state contract (the Estimator adds it to the
    objective with a fixed weight).

    Input ``[batch, seq, d]`` (or ``[batch, d]``); each token routes to its
    top-``k`` experts (k=1 switch, k=2 GShard with renormalized gates),
    subject to ``capacity_factor`` per choice — total slots scale with k
    (the GShard ``k * tokens * C / e`` convention); tokens whose every
    choice overflows ride the residual path untouched.
    """

    def __init__(self, num_experts: int, hidden_dim: int,
                 capacity_factor: Optional[float] = None,
                 aux_loss_weight: float = 1e-2,
                 group_size: int = 4096,
                 activation: str = "relu",
                 init: str = "glorot_uniform",
                 k: int = 1,
                 exchange: Optional[str] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        from ..common.config import global_config
        if not 1 <= k <= num_experts:
            raise ValueError(f"k={k} must be in [1, num_experts]")
        self.num_experts = num_experts
        self.hidden_dim = hidden_dim
        if capacity_factor is None:
            capacity_factor = float(
                global_config().get("parallel.moe_capacity_factor"))
        self.capacity_factor = capacity_factor
        # expert dispatch: dense one-hot einsums (XLA derives the
        # collective from the shardings) vs the explicit fixed-size
        # all-to-all exchange; 'auto' takes the exchange whenever an
        # expert-axis mesh is active and the shapes divide over it
        exchange = exchange if exchange is not None else str(
            global_config().get("parallel.moe_exchange"))
        if exchange not in ("dense", "alltoall", "auto"):
            raise ValueError(f"exchange={exchange!r} must be 'dense', "
                             f"'alltoall' or 'auto'")
        self.exchange = exchange
        self.aux_loss_weight = aux_loss_weight
        # routing happens within fixed-size token GROUPS so the dispatch
        # one-hot stays linear in the token count (a single global group
        # would be O(tokens^2) memory)
        self.group_size = group_size
        self.activation = activation
        self.init = initializers.get(init)
        # k=1 is the Switch transformer; k=2 the GShard top-2 router (gates
        # renormalized over the chosen experts, first choices claim
        # capacity before second choices)
        self.k = k

    def build(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2, k3 = jax.random.split(rng, 3)
        params = {
            "gate": self.init(k1, (d, self.num_experts)),
            # expert-major parameter blocks: axis 0 shards over `expert`
            "w_in": self.init(k2, (self.num_experts, d, self.hidden_dim)),
            "b_in": jnp.zeros((self.num_experts, self.hidden_dim)),
            "w_out": self.init(k3, (self.num_experts, self.hidden_dim, d)),
            "b_out": jnp.zeros((self.num_experts, d)),
        }
        # the load-balance loss travels through state under the generic
        # `__aux_loss__` contract (the Estimator adds it to the objective);
        # the drop counter accumulates under `__moe_dropped__` and is
        # drained into parallel.moe_dropped_tokens_total per epoch
        return params, {AUX_LOSS_KEY: jnp.zeros((), jnp.float32),
                        MOE_DROP_KEY: jnp.zeros((), jnp.int32)}

    def call(self, params, state, inputs, *, training=False, rng=None):
        from ..keras.layers.core import get_activation
        act = get_activation(self.activation)
        squeeze = inputs.ndim == 2
        x = inputs[:, None, :] if squeeze else inputs
        b, s, d = x.shape
        n_tok = b * s
        e = self.num_experts

        flat = x.reshape(n_tok, d)
        gsz = min(self.group_size, n_tok)
        pad = (-n_tok) % gsz
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad, d), flat.dtype)])
        g = flat.shape[0] // gsz
        grouped = flat.reshape(g, gsz, d)
        # GShard capacity convention: slots scale with k so second
        # choices aren't starved at the default capacity_factor
        cap = max(1, int(self.k * self.capacity_factor * gsz / e))

        # alignment pad rows must neither consume expert capacity nor
        # count in the balance statistics
        valid = (jnp.arange(g * gsz) < n_tok).reshape(g, gsz)

        logits = jnp.einsum("gtd,de->gte", grouped,
                            params["gate"].astype(flat.dtype)
                            ).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)            # [g, t, e]

        # top-k choices per token (argmax of the remaining probs each round)
        remaining = probs
        onehots, gates = [], []
        for _ in range(self.k):
            idx_c = jnp.argmax(remaining, axis=-1)         # [g, t]
            oh_c = jax.nn.one_hot(idx_c, e, dtype=jnp.float32)  # zoolint: disable=jit-host-sync — expert-count one-hot (e static and small): the GShard dispatch tensor, not a vocab densification
            gates.append(jnp.sum(probs * oh_c, axis=-1))
            onehots.append(oh_c * valid.astype(jnp.float32)[..., None])
            remaining = remaining * (1.0 - oh_c)
        if self.k > 1:  # GShard: gates renormalize over the chosen experts
            gate_sum = sum(gates)
            gates = [gc / jnp.maximum(gate_sum, 1e-9) for gc in gates]
        # k=1 keeps the RAW router probability (Switch transformer: the
        # gate scale is the router's gradient path)

        # capacity accounting: first choices claim slots before second
        # choices (the per-(group, expert) running count carries across
        # rounds), but the slots are DISJOINT, so all rounds merge into one
        # dispatch/combine pair and the expert FFN + all-to-all run ONCE
        claimed = jnp.zeros((g, 1, e), jnp.float32)
        dispatch_total = jnp.zeros((g, gsz, e, cap), flat.dtype)
        combine_total = jnp.zeros((g, gsz, e, cap), flat.dtype)
        any_kept = jnp.zeros(valid.shape, bool)
        onehot0 = onehots[0]  # choice-0 stats feed the balance loss
        for oh_c, gate_c in zip(onehots, gates):
            pos = ((jnp.cumsum(oh_c, axis=1) - 1.0) + claimed) * oh_c
            pos_in_expert = jnp.sum(pos, axis=-1).astype(jnp.int32)
            routed = jnp.sum(oh_c, axis=-1) > 0            # valid tokens
            keep = (pos_in_expert < cap) & routed          # capacity mask
            slot_onehot = jax.nn.one_hot(pos_in_expert, cap,  # zoolint: disable=jit-host-sync — capacity-slot one-hot (cap static and small): the GShard combine layout, not a vocab densification
                                         dtype=flat.dtype)
            dispatch = (oh_c.astype(flat.dtype)[..., None]
                        * slot_onehot[..., None, :]
                        * keep.astype(flat.dtype)[..., None, None])
            dispatch_total = dispatch_total + dispatch
            combine_total = combine_total + dispatch * gate_c.astype(
                flat.dtype)[..., None, None]
            any_kept = any_kept | keep
            claimed = claimed + jnp.sum(oh_c * keep[..., None].astype(
                jnp.float32), axis=1, keepdims=True)

        # expert inputs [g, e, cap, d] — the fixed-size dispatch the
        # exchange routes (dense path: the contraction over tokens is
        # where XLA inserts the all-to-all under expert sharding)
        xin = jnp.einsum("gtec,gtd->gecd", dispatch_total, grouped)
        w_in = params["w_in"].astype(flat.dtype)
        b_in = params["b_in"].astype(flat.dtype)
        w_out = params["w_out"].astype(flat.dtype)
        b_out = params["b_out"].astype(flat.dtype)
        ex_mesh = _exchange_mesh(g, e, self.exchange)
        if ex_mesh is not None:
            from functools import partial
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            from .pipeline import note_collective_bytes
            tok_spec = P(EXPERT_AXIS, None, None, None)
            ex = shard_map(
                partial(_expert_exchange, act=act, axis_name=EXPERT_AXIS),
                mesh=ex_mesh,
                in_specs=(tok_spec, P(EXPERT_AXIS, None, None),
                          P(EXPERT_AXIS, None), P(EXPERT_AXIS, None, None),
                          P(EXPERT_AXIS, None)),
                out_specs=tok_spec)
            # trace-time attribution: route + reverse move the full
            # dispatch buffer across the expert axis once each per step
            note_collective_bytes(2 * xin.size * xin.dtype.itemsize)
            out = ex(xin, w_in, b_in, w_out, b_out)
        else:
            h = act(jnp.einsum("gecd,edh->gech", xin, w_in)
                    + b_in[None, :, None, :])
            out = (jnp.einsum("gech,ehd->gecd", h, w_out)
                   + b_out[None, :, None, :])
        combined = jnp.einsum("gtec,gecd->gtd", combine_total, out)
        # tokens whose every choice was dropped ride the residual path
        y = jnp.where(any_kept[..., None], combined, grouped)
        y = y.reshape(-1, d)[:n_tok].reshape(b, s, d)
        onehot = onehot0  # balance statistics below use the first choice

        # switch-transformer load-balance loss: e * Σ_e (frac_tokens_e *
        # frac_probs_e), averaged over groups; the Estimator consumes it
        # from state via the `__aux_loss__` contract
        denom = jnp.maximum(jnp.sum(valid, axis=1, keepdims=True), 1.0)
        frac_tokens = jnp.sum(onehot, axis=1) / denom      # [g, e]
        vprobs = probs * valid.astype(probs.dtype)[..., None]
        frac_probs = jnp.sum(vprobs, axis=1) / denom
        aux = e * jnp.mean(jnp.sum(frac_tokens * frac_probs, axis=-1))
        # tokens whose EVERY choice overflowed: accumulated in state (the
        # Estimator drains the running count per epoch — never silent)
        dropped = jnp.sum(valid & ~any_kept).astype(jnp.int32)
        prev_drops = jnp.asarray(state.get(MOE_DROP_KEY, 0), jnp.int32)
        new_state = {AUX_LOSS_KEY: (aux * self.aux_loss_weight
                                    ).astype(jnp.float32),
                     MOE_DROP_KEY: prev_drops + dropped}
        return (y[:, 0, :] if squeeze else y), new_state

    def compute_output_shape(self, input_shape):
        return input_shape


def moe_sharding_rule(path, leaf):
    """Estimator ``param_sharding_rules`` entry: shard expert-major MoE
    parameter blocks over the ``expert`` mesh axis. Matches the LEAF key
    exactly — substring matching over the joined path would capture
    unrelated params whose names merely contain e.g. ``w_out``."""
    from jax.sharding import PartitionSpec as P
    leaf_key = str(getattr(path[-1], "key", path[-1])) if path else ""
    if leaf_key in ("w_in", "w_out", "b_in", "b_out") and leaf.ndim >= 2:
        return P(EXPERT_AXIS, *([None] * (leaf.ndim - 1)))
    return None
