"""Ring attention — context/sequence parallelism over the mesh ``seq`` axis.

New TPU-native capability (the reference has none — SURVEY §5 "long-context:
absent"): each device holds a ``seq_len / n_seq`` shard of Q, K, V. K/V shards
rotate around the ring via ``lax.ppermute`` over ICI while every device
accumulates flash-style partial softmax statistics for its local Q against
each visiting K/V shard. Communication overlaps the blockwise compute and the
full ``[seq, seq]`` score matrix never exists on any one chip, so max context
scales linearly with the number of devices on the ``seq`` axis.

Use :func:`ring_attention` inside ``shard_map`` (or let
:func:`ring_self_attention` set that up over a mesh). Differentiable: the
backward of ``ppermute`` is the reverse rotation, so gradients ride the same
ring.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import flash_attention, _NEG_INF

SEQ_AXIS = "seq"


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = SEQ_AXIS,
                   causal: bool = False,
                   scale: Optional[float] = None,
                   q_block: int = 512,
                   kv_block: int = 512) -> jax.Array:
    """Per-shard body: q/k/v are the LOCAL ``[b, h, seq/n, d]`` shards.

    Must run under ``shard_map``/``pmap`` with ``axis_name`` bound. With
    ``causal=True`` the global position of each shard (this device's
    ``axis_index``) masks future tokens across shard boundaries.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    def fold(acc, m, l, kc, vc, i):
        """Fold one visiting K/V shard's partial softmax stats into (acc, m, l)."""
        src_rank = (my + i) % n  # which shard's K/V we currently hold
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kc,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            rows = my * sq + lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
            cols = src_rank * sq + lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        # p in storage dtype: bf16 MXU multiplies with f32 accumulation
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc,
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    def hop(carry, i):
        acc, m, l, kc, vc = carry
        acc, m, l = fold(acc, m, l, kc, vc, i)
        # rotate k/v to the next device on the ring (overlaps with the next
        # hop's compute under XLA's async collective scheduling)
        perm = [(j, (j - 1) % n) for j in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (acc, m, l, kc, vc), None

    # accumulators derive from q*0 so they inherit q's varying-axis type —
    # shard_map's vma check requires the scan carry to be device-varying
    zero_q = q.astype(jnp.float32) * 0.0
    init = (zero_q,
            zero_q[..., :1] + _NEG_INF,
            zero_q[..., :1],
            k, v)
    # n-1 rotating hops, then the last visiting shard is folded without the
    # (wasted) final rotation
    (acc, m, l, kc, vc), _ = lax.scan(hop, init, jnp.arange(n - 1))
    acc, m, l = fold(acc, m, l, kc, vc, n - 1)
    return (acc / jnp.maximum(l, 1e-30)).astype(v.dtype)


def ring_self_attention(mesh: Mesh, q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Global entry: shards the seq axis of [b, h, s, d] over ``mesh['seq']``
    and runs the ring. Batch rides the ``data`` axis if present."""
    from jax import shard_map

    batch_axis = "data" if "data" in mesh.axis_names else None
    spec = P(batch_axis, None, SEQ_AXIS, None)
    fn = shard_map(
        partial(ring_attention, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = SEQ_AXIS,
                      causal: bool = False,
                      scale: Optional[float] = None) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all swaps the
    sharded axis from sequence to heads, each device computes full-sequence
    attention for ``heads/n`` heads, then all-to-all swaps back. Lower
    latency than the ring when heads ≥ devices and ICI all-to-all is cheap.

    Per-shard body for ``shard_map``; local shapes ``[b, h, seq/n, d]``.
    """
    n = lax.axis_size(axis_name)
    b, h, sq, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by seq-axis size {n}")

    def seq_to_heads(x):  # [b, h, sq, d] -> [b, h/n, sq*n, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):  # [b, h/n, sq*n, d] -> [b, h, sq, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # each device now holds the FULL sequence for its heads, so the pallas
    # flash kernel applies directly (blockwise fallback off-TPU)
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out)
