"""Ring attention — context/sequence parallelism over the mesh ``seq`` axis.

New TPU-native capability (the reference has none — SURVEY §5 "long-context:
absent"): each device holds a ``seq_len / n_seq`` shard of Q, K, V. K/V shards
rotate around the ring via ``lax.ppermute`` over ICI while every device
accumulates flash-style partial softmax statistics for its local Q against
each visiting K/V shard. Communication overlaps the blockwise compute and the
full ``[seq, seq]`` score matrix never exists on any one chip, so max context
scales linearly with the number of devices on the ``seq`` axis.

Use :func:`ring_attention` inside ``shard_map`` (or let
:func:`ring_self_attention` set that up over a mesh). Differentiable: the
backward of ``ppermute`` is the reverse rotation, so gradients ride the same
ring.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import flash_attention, flash_attention_lse, _NEG_INF

SEQ_AXIS = "seq"


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = SEQ_AXIS,
                   causal: bool = False,
                   scale: Optional[float] = None,
                   q_block: int = 512,
                   kv_block: int = 512) -> jax.Array:
    """Per-shard body: q/k/v are the LOCAL ``[b, h, seq/n, d]`` shards.

    Must run under ``shard_map``/``pmap`` with ``axis_name`` bound. With
    ``causal=True`` the global position of each shard (this device's
    ``axis_index``) masks future tokens across shard boundaries.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    def attend(kc, vc, i):
        """Attention of the local Q against one visiting K/V shard,
        returned as (normalized partial out, per-row lse) — each hop runs
        the flash kernel (pallas on TPU), and partials merge by lse."""
        def lse_attend(causal_flag):
            out, lse = flash_attention_lse(q, kc, vc, causal=causal_flag,
                                           scale=scale, q_block=q_block,
                                           kv_block=kv_block)
            # normalize to v.dtype: the pallas path returns q.dtype, the
            # blockwise path v.dtype — lax.switch needs identical avals
            # across branches for mixed-dtype q/v
            return out.astype(v.dtype), lse

        if not causal:
            return lse_attend(False)
        src_rank = (my + i) % n  # which shard's K/V we currently hold

        def full(_):  # visiting shard is entirely in the past
            return lse_attend(False)

        def diag(_):  # own shard: standard causal mask
            return lse_attend(True)

        def skip(_):  # entirely in the future: contributes nothing
            # neutral element derives from q so it stays device-varying
            # under shard_map's vma check
            return ((q * 0).astype(v.dtype),
                    q[..., 0].astype(jnp.float32) * 0 + _NEG_INF)

        idx = jnp.where(src_rank < my, 0, jnp.where(src_rank == my, 1, 2))
        return lax.switch(idx, [full, diag, skip], None)

    def merge(out, lse, out_h, lse_h):
        lse_new = jnp.logaddexp(lse, lse_h)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_hop = jnp.exp(lse_h - lse_new)[..., None]
        return (out * w_old + out_h.astype(jnp.float32) * w_hop), lse_new

    def hop(carry, i):
        out, lse, kc, vc = carry
        out_h, lse_h = attend(kc, vc, i)
        out, lse = merge(out, lse, out_h, lse_h)
        # rotate k/v to the next device on the ring (overlaps with the next
        # hop's compute under XLA's async collective scheduling)
        perm = [(j, (j - 1) % n) for j in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (out, lse, kc, vc), None

    # accumulators derive from q*0 so they inherit q's varying-axis type —
    # shard_map's vma check requires the scan carry to be device-varying
    init = (q.astype(jnp.float32) * 0.0,
            q[..., 0].astype(jnp.float32) * 0 + _NEG_INF,
            k, v)
    # n-1 rotating hops, then the last visiting shard is folded without the
    # (wasted) final rotation
    (out, lse, kc, vc), _ = lax.scan(hop, init, jnp.arange(n - 1))
    out_h, lse_h = attend(kc, vc, n - 1)
    out, _ = merge(out, lse, out_h, lse_h)
    return out.astype(v.dtype)


def ring_self_attention(mesh: Mesh, q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Global entry: shards the seq axis of [b, h, s, d] over ``mesh['seq']``
    and runs the ring. Batch rides the ``data`` axis if present."""
    from jax import shard_map

    batch_axis = "data" if "data" in mesh.axis_names else None
    spec = P(batch_axis, None, SEQ_AXIS, None)
    fn = shard_map(
        partial(ring_attention, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = SEQ_AXIS,
                      causal: bool = False,
                      scale: Optional[float] = None) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all swaps the
    sharded axis from sequence to heads, each device computes full-sequence
    attention for ``heads/n`` heads, then all-to-all swaps back. Lower
    latency than the ring when heads ≥ devices and ICI all-to-all is cheap.

    Per-shard body for ``shard_map``; local shapes ``[b, h, seq/n, d]``.
    """
    n = lax.axis_size(axis_name)
    b, h, sq, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by seq-axis size {n}")

    def seq_to_heads(x):  # [b, h, sq, d] -> [b, h/n, sq*n, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):  # [b, h/n, sq*n, d] -> [b, h, sq, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # each device now holds the FULL sequence for its heads, so the pallas
    # flash kernel applies directly (blockwise fallback off-TPU)
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out)
