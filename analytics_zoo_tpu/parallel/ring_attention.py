"""Ring attention — context/sequence parallelism over the mesh ``seq`` axis.

New TPU-native capability (the reference has none — SURVEY §5 "long-context:
absent"): each device holds a ``seq_len / n_seq`` shard of Q, K, V. K/V shards
rotate around the ring via ``lax.ppermute`` over ICI while every device
accumulates flash-style partial softmax statistics for its local Q against
each visiting K/V shard. Communication overlaps the blockwise compute and the
full ``[seq, seq]`` score matrix never exists on any one chip, so max context
scales linearly with the number of devices on the ``seq`` axis.

Use :func:`ring_attention` inside ``shard_map`` (or let
:func:`ring_self_attention` set that up over a mesh). Differentiable: the
backward of ``ppermute`` is the reverse rotation, so gradients ride the same
ring.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import flash_attention, flash_attention_lse, _NEG_INF
from .pipeline import _axis_size, _vary

SEQ_AXIS = "seq"


def _rotate_perm(n: int):
    """Ring rotation: device j sends its K/V shard to device j-1."""
    return [(j, (j - 1) % n) for j in range(n)]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = SEQ_AXIS,
                   causal: bool = False,
                   scale: Optional[float] = None,
                   q_block: int = 512,
                   kv_block: int = 512) -> jax.Array:
    """Per-shard body: q/k/v are the LOCAL ``[b, h, seq/n, d]`` shards.

    Must run under ``shard_map``/``pmap`` with ``axis_name`` bound. With
    ``causal=True`` the global position of each shard (this device's
    ``axis_index``) masks future tokens across shard boundaries.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    def attend(kc, vc, i):
        """Attention of the local Q against one visiting K/V shard,
        returned as (normalized partial out, per-row lse) — each hop runs
        the flash kernel (pallas on TPU), and partials merge by lse."""
        def lse_attend(causal_flag):
            out, lse = flash_attention_lse(q, kc, vc, causal=causal_flag,
                                           scale=scale, q_block=q_block,
                                           kv_block=kv_block)
            # normalize to v.dtype: the pallas path returns q.dtype, the
            # blockwise path v.dtype — lax.switch needs identical avals
            # across branches for mixed-dtype q/v
            return out.astype(v.dtype), lse

        if not causal:
            return lse_attend(False)
        src_rank = (my + i) % n  # which shard's K/V we currently hold

        def full(_):  # visiting shard is entirely in the past
            return lse_attend(False)

        def diag(_):  # own shard: standard causal mask
            return lse_attend(True)

        def skip(_):  # entirely in the future: contributes nothing
            # neutral element derives from q so it stays device-varying
            # under shard_map's vma check
            return ((q * 0).astype(v.dtype),
                    q[..., 0].astype(jnp.float32) * 0 + _NEG_INF)

        idx = jnp.where(src_rank < my, 0, jnp.where(src_rank == my, 1, 2))
        return lax.switch(idx, [full, diag, skip], None)

    def merge(out, lse, out_h, lse_h):
        lse_new = jnp.logaddexp(lse, lse_h)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_hop = jnp.exp(lse_h - lse_new)[..., None]
        return (out * w_old + out_h.astype(jnp.float32) * w_hop), lse_new

    def hop(carry, i):
        out, lse, kc, vc = carry
        out_h, lse_h = attend(kc, vc, i)
        out, lse = merge(out, lse, out_h, lse_h)
        # rotate k/v to the next device on the ring (overlaps with the next
        # hop's compute under XLA's async collective scheduling)
        perm = _rotate_perm(n)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (out, lse, kc, vc), None

    # accumulators derive from q*0 so they inherit q's varying-axis type —
    # shard_map's vma check requires the scan carry to be device-varying
    init = (q.astype(jnp.float32) * 0.0,
            q[..., 0].astype(jnp.float32) * 0 + _NEG_INF,
            k, v)
    # n-1 rotating hops, then the last visiting shard is folded without the
    # (wasted) final rotation
    (out, lse, kc, vc), _ = lax.scan(hop, init, jnp.arange(n - 1))
    out_h, lse_h = attend(kc, vc, n - 1)
    out, _ = merge(out, lse, out_h, lse_h)
    return out.astype(v.dtype)


def ring_self_attention(mesh: Mesh, q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Global entry: shards the seq axis of [b, h, s, d] over ``mesh['seq']``
    and runs the ring. Batch rides the ``data`` axis if present."""
    from jax.experimental.shard_map import shard_map

    batch_axis = "data" if "data" in mesh.axis_names else None
    spec = P(batch_axis, None, SEQ_AXIS, None)
    fn = shard_map(
        partial(ring_attention, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ring_masked_context(q: jax.Array, k_blk: jax.Array, v_blk: jax.Array,
                        visible_blk: jax.Array,
                        scale: float,
                        axis_name: str = SEQ_AXIS) -> jax.Array:
    """Per-shard decode-cache attention over a ``ppermute`` ring of KV
    BLOCKS: the 100k+-token context path where no single device holds the
    whole cache. Each device owns one ``[b, h, K/n, d]`` block of the key/
    value buffers plus the matching slice of the visibility mask; ``q``
    (the decode query, small ``t``) is replicated. Every ring step runs
    the literal ``masked_context`` score arithmetic against the visiting
    block — the same ``bhtd,bhkd`` float32 einsum, the same ``_NEG_INF``
    masking — and folds it into running (max, numerator, denominator)
    streaming-softmax statistics; blocks then rotate one hop. After n-1
    rotations every block has visited every device and ``num/den``
    reproduces ``masked_context`` over the full buffer (the reduction is
    blockwise, so parity vs the single-device softmax is documented
    float32 tolerance, not bitwise; a fully-masked row degrades to the
    same uniform average ``softmax`` of an all-``_NEG_INF`` row yields).
    """
    n = _axis_size(axis_name)

    def partial_scores(kc, vis):
        # one ring step == masked_context's score arithmetic, verbatim
        s = jnp.einsum("bhtd,bhkd->bhtk", q, kc,
                       preferred_element_type=jnp.float32) * scale
        return jnp.where(vis, s, _NEG_INF)

    def fold(carry_m, carry_num, carry_den, kc, vc, vis):
        s = partial_scores(kc, vis)
        m_new = jnp.maximum(carry_m, jnp.max(s, axis=-1))
        w_old = jnp.exp(carry_m - m_new)
        p = jnp.exp(s - m_new[..., None])
        num = (carry_num * w_old[..., None]
               + jnp.einsum("bhtk,bhkd->bhtd", p.astype(vc.dtype), vc,
                            preferred_element_type=jnp.float32))
        den = carry_den * w_old + jnp.sum(p, axis=-1)
        return m_new, num, den

    def hop(carry, i):
        m, num, den, kc, vc, vis = carry
        m, num, den = fold(m, num, den, kc, vc, vis)
        perm = _rotate_perm(n)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        vis = lax.ppermute(vis, axis_name, perm)
        return (m, num, den, kc, vc, vis), None

    # accumulators derive from q so they inherit its varying-axis type
    m0 = q[..., 0].astype(jnp.float32) * 0 + _NEG_INF
    num0 = q.astype(jnp.float32) * 0.0
    den0 = q[..., 0].astype(jnp.float32) * 0.0
    (m, num, den, kc, vc, vis), _ = lax.scan(
        hop, (m0, num0, den0, k_blk, v_blk, visible_blk),
        jnp.arange(n - 1))
    m, num, den = fold(m, num, den, kc, vc, vis)
    return (num / den[..., None]).astype(q.dtype)


def ring_context(mesh: Mesh, q: jax.Array, k_buf: jax.Array,
                 v_buf: jax.Array, visible: jax.Array,
                 scale: float) -> jax.Array:
    """Global entry: ``masked_context`` semantics with the KEY axis of the
    ``[b, h, K, d]`` K/V buffers (and the matching ``[b, h, t, K]`` mask)
    sharded over ``mesh['seq']`` — the whole cache never materializes on
    one device. Drop-in for ``masked_context(q, k, v, visible, scale)``
    at documented float32 tolerance."""
    from jax.experimental.shard_map import shard_map


    def body(qr, kc, vc, vis):
        ctx = ring_masked_context(_vary(qr, SEQ_AXIS), kc, vc, vis, scale)
        # every device computed the same logical result off the full ring;
        # the masked psum (exact zeros elsewhere) makes that invariance
        # visible to shard_map's replication check without changing values
        return lax.psum(
            jnp.where(lax.axis_index(SEQ_AXIS) == 0, ctx,
                      jnp.zeros_like(ctx)), SEQ_AXIS)

    kv_spec = P(None, None, SEQ_AXIS, None)
    vis_spec = P(None, None, None, SEQ_AXIS)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(), kv_spec, kv_spec, vis_spec),
                   out_specs=P())
    return fn(q, k_buf, v_buf, visible)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = SEQ_AXIS,
                      causal: bool = False,
                      scale: Optional[float] = None) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all swaps the
    sharded axis from sequence to heads, each device computes full-sequence
    attention for ``heads/n`` heads, then all-to-all swaps back. Lower
    latency than the ring when heads ≥ devices and ICI all-to-all is cheap.

    Per-shard body for ``shard_map``; local shapes ``[b, h, seq/n, d]``.
    """
    n = _axis_size(axis_name)
    b, h, sq, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by seq-axis size {n}")

    def seq_to_heads(x):  # [b, h, sq, d] -> [b, h/n, sq*n, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):  # [b, h/n, sq*n, d] -> [b, h, sq, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # each device now holds the FULL sequence for its heads, so the pallas
    # flash kernel applies directly (blockwise fallback off-TPU)
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out)
