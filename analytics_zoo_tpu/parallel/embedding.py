"""Sharded sparse-embedding engine: all-to-all lookup, segment-sum grads.

The reference pushes embedding tables through the same dense AllReduce as
every other parameter; a "millions of users" vocabulary neither fits HBM
replicated nor trains faster than its dense allreduce. This module shards
the VOCAB axis of a table over a mesh axis and keeps every step sparse:

* **forward** — dedup the local ids (``jnp.unique`` with a static size),
  route each unique id to its owning shard with one ``lax.all_to_all``,
  gather locally, and reverse-exchange the rows. Cost is
  O(ids x dim) exchange bytes, never O(vocab).
* **backward** — a ``custom_vjp`` whose backward ``segment_sum``s the
  output cotangent per unique id, reverse-exchanges the per-unique grads,
  and scatter-adds into *only the touched rows of the local shard*. The
  table cotangent is a GSPMD vocab-sharded array (its aval must match the
  table's), but it is never densified per-id (no one-hot), never
  replicated and never all-reduced.
* **update** — ``apply_row_update`` mirrors the exact optax arithmetic
  (sgd / adagrad / lazy adam) on the touched rows only, so optimizer
  state for untouched rows is neither read nor written.
* **cold tier** — ``HostColdTier`` keeps the coldest rows in a host-DRAM
  shared-memory slab (same machinery as ``feature/worker_pool.py``),
  served through ``pure_callback`` and trained with an eager host-side
  SGD in the backward callback.

The table is sharded over the SAME mesh axis the batch rides (the data
axis by default): each device requests rows for its own batch shard, so
the backward needs no cross-replica psum at all — every device's
scatter-add is complete for its shard once the grad exchange lands.

See docs/embeddings.md for the layout, parity and cold-tier contracts.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..common import metrics as _embed_metrics
from ..common.config import global_config

_M_OOB = _embed_metrics.counter(
    "embed.oob_ids_total",
    "Out-of-range embedding ids clamped by the data.validate_ids=count "
    "policy (keras/layers/embedding.py lookups).")
_M_EXCHANGE = _embed_metrics.counter(
    "embed.exchange_bytes_total",
    "Bytes moved by the sharded-lookup all-to-all exchanges (request ids "
    "+ gathered rows, summed over devices), attributed per train step "
    "from the traced program.")
_M_GRAD = _embed_metrics.counter(
    "embed.grad_bytes_total",
    "Bytes moved by the sharded embedding BACKWARD exchange (per-unique "
    "segment-sum grads, summed over devices), attributed per train step "
    "from the traced program.")
_M_COLD_HITS = _embed_metrics.counter(
    "embed.cold_hits_total",
    "Embedding ids served from the host-DRAM cold tier.")
_M_COLD_BYTES = _embed_metrics.gauge(
    "embed.cold_bytes",
    "Total host-DRAM shared-memory bytes held by live cold tiers.")
_M_TABLE_BYTES = _embed_metrics.gauge(
    "embed.table_bytes",
    "Total GLOBAL bytes of sharded embedding tables (padded vocab x dim; "
    "per-device HBM share is this / shard count).")

#: model-state key prefix under which layers stash the forward exchange
#: blob ("rows") so the estimator's sparse update can reuse the routing
#: without a second all-to-all. Stripped from the state tree by
#: ``pop_stashed_rows`` before the state is carried across steps.
ROWS_PREFIX = "__embed_rows__"

# ---------------------------------------------------------------------------
# default mesh plumbing

_DEFAULT_MESH: Optional[Mesh] = None


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    """Install the mesh layers shard against when they build outside an
    explicit mesh context (the estimator calls this with its own mesh)."""
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def default_mesh() -> Optional[Mesh]:
    if _DEFAULT_MESH is not None:
        return _DEFAULT_MESH
    try:
        from ..common.context import get_context
        return get_context().mesh
    except Exception:
        return None


# ---------------------------------------------------------------------------
# shard spec

@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """Static description of one vocab-sharded table (hashable: it rides
    as a ``custom_vjp`` nondiff argument and inside jit closures)."""
    mesh: Mesh
    axis: str            # mesh axis the vocab (and the ids) shard over
    shards: int          # mesh.shape[axis]
    rows_per_shard: int  # padded vocab / shards
    vocab: int           # logical (unpadded) hot vocab
    dim: int

    @property
    def padded(self) -> int:
        """Padded vocab; also the SENTINEL id: it routes to the last
        shard with an out-of-range local row, so gathers fill zeros and
        gradient scatters drop."""
        return self.shards * self.rows_per_shard

    @property
    def table_bytes(self) -> int:
        return self.padded * self.dim * 4

    @property
    def device_bytes(self) -> int:
        return self.rows_per_shard * self.dim * 4


def make_shard_spec(vocab: int, dim: int, mesh: Optional[Mesh] = None,
                    axis: Optional[str] = None) -> Optional[ShardSpec]:
    """Build a ShardSpec for a table, or None when there is nothing to
    shard over (no mesh, or a single-device axis)."""
    mesh = mesh if mesh is not None else default_mesh()
    if mesh is None:
        return None
    if axis is None:
        from .mesh import embedding_axis
        axis = embedding_axis(mesh)
    if axis not in mesh.axis_names:
        return None
    shards = int(mesh.shape[axis])
    if shards <= 1:
        return None
    rps = -(-int(vocab) // shards)  # ceil
    return ShardSpec(mesh=mesh, axis=axis, shards=shards,
                     rows_per_shard=rps, vocab=int(vocab), dim=int(dim))


def can_run(spec: Optional[ShardSpec], n_ids: int) -> bool:
    """The sharded path needs the flat id count divisible by the shard
    count (ids ride the same axis); otherwise callers fall back to the
    dense gather, which computes identical values."""
    return (spec is not None and spec.shards > 1
            and n_ids >= spec.shards and n_ids % spec.shards == 0)


# ---------------------------------------------------------------------------
# trace-time byte attribution (read by the estimator around compilation)

_TRACE_BYTES = {"exchange": 0, "grad": 0}


def reset_trace_bytes() -> None:
    _TRACE_BYTES["exchange"] = 0
    _TRACE_BYTES["grad"] = 0


def take_trace_bytes() -> Tuple[int, int]:
    ex, gr = _TRACE_BYTES["exchange"], _TRACE_BYTES["grad"]
    reset_trace_bytes()
    return ex, gr


def note_exchange_bytes(ex: int, gr: int) -> None:
    """Host-side per-step counter feed (the estimator calls this once per
    dispatched step with the trace-attributed byte totals)."""
    if ex:
        _M_EXCHANGE.inc(float(ex))
    if gr:
        _M_GRAD.inc(float(gr))


_TABLE_SIZES: Dict[str, int] = {}
_COLD_SIZES: Dict[str, int] = {}


def note_table_bytes(key: str, nbytes: int) -> None:
    _TABLE_SIZES[key] = int(nbytes)
    _M_TABLE_BYTES.set(float(sum(_TABLE_SIZES.values())))


def _note_cold_bytes(key: str, nbytes: int) -> None:
    if nbytes:
        _COLD_SIZES[key] = int(nbytes)
    else:
        _COLD_SIZES.pop(key, None)
    _M_COLD_BYTES.set(float(sum(_COLD_SIZES.values())))


# ---------------------------------------------------------------------------
# id validation (satellite: no more silent OOB clamps)

def _note_oob(n) -> None:
    n = int(n)
    if n:
        _M_OOB.inc(n)


def validate_ids(idx, vocab: int, allow_negative: bool = False):
    """Apply the ``data.validate_ids`` policy to a raw id array.

    * ``clamp``: the historical silent ``jnp.take`` clip.
    * ``count`` (default): clamp, but count offenders into
      ``embed.oob_ids_total`` (async debug callback — no dispatch stall).
    * ``raise``: raise ValueError when the ids are concrete (eager layer
      calls, i.e. unit tests); degrades to ``count`` under jit where a
      Python raise cannot see values.

    ``allow_negative`` keeps negative ids intact (SparseEmbedding /
    SparseDense use them as padding and mask them downstream); only the
    upper bound is then validated.
    """
    mode = str(global_config().get("data.validate_ids"))
    if mode not in ("clamp", "count", "raise"):
        raise ValueError(f"data.validate_ids={mode!r}: expected "
                         f"'clamp', 'count' or 'raise'")
    if allow_negative:
        clamped = jnp.minimum(idx, vocab - 1)
        if mode == "clamp":
            return clamped
        bad = idx >= vocab
    else:
        clamped = jnp.clip(idx, 0, vocab - 1)
        if mode == "clamp":
            return clamped
        bad = (idx < 0) | (idx >= vocab)
    n_bad = jnp.sum(bad)
    if mode == "raise" and not isinstance(n_bad, jax.core.Tracer):
        count = int(n_bad)
        if count:
            raise ValueError(
                f"{count} embedding id(s) out of range [0, {vocab}) "
                f"(data.validate_ids=raise)")
        return clamped
    jax.debug.callback(_note_oob, n_bad)
    return clamped


# ---------------------------------------------------------------------------
# per-shard bodies (module-level: policed by scripts/check_hot_path_syncs.py
# — no densified one-hot, no per-row Python loops, no host syncs)

def fused_kernels():
    """Trace-time resolution of the fused local-compute kernels
    (``ops/embedding_kernels.py``). Returns the module when the
    ``kernels.fused_embedding`` knob is on, else None — callers then trace
    the inline lax ops below, the bit-parity reference. The fused CPU path
    traces the SAME ops in the same order, so toggling the knob off-TPU is
    a jaxpr no-op (tests/test_fused_embedding.py pins this bitwise)."""
    if not global_config().get("kernels.fused_embedding"):
        return None
    from ..ops import embedding_kernels as _ek
    return _ek


def _routing(spec, ids):
    """Shared dedup-unique routing: sorted uniques, owning shard, and the
    (destination, slot) address of each unique in the request matrix."""
    n = ids.shape[0]
    u, inv = jnp.unique(ids, size=n, fill_value=spec.padded,
                        return_inverse=True)
    d = jnp.minimum(u // spec.rows_per_shard, spec.shards - 1)
    d = d.astype(jnp.int32)
    local_row = (u - d * spec.rows_per_shard).astype(jnp.int32)
    starts = jnp.searchsorted(d, jnp.arange(spec.shards, dtype=jnp.int32))
    slot = jnp.arange(n, dtype=jnp.int32) - starts[d].astype(jnp.int32)
    return u, inv.ravel(), d, local_row, slot


def _lookup_body(spec, tshard, ids):
    """Per-device forward: unique -> all-to-all id exchange -> local
    gather -> reverse row exchange -> undup. ``recv`` (the local rows
    other shards requested from us, SENTINEL-marked with rows_per_shard)
    is returned so backward and the sparse update reuse the routing."""
    n = ids.shape[0]
    _u, inv, d, local_row, slot = _routing(spec, ids)
    req = jnp.full((spec.shards, n), spec.rows_per_shard, dtype=jnp.int32)
    req = req.at[d, slot].set(local_row)
    recv = lax.all_to_all(req, spec.axis, split_axis=0, concat_axis=0,
                          tiled=True)
    ek = fused_kernels()
    if ek is not None:
        # fused local gather (pallas row-DMA kernel on TPU; identical
        # fill-mode take elsewhere)
        rows = ek.gather_rows(tshard, recv.ravel())
    else:
        rows = jnp.take(tshard, recv.ravel(), axis=0, mode="fill",
                        fill_value=0)
    back = lax.all_to_all(rows.reshape(spec.shards, n, spec.dim), spec.axis,
                          split_axis=0, concat_axis=0, tiled=True)
    out = jnp.take(back[d, slot], inv, axis=0)
    return out, recv


def _lookup_bwd_body(spec, g, ids, recv):
    """Per-device backward: segment-sum the cotangent per unique id,
    reverse-exchange the per-unique grads, scatter-add into only the
    touched rows of the local shard (SENTINEL rows drop)."""
    n = ids.shape[0]
    _u, inv, d, _local_row, slot = _routing(spec, ids)
    ek = fused_kernels()
    if ek is not None:
        # fused segment-sum straight into the request-shaped buffer, and
        # (post-exchange) a fused scatter-add into the row-subset
        # cotangent — [rows_per_shard, dim], never a dense [vocab, dim]
        g_req = ek.segment_grads(g, inv, d, slot, spec.shards)
    else:
        g_u = jax.ops.segment_sum(g, inv, num_segments=n)
        g_req = jnp.zeros((spec.shards, n, spec.dim),
                          g.dtype).at[d, slot].set(g_u)
    g_recv = lax.all_to_all(g_req, spec.axis, split_axis=0, concat_axis=0,
                            tiled=True)
    if ek is not None:
        return ek.scatter_rows(g_recv.reshape(spec.shards * n, spec.dim),
                               recv.ravel(), spec.rows_per_shard)
    ct = jnp.zeros((spec.rows_per_shard, spec.dim), g.dtype)
    ct = ct.at[recv.ravel()].add(g_recv.reshape(spec.shards * n, spec.dim),
                                 mode="drop")
    return ct


def _update_body(kind, hyper, spec, tshard, gshard, recv, *opt):
    """Per-device sparse row-subset optimizer update. Gathers ONLY the
    rows other shards touched this step (``recv``), applies the exact
    optax arithmetic for ``kind``, and scatters the rows back with
    mode=drop (SENTINEL markers vanish; duplicate requests of one row
    read the same summed grad and write identical values)."""
    flat = recv.ravel()
    t_rows = jnp.take(tshard, flat, axis=0, mode="fill", fill_value=0)
    g_rows = jnp.take(gshard, flat, axis=0, mode="fill", fill_value=0)
    lr = hyper["lr"]
    if kind == "sgd":
        # optax.sgd: u = (-lr) * g; p' = (p + u).astype(p.dtype)
        new_rows = (t_rows + (-lr) * g_rows).astype(tshard.dtype)
        return (tshard.at[flat].set(new_rows, mode="drop"),)
    if kind == "adagrad":
        # optax.scale_by_rss: acc' = g^2 + acc; u = rsqrt(acc' + eps) * g
        acc = opt[0]
        acc_rows = jnp.take(acc, flat, axis=0, mode="fill", fill_value=0)
        nu = g_rows * g_rows + acc_rows
        inv_rt = jnp.where(nu > 0, lax.rsqrt(nu + hyper["eps"]),
                           jnp.zeros_like(nu))
        new_rows = (t_rows + (-lr) * (inv_rt * g_rows)).astype(tshard.dtype)
        return (tshard.at[flat].set(new_rows, mode="drop"),
                acc.at[flat].set(nu.astype(acc.dtype), mode="drop"))
    # lazy adam: touched-row moments, global step count (documented as NOT
    # bit-identical to dense adam — stale-row bias correction differs)
    mu, nu, count = opt
    b1, b2 = hyper["b1"], hyper["b2"]
    mu_rows = jnp.take(mu, flat, axis=0, mode="fill", fill_value=0)
    nu_rows = jnp.take(nu, flat, axis=0, mode="fill", fill_value=0)
    new_mu = (1.0 - b1) * g_rows + b1 * mu_rows
    new_nu = (1.0 - b2) * (g_rows * g_rows) + b2 * nu_rows
    new_count = jnp.where(count < jnp.iinfo(jnp.int32).max, count + 1, count)
    c = new_count.astype(g_rows.dtype)
    mu_hat = new_mu / (1.0 - b1 ** c)
    nu_hat = new_nu / (1.0 - b2 ** c)
    step = (-lr) * (mu_hat / (jnp.sqrt(nu_hat) + hyper["eps"]))
    new_rows = (t_rows + step).astype(tshard.dtype)
    return (tshard.at[flat].set(new_rows, mode="drop"),
            mu.at[flat].set(new_mu.astype(mu.dtype), mode="drop"),
            nu.at[flat].set(new_nu.astype(nu.dtype), mode="drop"),
            new_count)


# ---------------------------------------------------------------------------
# lookup: custom_vjp over the shard_map'd bodies

def _lookup_impl(table, ids, spec):
    n_loc = ids.shape[0] // spec.shards
    _TRACE_BYTES["exchange"] += spec.shards * 2 * spec.shards * n_loc * (
        4 + spec.dim * table.dtype.itemsize)
    out, recv = shard_map(
        partial(_lookup_body, spec), mesh=spec.mesh,
        in_specs=(P(spec.axis, None), P(spec.axis)),
        out_specs=(P(spec.axis, None), P(spec.axis, None)))(table, ids)
    return out, recv


def _grad_impl(g, ids, recv, spec):
    n_loc = ids.shape[0] // spec.shards
    _TRACE_BYTES["grad"] += (spec.shards * 2 * spec.shards * n_loc
                             * spec.dim * 4)
    return shard_map(
        partial(_lookup_bwd_body, spec), mesh=spec.mesh,
        in_specs=(P(spec.axis, None), P(spec.axis), P(spec.axis, None)),
        out_specs=P(spec.axis, None))(g, ids, recv)


def _int_zeros(x):
    return np.zeros(np.shape(x), dtype=jax.dtypes.float0)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def sharded_lookup(table, flat_ids, spec):
    """Gather ``table[flat_ids]`` from a vocab-sharded ``[padded, dim]``
    table. Returns ``(rows [n, dim], recv_blob)``; the blob is the
    per-shard touched-row routing, opaque outside this module — feed it
    back to ``apply_row_update``. ids == ``spec.padded`` (SENTINEL) read
    zero rows and receive no gradient."""
    return _lookup_impl(table, flat_ids, spec)


def _lookup_fwd(table, flat_ids, spec):
    out, recv = _lookup_impl(table, flat_ids, spec)
    return (out, recv), (flat_ids, recv)


def _lookup_bwd(spec, res, cts):
    flat_ids, recv = res
    g_out, _g_recv = cts
    ct_table = _grad_impl(g_out, flat_ids, recv, spec)
    return ct_table, _int_zeros(flat_ids)


sharded_lookup.defvjp(_lookup_fwd, _lookup_bwd)


# ---------------------------------------------------------------------------
# sparse row-subset optimizer update

def init_row_state(kind: str, table) -> Dict[str, Any]:
    """Row-wise optimizer state for one sharded table, mirroring the
    corresponding optax init (adagrad: initial_accumulator_value=0.1)."""
    if kind == "sgd":
        return {}
    if kind == "adagrad":
        return {"acc": jnp.full_like(table, 0.1)}
    if kind == "adam":
        return {"mu": jnp.zeros_like(table), "nu": jnp.zeros_like(table),
                "count": jnp.zeros((), jnp.int32)}
    raise ValueError(f"no sparse row update for optimizer kind {kind!r}")


def apply_row_update(kind: str, hyper: Dict[str, float], spec: ShardSpec,
                     table, grad_ct, rows_blob, row_state):
    """Update only the touched rows of one sharded table (and their
    optimizer state) from the dense-but-sharded cotangent + the forward
    exchange blob. Returns ``(new_table, new_row_state)``."""
    spec2 = P(spec.axis, None)
    if kind == "sgd":
        (new_table,) = shard_map(
            partial(_update_body, kind, hyper, spec), mesh=spec.mesh,
            in_specs=(spec2, spec2, spec2), out_specs=(spec2,))(
            table, grad_ct, rows_blob)
        return new_table, {}
    if kind == "adagrad":
        new_table, acc = shard_map(
            partial(_update_body, kind, hyper, spec), mesh=spec.mesh,
            in_specs=(spec2, spec2, spec2, spec2),
            out_specs=(spec2, spec2))(
            table, grad_ct, rows_blob, row_state["acc"])
        return new_table, {"acc": acc}
    if kind == "adam":
        new_table, mu, nu, count = shard_map(
            partial(_update_body, kind, hyper, spec), mesh=spec.mesh,
            in_specs=(spec2, spec2, spec2, spec2, spec2, P()),
            out_specs=(spec2, spec2, spec2, P()))(
            table, grad_ct, rows_blob, row_state["mu"], row_state["nu"],
            row_state["count"])
        return new_table, {"mu": mu, "nu": nu, "count": count}
    raise ValueError(f"no sparse row update for optimizer kind {kind!r}")


def apply_dense_update(kind: str, hyper: Dict[str, float], table, grad,
                       row_state):
    """Fallback when a step produced no exchange blob (the lookup fell back
    to the dense gather): the same optimizer arithmetic as
    ``apply_row_update`` applied to every row. Elementwise, so GSPMD keeps
    the table's vocab sharding; zero-grad rows are bitwise no-ops for
    sgd/adagrad."""
    lr = hyper["lr"]
    if kind == "sgd":
        return (table + (-lr) * grad).astype(table.dtype), {}
    if kind == "adagrad":
        acc = row_state["acc"]
        nu = grad * grad + acc
        inv_rt = jnp.where(nu > 0, lax.rsqrt(nu + hyper["eps"]),
                           jnp.zeros_like(nu))
        return ((table + (-lr) * (inv_rt * grad)).astype(table.dtype),
                {"acc": nu.astype(acc.dtype)})
    if kind == "adam":
        mu, nu, count = row_state["mu"], row_state["nu"], row_state["count"]
        b1, b2 = hyper["b1"], hyper["b2"]
        new_mu = (1.0 - b1) * grad + b1 * mu
        new_nu = (1.0 - b2) * (grad * grad) + b2 * nu
        new_count = jnp.where(count < jnp.iinfo(jnp.int32).max,
                              count + 1, count)
        c = new_count.astype(grad.dtype)
        mu_hat = new_mu / (1.0 - b1 ** c)
        nu_hat = new_nu / (1.0 - b2 ** c)
        step = (-lr) * (mu_hat / (jnp.sqrt(nu_hat) + hyper["eps"]))
        return ((table + step).astype(table.dtype),
                {"mu": new_mu.astype(mu.dtype), "nu": new_nu.astype(nu.dtype),
                 "count": new_count})
    raise ValueError(f"no sparse row update for optimizer kind {kind!r}")


def pop_stashed_rows(model_state):
    """Split the exchange blobs layers stashed under ``ROWS_PREFIX`` out
    of a model-state tree. Returns ``({layer: {param_key: blob}},
    cleaned_state)`` — cleaned_state drops layer entries emptied by the
    pop so the carried state keeps the init-time tree structure."""
    if not isinstance(model_state, dict):
        return {}, model_state
    rows: Dict[str, Dict[str, Any]] = {}
    clean = {}
    for lname, sub in model_state.items():
        if not isinstance(sub, dict):
            clean[lname] = sub
            continue
        keep = {}
        for k, v in sub.items():
            if isinstance(k, str) and k.startswith(ROWS_PREFIX):
                rows.setdefault(lname, {})[k[len(ROWS_PREFIX):]] = v
            else:
                keep[k] = v
        if keep:
            clean[lname] = keep
    return rows, clean


# ---------------------------------------------------------------------------
# host-DRAM cold tier

class HostColdTier:
    """Host-resident tail of an embedding table, in a shared-memory slab
    (same machinery as feature/worker_pool.py so other local processes
    could map it). Rows are served into the jitted forward through
    ``pure_callback`` and trained with an eager SGD inside an ordered
    ``io_callback`` — no device HBM, no optimizer state on device.

    Single-process scope: multi-host training with a cold tier is not
    supported (the slab lives in one host's DRAM).
    """

    _ALIGN = 128

    def __init__(self, rows: int, dim: int, name: str = "cold",
                 lr: Optional[float] = None):
        from multiprocessing import shared_memory
        self.rows = int(rows)
        self.dim = int(dim)
        self.name = name
        self.lr = float(global_config().get("embed.cold_lr")
                        if lr is None else lr)
        nbytes = self.rows * self.dim * 4
        slab = ((nbytes + self._ALIGN - 1) // self._ALIGN) * self._ALIGN
        self._shm = shared_memory.SharedMemory(create=True,
                                               size=max(slab, self._ALIGN))
        self.view = np.ndarray((self.rows, self.dim), dtype=np.float32,
                               buffer=self._shm.buf)
        self.view[:] = 0.0
        self._closed = False
        _note_cold_bytes(self._shm.name, self._shm.size)

    # identity hash/eq (object defaults) — the tier is a custom_vjp
    # nondiff argument and must stay hashable despite the mutable slab

    @property
    def nbytes(self) -> int:
        return self.rows * self.dim * 4

    def fill(self, values) -> None:
        self.view[:] = np.asarray(values, dtype=np.float32)

    def fetch(self, rel_ids) -> np.ndarray:
        """Rows for relative ids; negatives / out-of-range return zeros
        (non-cold positions are masked to -1 by the caller)."""
        rel = np.asarray(rel_ids).ravel()
        ok = (rel >= 0) & (rel < self.rows)
        out = np.zeros((rel.shape[0], self.dim), dtype=np.float32)
        if ok.any():
            out[ok] = self.view[rel[ok]]
            _M_COLD_HITS.inc(int(ok.sum()))
        return out

    def apply_grad(self, rel_ids, g) -> None:
        rel = np.asarray(rel_ids).ravel()
        ok = (rel >= 0) & (rel < self.rows)
        if ok.any():
            np.add.at(self.view, rel[ok],
                      (-self.lr) * np.asarray(g)[ok].astype(np.float32))

    def save(self, path: str) -> None:
        np.save(path, self.view)

    def load(self, path: str) -> None:
        self.view[:] = np.load(path).astype(np.float32)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        _note_cold_bytes(self._shm.name, 0)
        self.view = None
        try:
            self._shm.close()
            self._shm.unlink()
        except Exception:
            pass

    def __del__(self):  # best-effort slab reclaim
        try:
            self.close()
        except Exception:
            pass


def _cold_fetch_impl(tier, rel_ids):
    n = rel_ids.shape[0]
    return jax.pure_callback(
        tier.fetch, jax.ShapeDtypeStruct((n, tier.dim), jnp.float32),
        rel_ids)


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def cold_lookup(tier, rel_ids, anchor):
    """Host-DRAM gather: rows for relative cold ids (-1 = not cold ->
    zero row, no gradient). Backward applies an eager host-side SGD to
    the slab (ordered io_callback), so cold rows train without device
    memory or device optimizer state.

    ``anchor`` must be a (cheap, e.g. scalar) value derived from the
    differentiated parameters: without it the autodiff graph has no path
    from the loss inputs through this call, and JAX prunes the backward
    (the cold rows would silently never train). Its cotangent is zero.
    """
    del anchor
    return _cold_fetch_impl(tier, rel_ids)


def _cold_fwd(tier, rel_ids, anchor):
    return _cold_fetch_impl(tier, rel_ids), (rel_ids, anchor)


def _cold_bwd(tier, res, g):
    from jax.experimental import io_callback
    rel_ids, anchor = res
    io_callback(tier.apply_grad, None, rel_ids, g, ordered=True)
    return _int_zeros(rel_ids), jnp.zeros_like(anchor)


cold_lookup.defvjp(_cold_fwd, _cold_bwd)


def exchange_cost_bytes(spec: ShardSpec, n_ids: int) -> Dict[str, float]:
    """Analytic per-step exchange cost for one lookup+grad of ``n_ids``
    ids (for benches / docs — the runtime counters use the traced
    totals). All-device totals, forward ids+rows and backward grads."""
    n_loc = max(n_ids // spec.shards, 1)
    fwd = spec.shards * 2 * spec.shards * n_loc * (4 + spec.dim * 4)
    bwd = spec.shards * 2 * spec.shards * n_loc * spec.dim * 4
    return {"forward_bytes": float(fwd), "grad_bytes": float(bwd),
            "dense_grad_bytes": float(spec.padded * spec.dim * 4
                                      * math.prod(spec.mesh.devices.shape))}
