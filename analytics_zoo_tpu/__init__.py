"""analytics_zoo_tpu — a TPU-native unified analytics + AI framework.

Brand-new JAX/XLA/pallas/pjit implementation of the Analytics Zoo capability
surface: sharded host data pipelines feeding an on-device data-parallel
synchronous-SGD loop, Keras-style and capture-style training APIs, a pooled
inference engine, serving, and a model zoo. See SURVEY.md for the layer map
this follows.
"""

__version__ = "0.1.0"

from .common.context import init_tpu_context, get_context, ZooTpuContext  # noqa: F401
from .common.config import global_config  # noqa: F401
