"""Inference engine (reference ``pipeline/inference/InferenceModel.scala:30``
+ ``net/TFNet.scala``): pooled, multi-format, quantizable model serving."""
from .inference_model import InferenceModel  # noqa: F401
from .quantize import dequantize_params, quantize_params  # noqa: F401
