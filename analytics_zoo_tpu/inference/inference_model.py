"""InferenceModel — pooled multi-backend serving model.

Parity with the reference (``pipeline/inference/InferenceModel.scala:30``):
``concurrentNum`` model copies in a ``LinkedBlockingQueue``, borrowed per
predict call; loaders for multiple formats; int8 quantized variants. TPU
re-design:

- a jitted forward is already thread-safe and the TPU serializes compute, so
  "copies" become a semaphore of ``concurrent_num`` dispatch slots — same
  backpressure contract, no duplicated weights in HBM.
- bucketed-shape AOT compile cache (≙ OpenVINO model-optimizer IR cache,
  ``OpenVinoInferenceSupportive.scala:64``): batch is padded to the next
  bucket so arbitrary request sizes reuse a handful of compiled programs
  (serving under XLA recompilation, SURVEY §7 hard part (f)).
- backends: native zoo models / checkpoints, raw JAX fns, flax modules,
  TF SavedModel (via ``jax2tf.call_tf``), TorchScript (host-side torch CPU,
  ≙ TorchNet), with bf16/int8 weight quantization.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..common import metrics as _metrics
from ..common import profiler as _profiler
from ..common.context import wire_compilation_cache
from .quantize import dequantize_params, quantize_params

_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

#: process-wide XLA compile telemetry (per-model per-bucket detail stays in
#: ``InferenceModel.compile_counts`` / ``compile_seconds``)
_M_COMPILE = _metrics.counter(
    "infer.compile_total", "XLA executables compiled by InferenceModel.")
_M_COMPILE_S = _metrics.counter(
    "infer.compile_seconds_total",
    "Seconds spent in InferenceModel XLA compiles.")


class _TextArtifact:
    """A raw-StableHLO AOT artifact (TF-imported models, export_compiled's
    ``stablehlo_text`` format): compiled straight through PJRT on first
    call — serving needs neither TF nor the exporting process.

    ``output_keys``: for dict-output signatures, the names matching the
    program's flat result order (tf.nest flattens dicts by sorted key), so
    the artifact path returns the SAME dict shape as the live call_tf
    path."""

    def __init__(self, text: str, n_outputs: int, output_keys=None):
        self._text = text
        self._n = n_outputs
        self._keys = list(output_keys) if output_keys else None
        self._exe = None
        self._lock = threading.Lock()

    def _compile(self):
        # Raw-StableHLO execution has no public jax surface yet; this leans
        # on jax internals and is feature-checked so a jax upgrade fails with
        # a clear message instead of an AttributeError mid-serving.
        try:
            from jax._src.interpreters import mlir as jmlir
            from jax._src.lib import _jax, xla_client as xc
            from jax._src.lib.mlir import ir as mlir_ir
        except ImportError as e:  # pragma: no cover - version drift guard
            raise RuntimeError(
                "this jax version moved the internal StableHLO-compile "
                "surface the AOT text-artifact loader relies on; pin jax to "
                "a tested release or re-export the model with jax.export"
            ) from e
        client = jax.devices()[0].client
        if not (hasattr(client, "compile_and_load")
                and hasattr(_jax, "DeviceList")
                and hasattr(xc, "CompileOptions")):  # pragma: no cover
            raise RuntimeError(
                "jax internals moved (compile_and_load/DeviceList/"
                "CompileOptions); this jax version is incompatible with the "
                "raw-StableHLO loader — pin jax or re-export with jax.export")
        with jmlir.make_ir_context():
            module = mlir_ir.Module.parse(self._text)
            return client.compile_and_load(
                module, _jax.DeviceList(tuple(jax.devices()[:1])),
                xc.CompileOptions(), [])

    def call(self, *args):
        with self._lock:
            if self._exe is None:
                self._exe = self._compile()
        bufs = [jax.device_put(np.asarray(a)) for a in args]
        res = self._exe.execute_sharded(bufs)
        outs = [a[0] for a in res.disassemble_into_single_device_arrays()]
        if self._keys is not None:
            return dict(zip(self._keys, outs))
        return outs[0] if self._n == 1 else tuple(outs)


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + 1023) // 1024) * 1024


class InferenceModel:
    def __init__(self, concurrent_num: int = 1):
        if concurrent_num < 1:
            raise ValueError("concurrent_num must be >= 1")
        self.concurrent_num = concurrent_num
        self._slots = threading.Semaphore(concurrent_num)
        self._forward: Optional[Callable] = None  # forward(params, x)
        self._params: Any = None
        self._jit: Optional[Callable] = None  # jit caches per shape itself
        self._host_predict: Optional[Callable] = None  # non-XLA backends
        # compile-warmth layer: AOT-compiled executables keyed by exact
        # input signature, with per-bucket compile counters so "did the
        # first request compile?" is an assertion, not a latency guess
        self._compiled: Dict[Tuple, Any] = {}
        self._compile_lock = threading.Lock()
        self.compile_counts: Dict[int, int] = {}
        self.compile_seconds: Dict[int, float] = {}
        wire_compilation_cache()  # compile.cache_dir, if configured

    def _set_forward(self, forward: Callable) -> None:
        """Install the forward fn and its jit wrapper eagerly — one wrapper
        per model, so concurrent cold predicts share XLA's compile cache
        instead of racing to build separate wrappers."""
        self._forward = forward
        self._jit = jax.jit(forward)
        self._reset_compile_cache()
        # loader-specific side channels die with the forward they belong
        # to — a reused InferenceModel must not export a PREVIOUS model
        self._savedmodel_ir = None
        self._keras_model = None
        self._keras_state = None

    def _reset_compile_cache(self) -> None:
        """A new forward (or new params tree) invalidates every compiled
        executable AND the warmth accounting."""
        with self._compile_lock:
            self._compiled = {}
            self.compile_counts = {}
            self.compile_seconds = {}

    def _ensure_compiled(self, xs: List[np.ndarray], is_multi: bool,
                         bucket: int):
        """Fetch (or AOT-compile) the executable for this exact padded
        input signature. ``jit.lower().compile()`` bypasses jit's implicit
        per-call cache, so the memo here is the ONLY cache — which is what
        makes the per-bucket counters truthful."""
        key = (is_multi, tuple((a.shape, a.dtype.str) for a in xs))
        exe = self._compiled.get(key)
        if exe is not None:
            return exe
        with self._compile_lock:
            exe = self._compiled.get(key)
            if exe is None:
                t0 = time.perf_counter()
                exe = self._jit.lower(
                    self._params, list(xs) if is_multi else xs[0]).compile()
                self._compiled[key] = exe
                elapsed = time.perf_counter() - t0
                self.compile_counts[bucket] = \
                    self.compile_counts.get(bucket, 0) + 1
                self.compile_seconds[bucket] = \
                    self.compile_seconds.get(bucket, 0.0) + elapsed
                _M_COMPILE.inc()
                _M_COMPILE_S.inc(elapsed)
                _profiler.record_phase("serving", "compile", elapsed,
                                       start=t0)
        return exe

    def prewarm(self, example,
                buckets: Optional[Sequence[int]] = None) -> "InferenceModel":
        """Compile the expected shape buckets BEFORE traffic arrives.

        ``example``: one input batch (any batch size) fixing dtypes and
        feature shapes — the same convention as :meth:`export_compiled`.
        ``buckets``: request batch sizes to warm (each resolves through the
        same bucket selection ``predict`` uses); defaults to the bucket the
        example's own batch size pads to. A production server calls this at
        load time so no client eats the multi-second first-hit XLA compile
        mid-traffic-ramp; with ``compile.cache_dir`` set the warmup itself
        is usually a disk read. Host-side backends (TorchScript) have
        nothing to warm. Compiles are recorded in ``compile_counts`` /
        ``compile_seconds`` per bucket."""
        if self._host_predict is not None:
            return self
        aot = getattr(self, "_aot", None)
        if self._forward is None and aot is None:
            raise RuntimeError("load a model first")
        is_multi = isinstance(example, (list, tuple))
        xs = [np.asarray(a) for a in (example if is_multi else [example])]
        n = xs[0].shape[0]
        sizes = [n] if buckets is None else [int(b) for b in buckets]
        resolved = set()
        for size in sizes:
            if aot is not None:
                b = next((bb for bb in sorted(aot) if max(size, 1) <= bb),
                         None)
                if b is None:  # larger than every exported bucket: predict
                    continue   # would chunk to the biggest, already covered
            else:
                b = _bucket(size)
            resolved.add(b)
        for b in sorted(resolved):
            shaped = [np.repeat(a[:1], b, axis=0) if n
                      else np.zeros((b,) + a.shape[1:], a.dtype) for a in xs]
            if aot is not None:
                art = aot[b]
                if isinstance(art, _TextArtifact):
                    t0 = time.perf_counter()
                    with art._lock:
                        if art._exe is None:
                            art._exe = art._compile()
                            elapsed = time.perf_counter() - t0
                            self.compile_counts[b] = \
                                self.compile_counts.get(b, 0) + 1
                            self.compile_seconds[b] = \
                                self.compile_seconds.get(b, 0.0) + elapsed
                            _M_COMPILE.inc()
                            _M_COMPILE_S.inc(elapsed)
                            _profiler.record_phase("serving", "compile",
                                                   elapsed, start=t0)
                # serialized jax.export artifacts load pre-compiled
            else:
                self._ensure_compiled(shaped, is_multi, b)
        return self

    @staticmethod
    def _device(tree):
        """Explicit placement: letting jit transfer host numpy implicitly is
        dramatically slower on remote-device backends (measured ~100x on a
        tunneled TPU) than one batched device_put."""
        put = jax.device_put(tree)
        jax.block_until_ready(put)
        return put

    # -- loaders (doLoad* family) ---------------------------------------------

    def load_zoo(self, path: str) -> "InferenceModel":
        """Load a saved ``ZooModel`` directory (≙ doLoadBigDL)."""
        from ..models.common import ZooModel
        zm = ZooModel.load_model(path)
        est = zm.model.get_estimator()
        model = zm.model

        def forward(params, x):
            y, _ = model.call(params, est.model_state, x, training=False)
            return y

        self._set_forward(forward)
        self._params = self._device(est.params)
        self._keras_model = model  # calibrated int8 needs the layer graph
        self._keras_state = est.model_state
        return self

    def load_keras(self, model, params=None, model_state=None
                   ) -> "InferenceModel":
        """Wrap an in-memory Keras-style model (compiled or raw)."""
        if params is None:
            est = model.get_estimator()
            params, model_state = est.params, est.model_state
        model_state = model_state or {}

        def forward(p, x):
            y, _ = model.call(p, model_state, x, training=False)
            return y

        self._set_forward(forward)
        self._params = self._device(params)
        self._keras_model = model  # calibration needs the layer graph
        self._keras_state = model_state
        return self

    def load_jax(self, forward_fn: Callable, params: Any) -> "InferenceModel":
        """Raw ``forward(params, x)`` + params pytree (≙ doLoadTF frozen)."""
        self._set_forward(forward_fn)
        self._params = self._device(params)
        return self

    def load_flax(self, module, variables: Any) -> "InferenceModel":
        def forward(vars_, x):
            return module.apply(vars_, x)
        self._set_forward(forward)
        self._params = self._device(variables)
        return self

    def load_savedmodel(self, path: str, signature: str = "serving_default"
                        ) -> "InferenceModel":
        """TF SavedModel import (≙ doLoadTF SavedModel,
        ``TFNetForInference.scala``). The signature is wrapped with
        ``jax2tf.call_tf`` and predict()'s jit EMBEDS the lowered TF
        computation into the XLA program — TF runs at trace time (once per
        shape bucket), not per request. For serving with no TF dependency
        at all, round-trip to a serialized artifact:
        ``load_savedmodel(p).export_compiled(dir, example)`` then serve via
        ``load_compiled(dir)`` (pure StableHLO; tested TF-free in
        ``tests/test_capture_inference.py``)."""
        import tensorflow as tf  # gated import
        from jax.experimental import jax2tf
        loaded = tf.saved_model.load(path)
        fn = loaded.signatures[signature]
        keys = list(fn.structured_input_signature[1].keys())

        def positional_fn(*args):  # signatures take kwargs; call_tf positional
            return fn(**dict(zip(keys, args)))

        def forward(params, x):
            del params
            xs = x if isinstance(x, (list, tuple)) else [x]
            out = jax2tf.call_tf(positional_fn)(*xs)
            if isinstance(out, dict) and len(out) == 1:
                return next(iter(out.values()))
            return out

        def stablehlo_ir(shaped):
            """Lower the signature at concrete shapes via TF's own XLA
            bridge — raw StableHLO text, no call_tf effect, serializable
            (export_compiled's TF-free artifact path)."""
            jfn = tf.function(positional_fn, jit_compile=True)
            specs = [tf.TensorSpec(np.asarray(a).shape,
                                   tf.as_dtype(np.asarray(a).dtype))
                     for a in shaped]
            return str(jfn.experimental_get_compiler_ir(*specs)(
                stage="stablehlo"))

        self._set_forward(forward)
        self._params = {}
        self._keep_alive = loaded
        self._savedmodel_ir = stablehlo_ir
        return self

    def load_onnx(self, path: str) -> "InferenceModel":
        """ONNX file → native model pool entry (≙ the OpenVINO-IR load role;
        imports through the dependency-free ONNX loader)."""
        from ..net import load_onnx as _load
        return self.load_keras(*_load(path))

    def load_caffe(self, prototxt_path: str,
                   caffemodel_path: Optional[str] = None,
                   input_shape: Optional[Sequence[int]] = None
                   ) -> "InferenceModel":
        """Caffe prototxt+caffemodel → native model pool entry
        (≙ doLoadCaffe). ``input_shape``: (C, H, W), for deploy prototxts
        that declare no input shape."""
        from ..net import load_caffe as _load
        return self.load_keras(*_load(prototxt_path, caffemodel_path,
                                      input_shape=input_shape))

    def load_torch(self, path: str) -> "InferenceModel":
        """TorchScript model on host CPU (≙ doLoadPyTorch / TorchNet JNI).
        Runs outside XLA; the pool semaphore is the real concurrency guard."""
        import torch  # gated import
        module = torch.jit.load(path)
        module.eval()

        def host_predict(x):
            import torch as _t
            with _t.no_grad():
                xs = x if isinstance(x, (list, tuple)) else [x]
                out = module(*[_t.from_numpy(np.asarray(a, np.float32))
                               for a in xs])
                return out.numpy()

        self._host_predict = host_predict
        return self

    # -- quantization (int8/VNNI path equivalent) -----------------------------

    def quantize(self, dtype: str = "bf16", calibration_data=None,
                 percentile: float = 99.9) -> "InferenceModel":
        """``bf16`` casts weights; ``int8`` without calibration is
        weight-only (dequantized on the fly). ``int8`` WITH
        ``calibration_data`` (an iterable of input batches, e.g. a
        FeatureSet iterator) runs activation observers over the batches and
        installs the static-quantization path: Dense/Conv kernels carry
        per-tensor activation scales and execute on the int8 grid
        (the reference's calibrated OpenVINO int8,
        ``OpenVinoInferenceSupportive.scala:64``)."""
        if self._params is None:
            raise RuntimeError("load a model first")
        base = self._forward
        if dtype == "int8" and calibration_data is not None:
            model = getattr(self, "_keras_model", None)
            if model is None:
                raise ValueError(
                    "calibrated int8 needs a keras-graph model "
                    "(load_keras/load_zoo); weight-only int8 works for "
                    "opaque forwards — call quantize('int8') without "
                    "calibration_data")
            from .quantize import observe_activation_scales
            host_params = jax.tree_util.tree_map(np.asarray, self._params)
            act_scales = observe_activation_scales(
                model, host_params, self._keras_state, calibration_data,
                percentile=percentile)
            qparams = quantize_params(self._params, "int8",
                                      act_scales=act_scales)
            self._act_scales = act_scales
            # layers consume their quantized kernels natively — the base
            # forward runs unchanged on the mixed tree; the param AVALs
            # changed, so every compiled executable is stale
            self._params = self._device(qparams)
            self._reset_compile_cache()
            return self
        qparams = quantize_params(self._params, dtype)

        if dtype == "int8":
            def forward(qp, x):
                return base(dequantize_params(qp), x)
        else:
            def forward(qp, x):
                import jax.numpy as jnp
                y = base(qp, x)
                return jax.tree_util.tree_map(
                    lambda t: t.astype(jnp.float32), y)
        self._set_forward(forward)
        self._params = self._device(qparams)
        return self

    # -- AOT artifact export/import (OpenVINO model-optimizer IR role) --------

    def export_compiled(self, path: str, example,
                        batch_sizes: Sequence[int] = (1, 8, 32, 128),
                        platforms: Sequence[str] = ("cpu", "tpu")
                        ) -> "InferenceModel":
        """Ahead-of-time compile the loaded forward at fixed batch buckets
        and serialize the artifacts to ``path`` (≙ OpenVINO model-optimizer
        IR emission, ``OpenVinoInferenceSupportive.scala:64-123``). Params
        are frozen into the artifact as constants — the exported file IS the
        model, no separate weights. ``example``: one input batch (any batch
        size) fixing dtypes/feature shapes. Artifacts lower for every
        platform in ``platforms`` so an export made on a CPU host serves on
        TPU."""
        import json

        import jax.export as jex

        from ..common import file_io

        if self._forward is None:
            raise RuntimeError("load a model first")
        file_io.makedirs(path, exist_ok=True)
        multi = isinstance(example, (list, tuple))
        xs = [np.asarray(a) for a in (example if multi else [example])]
        if getattr(self, "_savedmodel_ir", None) is not None:
            # TF-imported model: the artifact is the TF-side StableHLO
            # lowering itself (raw text per bucket) — serving it never
            # touches TF (jax.export can't serialize call_tf's effect)
            y = self._forward(self._params, xs if multi else xs[0])
            n_out = (len(jax.tree_util.tree_leaves(y))
                     if isinstance(y, (dict, list, tuple)) else 1)
            # dict outputs keep their names: XLA's flat result order is
            # tf.nest's flatten order (sorted keys)
            out_keys = sorted(y.keys()) if isinstance(y, dict) else None
            for b in sorted(batch_sizes):
                shaped = [np.repeat(a[:1], b, axis=0) for a in xs]
                text = self._savedmodel_ir(shaped)
                with file_io.fopen(
                        file_io.join(path, f"batch-{b}.stablehlo.txt"),
                        "w") as f:
                    f.write(text)
            with file_io.fopen(file_io.join(path, "aot_meta.json"),
                               "w") as f:
                f.write(json.dumps({"batch_sizes": sorted(batch_sizes),
                                    "multi": multi,
                                    "format": "stablehlo_text",
                                    "n_outputs": n_out,
                                    "output_keys": out_keys,
                                    "platforms": list(platforms)}))
            return self
        params = self._params
        fwd = self._forward
        # mirror predict()'s calling convention exactly: a list input stays
        # a list even with one element
        if multi:
            frozen = jax.jit(lambda *args: fwd(params, list(args)))
        else:
            frozen = jax.jit(lambda x: fwd(params, x))
        for b in sorted(batch_sizes):
            shaped = [np.repeat(a[:1], b, axis=0) for a in xs]
            exp = jex.export(frozen, platforms=tuple(platforms))(*shaped)
            with file_io.fopen(file_io.join(path, f"batch-{b}.stablehlo"),
                               "wb") as f:
                f.write(exp.serialize())
        with file_io.fopen(file_io.join(path, "aot_meta.json"), "w") as f:
            f.write(json.dumps({"batch_sizes": sorted(batch_sizes),
                                "multi": multi,
                                "platforms": list(platforms)}))
        return self

    def load_compiled(self, path: str) -> "InferenceModel":
        """Load an :meth:`export_compiled` artifact directory; ``predict``
        then runs the pre-compiled programs (pad to the bucket, trim) with
        zero JIT compiles at serve time."""
        import json

        import jax.export as jex

        from ..common import file_io

        with file_io.fopen(file_io.join(path, "aot_meta.json")) as f:
            meta = json.loads(f.read())
        arts = {}
        if meta.get("format") == "stablehlo_text":
            for b in meta["batch_sizes"]:
                with file_io.fopen(
                        file_io.join(path, f"batch-{b}.stablehlo.txt")) as f:
                    arts[b] = _TextArtifact(f.read(),
                                            int(meta.get("n_outputs", 1)),
                                            meta.get("output_keys"))
        else:
            for b in meta["batch_sizes"]:
                with file_io.fopen(
                        file_io.join(path, f"batch-{b}.stablehlo"),
                        "rb") as f:
                    arts[b] = jex.deserialize(f.read())
        self._aot = arts
        self._aot_multi = bool(meta["multi"])
        return self

    # -- predict (doPredict) --------------------------------------------------

    def predict(self, x, batch_size: Optional[int] = None, *,
                _fetch: bool = True):
        """Borrow a pool slot, pad to the shape bucket, run, trim.
        ``batch_size`` splits oversized inputs into chunks (each bucketed).
        With a :meth:`load_compiled` artifact, the pre-compiled program for
        the bucket runs instead of the JIT path — same pad/chunk/trim
        contract."""
        if self._host_predict is not None:
            with self._slots:
                res = self._host_predict(x)
                return res if _fetch else (lambda: res)
        aot = getattr(self, "_aot", None)
        if self._forward is None and aot is None:
            raise RuntimeError("no model loaded")
        is_multi = isinstance(x, (list, tuple))
        if aot is not None and is_multi != self._aot_multi:
            want = "a list of inputs" if self._aot_multi else "one array"
            raise ValueError(
                f"this AOT artifact was exported for {want}; got "
                f"{'a list' if is_multi else 'one array'}")
        xs = [np.asarray(a) for a in (x if is_multi else [x])]
        n = xs[0].shape[0]

        # effective chunk limit: caller's batch_size, and for AOT also the
        # largest exported bucket
        limit = batch_size
        if aot is not None:
            biggest = max(aot)
            limit = biggest if limit is None else min(limit, biggest)
        if limit is not None and n > limit:
            # chunks inherit _fetch: an async caller gets every chunk
            # DISPATCHED now and a thunk that fetches/concats later, so the
            # pipeline overlap survives bucketed chunking
            chunk_thunks = [self.predict(
                [a[i:i + limit] for a in xs] if is_multi
                else xs[0][i:i + limit], batch_size=limit, _fetch=False)
                for i in range(0, n, limit)]

            def gather():
                chunks = [t() for t in chunk_thunks]
                if isinstance(chunks[0], (list, tuple)):
                    return type(chunks[0])(
                        np.concatenate([c[i] for c in chunks])
                        for i in range(len(chunks[0])))
                if isinstance(chunks[0], dict):
                    return {k: np.concatenate([c[k] for c in chunks])
                            for k in chunks[0]}
                return np.concatenate(chunks)

            return gather() if _fetch else gather

        if aot is not None:
            # smallest exported bucket that fits; empty batches still run
            # the bucket-1 program and trim to zero rows
            bucket = next(b for b in sorted(aot) if max(n, 1) <= b)
        else:
            bucket = _bucket(n)
        if bucket != n:
            pad_row = (lambda a: a[-1:] if n else
                       np.zeros((1,) + a.shape[1:], a.dtype))
            xs = [np.concatenate(
                [a, np.repeat(pad_row(a), bucket - n, axis=0)]) for a in xs]
        if aot is None:
            # resolve (or compile) the executable BEFORE taking a pool
            # slot: a cold bucket must not hold a dispatch slot hostage
            # for the length of an XLA compile
            exe = self._ensure_compiled(xs, is_multi, bucket)
        args = jax.device_put(xs)  # explicit transfer (see _device)
        with self._slots:
            if aot is not None:
                y = aot[bucket].call(*args)
            else:
                y = exe(self._params, args if is_multi else args[0])
        def fetch():
            trim = lambda t: np.asarray(t)[:n]
            if isinstance(y, dict):
                return {k: trim(v) for k, v in y.items()}
            if isinstance(y, (list, tuple)):
                return type(y)(trim(t) for t in y)
            return trim(y)

        return fetch() if _fetch else fetch

    def predict_async(self, x, batch_size: Optional[int] = None):
        """Dispatch a predict WITHOUT blocking on the device→host fetch.
        Returns a zero-argument callable producing :meth:`predict`'s result;
        the device computes while the caller overlaps other work (the
        serving pipeline decodes batch N+1 during batch N's flight)."""
        return self.predict(x, batch_size, _fetch=False)

    def predict_many(self, batches: Sequence) -> List:
        """Concurrent batch predicts through the pool (thread fan-out)."""
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(max_workers=self.concurrent_num) as ex:
            return list(ex.map(self.predict, batches))
