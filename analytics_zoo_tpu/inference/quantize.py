"""Post-training quantization (the reference's OpenVINO int8/VNNI path,
``OpenVinoInferenceSupportive.scala:64`` + ``examples/vnni/*`` — SURVEY §2.3
maps it to "int8/bf16 quantized inference via XLA").

- bf16: cast weight pytrees; TPU MXUs consume bf16 natively, halving HBM
  traffic with ~no accuracy loss.
- int8 (weight-only): symmetric per-tensor weight quantization with fp32
  scales; weights are stored int8 (4x smaller) and dequantized on the fly —
  XLA fuses the ``int8 -> f32 mul`` into the consumer matmul's operand load.
- int8 (calibrated): activation observers run a calibration set through the
  model recording per-layer input ranges (max or percentile — the
  reference's OpenVINO calibration tool role); the resulting per-tensor
  activation scales ride inside the quantized-kernel leaves, and Dense /
  Convolution2D execute a static-quantization path (Dense: real int8×int8
  MXU matmul with int32 accumulation; conv: activations snapped to the int8
  grid so the deployed numerics are modeled faithfully).
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def quantize_params(params: Any, dtype: str = "bf16",
                    act_scales: Optional[Dict[str, float]] = None) -> Any:
    """Quantize a parameter pytree. int8 leaves become
    ``{"q": int8, "scale": f32}`` dicts; bf16 leaves are plain casts.

    With ``act_scales`` ({layer_name: activation_scale} from
    :func:`observe_activation_scales`), ONLY the kernels of calibrated
    layers are quantized and each carries its ``act_scale`` — uncalibrated
    layers (embeddings, norms, heads the observer never saw) stay fp32, so
    layers that cannot consume quantized leaves are never handed one.
    """
    if dtype in ("bf16", "bfloat16"):
        return jax.tree_util.tree_map(
            lambda t: t.astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(t).dtype, jnp.floating) else t,
            params)
    if dtype != "int8":
        raise ValueError(f"unsupported quantization dtype {dtype}")

    def qleaf(t):
        scale = jnp.maximum(jnp.max(jnp.abs(t)), 1e-8) / 127.0
        return {"q": jnp.clip(jnp.round(t / scale), -127, 127
                              ).astype(jnp.int8),
                "scale": scale.astype(jnp.float32)}

    if act_scales is None:
        def q(t):
            t = jnp.asarray(t)
            if not jnp.issubdtype(t.dtype, jnp.floating) or t.ndim < 2:
                return t  # biases/scalars stay fp32 (negligible size)
            return qleaf(t)

        return jax.tree_util.tree_map(q, params)

    def q_with_path(path, t):
        t = jnp.asarray(t)
        segs = [str(getattr(p, "key", p)) for p in path]
        # a layer's kernel lives at [...container..., layer_name, "kernel"]
        if (len(segs) >= 2 and segs[-1] == "kernel"
                and segs[-2] in act_scales
                and jnp.issubdtype(t.dtype, jnp.floating) and t.ndim >= 2):
            qd = qleaf(t)
            qd["act_scale"] = jnp.float32(act_scales[segs[-2]])
            return qd
        return t

    return jax.tree_util.tree_map_with_path(q_with_path, params)


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and "q" in x and "scale" in x


def dequantize_params(params: Any, dtype=jnp.float32) -> Any:
    """Inverse of int8 quantization (bf16 casts just upcast)."""

    def dq(t):
        if _is_qleaf(t):
            return (t["q"].astype(dtype) * t["scale"]).astype(dtype)
        t = jnp.asarray(t)
        if jnp.issubdtype(t.dtype, jnp.floating):
            return t.astype(dtype)
        return t

    return jax.tree_util.tree_map(dq, params, is_leaf=_is_qleaf)


# ---------------------------------------------------------------------------
# calibration — activation observers
# ---------------------------------------------------------------------------


def _quantizable_layers(model):
    """Dense/Convolution2D instances reachable from ``model`` (the layers
    with a static-int8 execution path)."""
    from ..keras.engine import Model, Sequential
    out = []

    def walk(m):
        if isinstance(m, Sequential):
            for l in m.layers:
                walk(l)
        elif isinstance(m, Model):
            seen = set()
            for node in m._nodes:
                if id(node.layer) not in seen:
                    seen.add(id(node.layer))
                    walk(node.layer)
        elif type(m).__name__ in ("Dense", "Convolution2D"):
            out.append(m)
    walk(model)
    return out


def observe_activation_scales(model, params, state, batches: Iterable,
                              percentile: float = 99.9
                              ) -> Dict[str, float]:
    """Run calibration batches through ``model`` eagerly, recording each
    Dense/Conv2D layer's input magnitude (``percentile`` of |x|, or the max
    at 100) — returns {layer_name: activation_scale} with
    ``scale = range / 127`` ready for :func:`quantize_params`.

    The observers are installed as temporary per-instance ``call`` wrappers
    and always removed; eager (unjitted) execution makes the concrete
    activation values visible to the recorder.
    """
    layers = _quantizable_layers(model)
    stats: Dict[str, float] = {}
    originals = []
    try:
        for layer in layers:
            orig = layer.call

            def wrapped(p, s, inputs, *, _orig=orig, _name=layer.name, **kw):
                arr = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
                a = np.abs(np.asarray(arr, np.float32))
                v = (float(a.max()) if percentile >= 100
                     else float(np.percentile(a, percentile)))
                stats[_name] = max(stats.get(_name, 0.0), v)
                return _orig(p, s, inputs, **kw)

            layer.call = wrapped
            originals.append((layer, orig))
        for batch in batches:
            x = batch[0] if isinstance(batch, tuple) else batch
            model.call(params, state, x, training=False)
    finally:
        for layer, orig in originals:
            layer.call = orig
    return {name: max(v, 1e-8) / 127.0 for name, v in stats.items()}


# ---------------------------------------------------------------------------
# static-int8 execution helpers (called by Dense / Convolution2D)
# ---------------------------------------------------------------------------


def qdense_apply(inputs, qkernel) -> jax.Array:
    """Dense matmul against a quantized kernel. With a calibrated
    ``act_scale`` the activations snap to the int8 grid and the matmul runs
    int8×int8 with int32 accumulation (the MXU's native int8 path — 2x bf16
    peak on v5e); without, weights dequantize on the fly."""
    s_w = qkernel["scale"]
    s_a = qkernel.get("act_scale")
    if s_a is None:
        return inputs @ (qkernel["q"].astype(inputs.dtype)
                         * s_w.astype(inputs.dtype))
    xq = jnp.clip(jnp.round(inputs.astype(jnp.float32) / s_a),
                  -127, 127).astype(jnp.int8)
    y = jax.lax.dot_general(
        xq, qkernel["q"], (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * (s_a * s_w)


def qconv_apply(inputs, qkernel, strides, padding, dilation, groups
                ) -> jax.Array:
    """Conv against a quantized kernel. With a calibrated ``act_scale`` the
    activations snap to the int8 grid and the conv runs int8×int8 with
    int32 accumulation (measured ~1.5x over the f32 conv on v5e — the VNNI
    analog); without calibration, weights dequantize on the fly."""
    s_w = qkernel["scale"]
    s_a = qkernel.get("act_scale")
    if s_a is None:
        w = (qkernel["q"].astype(inputs.dtype)
             * s_w.astype(inputs.dtype))
        return jax.lax.conv_general_dilated(
            inputs, w, window_strides=strides, padding=padding,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    xq = jnp.clip(jnp.round(inputs.astype(jnp.float32) / s_a),
                  -127, 127).astype(jnp.int8)
    y = jax.lax.conv_general_dilated(
        xq, qkernel["q"], window_strides=strides, padding=padding,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * (s_a * s_w)
