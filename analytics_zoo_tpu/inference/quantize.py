"""Post-training quantization (the reference's OpenVINO int8/VNNI path,
``OpenVinoInferenceSupportive.scala`` + ``examples/vnni/*`` — SURVEY §2.3
maps it to "int8/bf16 quantized inference via XLA").

- bf16: cast weight pytrees; TPU MXUs consume bf16 natively, halving HBM
  traffic with ~no accuracy loss.
- int8: symmetric per-tensor weight quantization with fp32 scales; weights
  are stored int8 (4x smaller) and dequantized on the fly — XLA fuses the
  ``int8 -> f32 mul`` into the consumer matmul's operand load."""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def quantize_params(params: Any, dtype: str = "bf16") -> Any:
    """Quantize a parameter pytree. int8 leaves become
    ``{"q": int8, "scale": f32}`` dicts; bf16 leaves are plain casts."""
    if dtype in ("bf16", "bfloat16"):
        return jax.tree_util.tree_map(
            lambda t: t.astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(t).dtype, jnp.floating) else t,
            params)
    if dtype != "int8":
        raise ValueError(f"unsupported quantization dtype {dtype}")

    def q(t):
        t = jnp.asarray(t)
        if not jnp.issubdtype(t.dtype, jnp.floating) or t.ndim < 2:
            return t  # biases/scalars stay fp32 (negligible size)
        scale = jnp.maximum(jnp.max(jnp.abs(t)), 1e-8) / 127.0
        return {"q": jnp.clip(jnp.round(t / scale), -127, 127
                              ).astype(jnp.int8),
                "scale": scale.astype(jnp.float32)}

    return jax.tree_util.tree_map(q, params)


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and set(x.keys()) == {"q", "scale"}


def dequantize_params(params: Any, dtype=jnp.float32) -> Any:
    """Inverse of int8 quantization (bf16 casts just upcast)."""

    def dq(t):
        if _is_qleaf(t):
            return (t["q"].astype(dtype) * t["scale"]).astype(dtype)
        t = jnp.asarray(t)
        if jnp.issubdtype(t.dtype, jnp.floating):
            return t.astype(dtype)
        return t

    return jax.tree_util.tree_map(dq, params, is_leaf=_is_qleaf)
