"""Model-zoo base classes.

``ZooModel`` (reference ``models/common/ZooModel.scala:38``): a built-in model
is a thin config object that builds a Keras-style graph, trains/predicts
through the Estimator, and persists as ``config + weights`` (the reference's
``saveModel``/``loadModel`` ``.model`` archive becomes a directory with a JSON
config and an orbax weight checkpoint).

``Recommender`` (reference ``models/recommendation/Recommender.scala``): adds
``predict_user_item_pair`` / ``recommend_for_user`` / ``recommend_for_item``
over (user, item) pair arrays — numpy in place of RDDs.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common import file_io

_MODEL_REGISTRY: Dict[str, type] = {}


def register_zoo_model(cls):
    _MODEL_REGISTRY[cls.__name__] = cls
    return cls


class ZooModel:
    """Base for built-in models. Subclasses implement ``build_model()``
    returning a keras ``Model``/``Sequential`` and ``get_config()``."""

    def __init__(self):
        self.model = None

    def _ensure_built(self):
        if self.model is None:
            self.model = self.build_model()
        return self.model

    def build_model(self):
        raise NotImplementedError

    def get_config(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- training facade ------------------------------------------------------

    def compile(self, optimizer, loss, metrics=None):
        self._ensure_built().compile(optimizer, loss, metrics)

    def fit(self, *args, **kwargs):
        return self._ensure_built().fit(*args, **kwargs)

    def evaluate(self, *args, **kwargs):
        return self._ensure_built().evaluate(*args, **kwargs)

    def predict(self, *args, **kwargs):
        return self._ensure_built().predict(*args, **kwargs)

    # -- persistence (ZooModel.saveModel / loadModel) -------------------------

    def save_model(self, path: str) -> None:
        """Accepts local paths or ``scheme://`` URIs (gs:// etc. — the
        reference saves models through its HDFS-aware filesystem layer,
        ``common/Utils.scala:97``)."""
        file_io.makedirs(path, exist_ok=True)
        config = {"class": type(self).__name__, "config": self.get_config()}
        with file_io.fopen(file_io.join(path, "zoo_model.json"), "w") as f:
            f.write(json.dumps(config, indent=2))
        self._ensure_built().save_model(file_io.join(path, "weights"))

    @staticmethod
    def _instantiate_and_load(cls_name: str, config: Dict[str, Any],
                              weights_uri: str) -> "ZooModel":
        """Registry lookup → build → compile-before-weights-load →
        load_weights (the one place this invariant lives; both load_model
        and load_pretrained route through it)."""
        cls = _MODEL_REGISTRY.get(cls_name)
        if cls is None:
            raise ValueError(f"unknown zoo model class {cls_name}; "
                             f"registered: {sorted(_MODEL_REGISTRY)}")
        inst = cls(**config)
        inst._ensure_built()
        # models must be compiled before weights load to own an estimator
        if not hasattr(inst.model, "loss_fn"):
            inst.default_compile()
        inst.model.load_weights(weights_uri)
        return inst

    @staticmethod
    def load_model(path: str) -> "ZooModel":
        with file_io.fopen(file_io.join(path, "zoo_model.json")) as f:
            spec = json.loads(f.read())
        return ZooModel._instantiate_and_load(
            spec["class"], spec["config"], file_io.join(path, "weights"))

    def default_compile(self):
        self.compile(optimizer="adam", loss="mse")

    # -- pretrained bundles ---------------------------------------------------
    #
    # The reference zoo ships loadable pretrained artifacts carrying the
    # model weights AND their label map + per-model preprocessing config
    # (ImageClassifier.scala:37 label maps; ObjectDetectionConfig.scala:1
    # per-variant preproc). A bundle is ONE directory (local or scheme://):
    #   zoo_bundle.json   format tag, class, config, labels, preproc spec
    #   weights/          the checkpoint (same layout as save_model)

    BUNDLE_FORMAT = "zoo-tpu-bundle/1"

    def preprocessing_spec(self) -> Optional[List[Dict[str, Any]]]:
        """Serializable inference preprocessing (see feature/image/spec.py);
        None when the model has no canonical input chain."""
        return None

    def save_pretrained(self, path: str) -> None:
        """Write a single pretrained artifact: weights + config + label map
        + preprocessing spec, over the scheme-aware IO (gs:// works)."""
        file_io.makedirs(path, exist_ok=True)
        bundle = {
            "format": self.BUNDLE_FORMAT,
            "class": type(self).__name__,
            "config": self.get_config(),
            "labels": getattr(self, "labels", None),
            "preprocessing": self.preprocessing_spec(),
        }
        with file_io.fopen(file_io.join(path, "zoo_bundle.json"), "w") as f:
            f.write(json.dumps(bundle, indent=2))
        self._ensure_built().save_model(file_io.join(path, "weights"))

    @staticmethod
    def load_pretrained(uri: str) -> "ZooModel":
        """Load a bundle written by :meth:`save_pretrained` from a local
        path or remote URI; the returned model predicts with labels and
        exposes the bundled preprocessing chain via
        :meth:`bundled_preprocessing`."""
        with file_io.fopen(file_io.join(uri, "zoo_bundle.json")) as f:
            bundle = json.loads(f.read())
        fmt = bundle.get("format")
        if fmt != ZooModel.BUNDLE_FORMAT:
            raise ValueError(f"{uri!r} is not a zoo-tpu pretrained bundle "
                             f"(format {fmt!r}); for bare checkpoints use "
                             f"ZooModel.load_model")
        inst = ZooModel._instantiate_and_load(
            bundle["class"], bundle["config"], file_io.join(uri, "weights"))
        if bundle.get("labels") is not None:
            inst.labels = bundle["labels"]
        inst._bundle_preprocessing = bundle.get("preprocessing")
        return inst

    def bundled_preprocessing(self):
        """The preprocessing chain this model was bundled with (falls back
        to the model's own canonical spec)."""
        from ..feature.image.spec import build_preprocessing
        spec = getattr(self, "_bundle_preprocessing", None)
        if spec is None:
            spec = self.preprocessing_spec()
        return build_preprocessing(spec)


class Ranker:
    """Mixin for ranking models (reference ``models/common/Ranker.scala:33``):
    ``evaluate_ndcg`` / ``evaluate_map`` over grouped candidate lists.

    The TPU-native contract replaces the reference's one-Sample-per-query
    TextSet with arrays: ``x`` grouped as [Q, L, ...] (one row per query's
    candidate list) and ``y`` as [Q, L] relevance labels — the whole
    evaluation is a single batched forward + vectorized metric instead of
    per-record Spark tasks.
    """

    def _group_scores(self, x: np.ndarray, batch_size: int = 128) -> np.ndarray:
        x = np.asarray(x)
        q, l = x.shape[0], x.shape[1]
        flat = x.reshape((q * l,) + x.shape[2:])
        scores = np.asarray(self.predict(flat, batch_size=batch_size))
        scores = scores.reshape(q, l, -1)
        # multi-class outputs rank by the positive-class probability
        # (last column); single-score models pass through unchanged
        return scores[..., -1]

    def evaluate_ndcg(self, x, y, k: int, threshold: float = 0.0,
                      batch_size: int = 128) -> float:
        """Mean NDCG@k over queries (``Ranker.evaluateNDCG``)."""
        import jax.numpy as jnp
        from ..keras.metrics import ndcg_score
        scores = self._group_scores(x, batch_size)
        vals = ndcg_score(jnp.asarray(np.asarray(y, np.float32)),
                          jnp.asarray(scores), k, threshold)
        return float(jnp.mean(vals))

    def evaluate_map(self, x, y, threshold: float = 0.0,
                     batch_size: int = 128) -> float:
        """Mean average precision over queries (``Ranker.evaluateMAP``)."""
        import jax.numpy as jnp
        from ..keras.metrics import map_score
        scores = self._group_scores(x, batch_size)
        vals = map_score(jnp.asarray(np.asarray(y, np.float32)),
                         jnp.asarray(scores), threshold)
        return float(jnp.mean(vals))

    def evaluate_hit_ratio(self, x, y, k: int = 10, threshold: float = 0.0,
                           batch_size: int = 128) -> float:
        """Mean HitRatio@k over queries (BigDL ``HitRatio`` role)."""
        import jax.numpy as jnp
        from ..keras.metrics import hit_ratio_score
        scores = self._group_scores(x, batch_size)
        vals = hit_ratio_score(jnp.asarray(np.asarray(y, np.float32)),
                               jnp.asarray(scores), k, threshold)
        return float(jnp.mean(vals))


class Recommender(ZooModel, Ranker):
    """Adds ranking helpers over (user, item) pair predictions."""

    def _pair_probs(self, user_ids: np.ndarray, item_ids: np.ndarray,
                    batch_size: int = 1024) -> np.ndarray:
        pairs = np.stack([user_ids, item_ids], axis=1).astype(np.float32)
        probs = self.predict(pairs, batch_size=batch_size)
        return np.asarray(probs)

    def predict_user_item_pair(self, user_ids, item_ids, batch_size: int = 1024
                               ) -> List[Tuple[int, int, int, float]]:
        """Returns (user, item, predicted_class, probability) per pair
        (reference ``predictUserItemPair``; classes are 1-based like BigDL)."""
        probs = self._pair_probs(np.asarray(user_ids), np.asarray(item_ids),
                                 batch_size)
        cls = np.argmax(probs, axis=-1)
        return [(int(u), int(i), int(c) + 1, float(p[c]))
                for u, i, c, p in zip(user_ids, item_ids, cls, probs)]

    def recommend_for_user(self, user_ids, item_ids, max_items: int = 5,
                           batch_size: int = 1024):
        """Top-N items per user from candidate (user, item) pairs. Ranks by
        (predicted class desc, probability desc) — the reference's
        ``sortBy(y => (-y.prediction, -y.probability))``
        (``Recommender.scala:55``)."""
        preds = self.predict_user_item_pair(user_ids, item_ids, batch_size)
        by_user: Dict[int, List] = {}
        for u, i, c, p in preds:
            by_user.setdefault(u, []).append((i, c, p))
        out = {}
        for u, items in by_user.items():
            items.sort(key=lambda t: (-t[1], -t[2]))
            out[u] = items[:max_items]
        return out

    def recommend_for_item(self, user_ids, item_ids, max_users: int = 5,
                           batch_size: int = 1024):
        preds = self.predict_user_item_pair(user_ids, item_ids, batch_size)
        by_item: Dict[int, List] = {}
        for u, i, c, p in preds:
            by_item.setdefault(i, []).append((u, c, p))
        out = {}
        for i, users in by_item.items():
            users.sort(key=lambda t: (-t[1], -t[2]))
            out[i] = users[:max_users]
        return out
