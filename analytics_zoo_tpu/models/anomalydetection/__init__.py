from .anomaly_detector import AnomalyDetector, detect_anomalies, unroll  # noqa: F401
