"""LSTM anomaly detector (reference
``models/anomalydetection/AnomalyDetector.scala:40`` + unroll/threshold utils
in ``anomalydetection/Utils.scala``): stacked LSTMs forecast the next value of
a time series; records with the largest forecast error are anomalies."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import ZooModel, register_zoo_model
from ...keras import Sequential
from ...keras.layers import Dense, Dropout, LSTM


def unroll(data: np.ndarray, unroll_length: int,
           predict_step: int = 1) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding windows: features [n, unroll_length, d], labels = the value
    ``predict_step`` after each window (first feature column)."""
    data = np.asarray(data, np.float32)
    if data.ndim == 1:
        data = data[:, None]
    n = len(data) - unroll_length - predict_step + 1
    if n <= 0:
        raise ValueError("series shorter than unroll_length + predict_step")
    idx = np.arange(unroll_length)[None, :] + np.arange(n)[:, None]
    x = data[idx]
    y = data[np.arange(n) + unroll_length + predict_step - 1, 0]
    return x, y.astype(np.float32)


def detect_anomalies(y_true: np.ndarray, y_pred: np.ndarray,
                     anomaly_size: int = 5
                     ) -> List[Tuple[int, float, float, bool]]:
    """Mark the ``anomaly_size`` records with the largest absolute forecast
    error (reference ``AnomalyDetector.detectAnomalies``). Returns
    (index, truth, predicted, is_anomaly) per record."""
    y_true = np.asarray(y_true).reshape(-1)
    y_pred = np.asarray(y_pred).reshape(-1)
    err = np.abs(y_true - y_pred)
    if anomaly_size <= 0:
        threshold = np.inf  # nothing flagged
    elif anomaly_size <= len(err):
        threshold = np.sort(err)[-anomaly_size]
    else:
        threshold = -1.0  # everything flagged
    return [(i, float(t), float(p), bool(e >= threshold))
            for i, (t, p, e) in enumerate(zip(y_true, y_pred, err))]


@register_zoo_model
class AnomalyDetector(ZooModel):
    """``feature_shape`` = (unroll_length, feature_dim)."""

    def __init__(self, feature_shape: Sequence[int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2)):
        super().__init__()
        if len(hidden_layers) != len(dropouts):
            raise ValueError("hidden_layers and dropouts must align")
        self.feature_shape = tuple(feature_shape)
        self.hidden_layers = list(hidden_layers)
        self.dropouts = list(dropouts)

    def get_config(self) -> Dict[str, Any]:
        return {"feature_shape": list(self.feature_shape),
                "hidden_layers": self.hidden_layers,
                "dropouts": self.dropouts}

    def build_model(self) -> Sequential:
        model = Sequential(name="anomaly_detector")
        for units, drop in zip(self.hidden_layers[:-1], self.dropouts[:-1]):
            model.add(LSTM(units, return_sequences=True))
            model.add(Dropout(drop))
        model.add(LSTM(self.hidden_layers[-1], return_sequences=False))
        model.add(Dropout(self.dropouts[-1]))
        model.add(Dense(1))
        return model

    def default_compile(self):
        self.compile(optimizer="adam", loss="mse", metrics=["mse"])
