from .common import Recommender, ZooModel, register_zoo_model  # noqa: F401
from .recommendation import (  # noqa: F401
    ColumnFeatureInfo, NeuralCF, SessionRecommender, WideAndDeep,
    cross_columns, features_from_dataframe)
from .anomalydetection import (  # noqa: F401
    AnomalyDetector, detect_anomalies, unroll)
from .textclassification import TextClassifier  # noqa: F401
from .textmatching import KNRM  # noqa: F401
from .seq2seq import Seq2seq  # noqa: F401
from .textmodels import (  # noqa: F401
    IntentEntity, NER, POSTagger, SequenceTagger)
from .image.imageclassification import ImageClassifier  # noqa: F401
from .image.objectdetection import (  # noqa: F401
    DETECTION_CONFIGS, ObjectDetector, detection_config)
