from .common import Recommender, ZooModel, register_zoo_model  # noqa: F401
from .recommendation import NeuralCF  # noqa: F401
