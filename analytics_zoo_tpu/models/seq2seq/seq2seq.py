"""Seq2seq (reference ``models/seq2seq/Seq2seq.scala:50``: RNN encoder →
bridge → RNN decoder → generator head, teacher-forced training, greedy
inference loop).

TPU design: encoder and decoder are stacked fused-gate LSTM/GRU scans
(``keras/layers/recurrent.py``); the bridge maps every encoder final state to
the decoder's initial state ("passthrough" identity or "dense" learned
projection — the reference Bridge.scala contract). Training input is
``[encoder_seq, decoder_seq]`` (teacher forcing); ``infer`` runs the greedy
decode loop on host with a jitted single-step."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common import ZooModel, register_zoo_model
from ...keras import Sequential
from ...keras.engine import Layer
from ...keras.layers import Dense, GRU, LSTM


class _Seq2seqCore(Layer):
    def __init__(self, rnn_type: str, num_layers: int, hidden_size: int,
                 bridge: str, generator_dim: Optional[int],
                 generator_activation: Optional[str], name=None):
        super().__init__(name)
        rnn_type = rnn_type.lower()
        if rnn_type not in ("lstm", "gru"):
            raise ValueError(f"unsupported rnn_type {rnn_type}")
        if bridge not in ("passthrough", "dense"):
            raise ValueError(f"unsupported bridge {bridge}")
        self.rnn_type = rnn_type
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.bridge = bridge
        self.generator_dim = generator_dim
        cls = LSTM if rnn_type == "lstm" else GRU
        self.n_states = 2 if rnn_type == "lstm" else 1
        self.enc_layers = [
            cls(hidden_size, return_sequences=True, return_state=True,
                name=f"{self.name}_enc_{i}") for i in range(num_layers)]
        self.dec_layers = [
            cls(hidden_size, return_sequences=True, return_state=True,
                name=f"{self.name}_dec_{i}") for i in range(num_layers)]
        self.generator = (Dense(generator_dim,
                                activation=generator_activation,
                                name=f"{self.name}_generator")
                          if generator_dim else None)

    def build(self, rng, input_shape):
        enc_shape, dec_shape = input_shape[0], input_shape[1]
        params = {}
        shape = enc_shape
        for i, layer in enumerate(self.enc_layers):
            rng, sub = jax.random.split(rng)
            params[f"enc_{i}"], _ = layer.build(sub, shape)
            shape = (shape[0], shape[1], self.hidden_size)
        shape = dec_shape
        for i, layer in enumerate(self.dec_layers):
            rng, sub = jax.random.split(rng)
            params[f"dec_{i}"], _ = layer.build(sub, shape)
            shape = (shape[0], shape[1], self.hidden_size)
        if self.bridge == "dense":
            for i in range(self.num_layers):
                for s in range(self.n_states):
                    rng, sub = jax.random.split(rng)
                    d = Dense(self.hidden_size, name=f"bridge_{i}_{s}")
                    params[f"bridge_{i}_{s}"], _ = d.build(
                        sub, (None, self.hidden_size))
        if self.generator is not None:
            rng, sub = jax.random.split(rng)
            params["generator"], _ = self.generator.build(
                sub, (None, None, self.hidden_size))
        return params, {}

    def compute_output_shape(self, input_shape):
        dec_shape = input_shape[1]
        out_dim = self.generator_dim or self.hidden_size
        return (dec_shape[0], dec_shape[1], out_dim)

    def _bridge_state(self, params, i, states):
        if self.bridge == "passthrough":
            return states
        out = []
        for s, st in enumerate(states):
            p = params[f"bridge_{i}_{s}"]
            out.append(st @ p["kernel"] + p["bias"])
        return out

    def encode(self, params, x):
        """Run the encoder stack; returns per-layer final states."""
        states = []
        for i, layer in enumerate(self.enc_layers):
            outs, _ = layer.call(params[f"enc_{i}"], {}, x)
            x, layer_states = outs[0], outs[1:]
            states.append(self._bridge_state(params, i, layer_states))
        return states

    def decode(self, params, y, init_states):
        """Run the decoder stack from ``init_states``; returns
        (sequence output, per-layer final states)."""
        new_states = []
        for i, layer in enumerate(self.dec_layers):
            outs, _ = layer.call(
                params[f"dec_{i}"], {}, [y] + list(init_states[i]))
            y, layer_states = outs[0], outs[1:]
            new_states.append(list(layer_states))
        if self.generator is not None:
            p = self.generator
            y, _ = p.call(params["generator"], {}, y)
        return y, new_states

    def call(self, params, state, inputs, *, training=False, rng=None):
        enc_in, dec_in = inputs[0], inputs[1]
        enc_states = self.encode(params, enc_in)
        y, _ = self.decode(params, dec_in, enc_states)
        return y, state


@register_zoo_model
class Seq2seq(ZooModel):
    """Inputs: [encoder features [b, in_seq, in_dim],
    decoder features [b, out_seq, out_dim]] → [b, out_seq, generator_dim]."""

    def __init__(self, rnn_type: str = "lstm", num_layers: int = 1,
                 hidden_size: int = 64, bridge: str = "passthrough",
                 generator_dim: Optional[int] = None,
                 generator_activation: Optional[str] = None):
        super().__init__()
        self.rnn_type = rnn_type
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.bridge = bridge
        self.generator_dim = generator_dim
        self.generator_activation = generator_activation

    def get_config(self) -> Dict[str, Any]:
        return {"rnn_type": self.rnn_type, "num_layers": self.num_layers,
                "hidden_size": self.hidden_size, "bridge": self.bridge,
                "generator_dim": self.generator_dim,
                "generator_activation": self.generator_activation}

    def build_model(self) -> Sequential:
        core = _Seq2seqCore(self.rnn_type, self.num_layers, self.hidden_size,
                            self.bridge, self.generator_dim,
                            self.generator_activation, name="seq2seq_core")
        self.core = core
        return Sequential([core], name="seq2seq")

    def default_compile(self):
        self.compile(optimizer="adam", loss="mse")

    def infer(self, enc_input: np.ndarray, start_sign: np.ndarray,
              max_seq_len: int = 30,
              stop_sign: Optional[np.ndarray] = None) -> np.ndarray:
        """Greedy autoregressive decode (reference ``Seq2seq.infer``): feed
        ``start_sign`` [out_dim], append each generated step. The per-step
        encoder+decoder is jitted once; the loop runs on host."""
        self._ensure_built()
        est = self.model.get_estimator()
        if est.params is None:
            raise RuntimeError("model has no parameters yet; fit or "
                               "load_weights first")
        params = est.params["seq2seq_core"]
        core = self.core

        @jax.jit
        def enc_fn(params, x):
            return core.encode(params, x)

        @jax.jit
        def step_fn(params, y_t, states):
            out, new_states = core.decode(params, y_t, states)
            return out[:, -1], new_states

        enc_input = np.asarray(enc_input, np.float32)
        b = enc_input.shape[0]
        states = enc_fn(params, jnp.asarray(enc_input))
        y_t = jnp.broadcast_to(
            jnp.asarray(start_sign, jnp.float32)[None, None, :],
            (b, 1, len(start_sign)))
        outs = []
        done = np.zeros(b, bool)  # per-sequence stop tracking
        stop = (np.asarray(stop_sign, np.float32)
                if stop_sign is not None else None)
        for _ in range(max_seq_len):
            y_next, states = step_fn(params, y_t, states)
            step_out = np.array(y_next)  # copy: device views are read-only
            if stop is not None:
                # finished sequences keep emitting the stop sign
                step_out[done] = stop
                done |= np.all(np.abs(step_out - stop[None, :]) < 1e-4, axis=1)
            outs.append(step_out)
            if stop is not None and done.all():
                break
            y_t = jnp.asarray(step_out)[:, None, :]
        return np.stack(outs, axis=1)
