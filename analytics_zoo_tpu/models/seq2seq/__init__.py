from .seq2seq import Seq2seq  # noqa: F401
