from .text_classifier import TextClassifier  # noqa: F401
