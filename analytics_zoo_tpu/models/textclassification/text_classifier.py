"""Text classifier (reference
``models/textclassification/TextClassifier.scala:34``): embedding → CNN/LSTM/
GRU encoder → Dense(128) relu → softmax. Input is either token ids [seq_len]
(``vocab_size`` given, trainable embedding) or pre-embedded vectors
[seq_len, token_length]."""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..common import ZooModel, register_zoo_model
from ...keras import Sequential
from ...keras.layers import (
    Activation, Convolution1D, Dense, Dropout, Embedding, GlobalMaxPooling1D,
    GRU, LSTM)


@register_zoo_model
class TextClassifier(ZooModel):
    def __init__(self, class_num: int, token_length: int,
                 sequence_length: int = 500, encoder: str = "cnn",
                 encoder_output_dim: int = 256,
                 vocab_size: Optional[int] = None,
                 embedding_weights: Optional[np.ndarray] = None,
                 train_embedding: bool = True):
        super().__init__()
        if encoder.lower() not in ("cnn", "lstm", "gru"):
            raise ValueError(f"unsupported encoder {encoder}")
        self.class_num = class_num
        self.token_length = token_length
        self.sequence_length = sequence_length
        self.encoder = encoder.lower()
        self.encoder_output_dim = encoder_output_dim
        self.vocab_size = vocab_size
        self.embedding_weights = embedding_weights
        self.train_embedding = train_embedding

    def get_config(self) -> Dict[str, Any]:
        return {"class_num": self.class_num,
                "token_length": self.token_length,
                "sequence_length": self.sequence_length,
                "encoder": self.encoder,
                "encoder_output_dim": self.encoder_output_dim,
                "vocab_size": self.vocab_size,
                "train_embedding": self.train_embedding}

    def build_model(self) -> Sequential:
        model = Sequential(name="text_classifier")
        if self.vocab_size:
            model.add(Embedding(self.vocab_size, self.token_length,
                                weights=self.embedding_weights,
                                trainable=self.train_embedding,
                                name="embedding"))
        if self.encoder == "cnn":
            model.add(Convolution1D(self.encoder_output_dim, 5,
                                    activation="relu"))
            model.add(GlobalMaxPooling1D())
        elif self.encoder == "lstm":
            model.add(LSTM(self.encoder_output_dim))
        else:
            model.add(GRU(self.encoder_output_dim))
        model.add(Dense(128))
        model.add(Dropout(0.2))
        model.add(Activation("relu"))
        model.add(Dense(self.class_num, activation="softmax"))
        return model

    def default_compile(self):
        self.compile(optimizer="adagrad",
                     loss="sparse_categorical_crossentropy",
                     metrics=["accuracy"])
