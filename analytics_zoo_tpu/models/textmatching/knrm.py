"""KNRM kernel-pooling text matching (reference
``models/textmatching/KNRM.scala:60``): query+doc token ids (concatenated,
like the reference — embedding weights are shared by construction), embedding
→ translation (cosine-free batched dot) matrix → RBF kernel pooling →
Dense(1). ``target_mode`` "ranking" (linear score) or "classification"
(sigmoid probability).

The kernel pooling is one vectorized einsum over all kernels instead of the
reference's per-kernel graph ops — XLA fuses the [b, q, d, K] exp/sum chain.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np
import jax.numpy as jnp

from ..common import Ranker, ZooModel, register_zoo_model
from ...keras import Input, Model
from ...keras.engine import Layer
from ...keras.layers import Dense, Embedding


class _KernelPooling(Layer):
    """[b, q_len, d_len] similarity → [b, kernel_num] log-pooled features."""

    def __init__(self, kernel_num: int, sigma: float, exact_sigma: float,
                 name=None):
        super().__init__(name)
        self.kernel_num = kernel_num
        mus, sigmas = [], []
        for i in range(kernel_num):
            mu = 1.0 / (kernel_num - 1) + (2.0 * i) / (kernel_num - 1) - 1.0
            if mu > 1.0:  # exact-match kernel
                mus.append(1.0)
                sigmas.append(exact_sigma)
            else:
                mus.append(mu)
                sigmas.append(sigma)
        self.mus = np.asarray(mus, np.float32)
        self.sigmas = np.asarray(sigmas, np.float32)

    def call(self, params, state, inputs, *, training=False, rng=None):
        mm = inputs[..., None]  # [b, q, d, 1]
        mu = jnp.asarray(self.mus)[None, None, None, :]
        sg = jnp.asarray(self.sigmas)[None, None, None, :]
        kexp = jnp.exp(-0.5 * ((mm - mu) / sg) ** 2)   # [b, q, d, K]
        doc_sum = kexp.sum(axis=2)                      # [b, q, K]
        phi = jnp.log1p(doc_sum).sum(axis=1)            # [b, K]
        return phi, state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.kernel_num)


class _TranslationMatrix(Layer):
    """Split concat embedding into q/d and batch-dot: [b, q_len, d_len]."""

    def __init__(self, text1_length: int, name=None):
        super().__init__(name)
        self.text1_length = text1_length

    def call(self, params, state, inputs, *, training=False, rng=None):
        q = inputs[:, :self.text1_length]
        d = inputs[:, self.text1_length:]
        return jnp.einsum("bqe,bde->bqd", q, d,
                          preferred_element_type=jnp.float32), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.text1_length,
                input_shape[1] - self.text1_length)


@register_zoo_model
class KNRM(ZooModel, Ranker):
    def __init__(self, text1_length: int, text2_length: int, vocab_size: int,
                 embed_size: int = 300,
                 embed_weights: Optional[np.ndarray] = None,
                 train_embed: bool = True, kernel_num: int = 21,
                 sigma: float = 0.1, exact_sigma: float = 0.001,
                 target_mode: str = "ranking"):
        super().__init__()
        if kernel_num < 2:
            raise ValueError("kernel_num must be >= 2")
        if target_mode not in ("ranking", "classification"):
            raise ValueError(f"unknown target_mode {target_mode}")
        self.text1_length = text1_length
        self.text2_length = text2_length
        self.vocab_size = vocab_size
        self.embed_size = embed_size
        self.embed_weights = embed_weights
        self.train_embed = train_embed
        self.kernel_num = kernel_num
        self.sigma = sigma
        self.exact_sigma = exact_sigma
        self.target_mode = target_mode

    def get_config(self) -> Dict[str, Any]:
        return {"text1_length": self.text1_length,
                "text2_length": self.text2_length,
                "vocab_size": self.vocab_size, "embed_size": self.embed_size,
                "train_embed": self.train_embed,
                "kernel_num": self.kernel_num, "sigma": self.sigma,
                "exact_sigma": self.exact_sigma,
                "target_mode": self.target_mode}

    def build_model(self) -> Model:
        inp = Input((self.text1_length + self.text2_length,), name="qd_ids")
        e = Embedding(self.vocab_size, self.embed_size,
                      weights=self.embed_weights, trainable=self.train_embed,
                      name="shared_embedding")(inp)
        mm = _TranslationMatrix(self.text1_length, name="translation")(e)
        phi = _KernelPooling(self.kernel_num, self.sigma, self.exact_sigma,
                             name="kernel_pooling")(mm)
        if self.target_mode == "ranking":
            out = Dense(1, init="uniform", name="score")(phi)
        else:
            out = Dense(1, init="uniform", activation="sigmoid",
                        name="score")(phi)
        return Model(inp, out, name="knrm")

    def default_compile(self):
        loss = "rank_hinge" if self.target_mode == "ranking" \
            else "binary_crossentropy"
        self.compile(optimizer="adam", loss=loss)
