from .knrm import KNRM  # noqa: F401
