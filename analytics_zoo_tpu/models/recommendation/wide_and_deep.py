"""Wide & Deep recommender (reference
``models/recommendation/WideAndDeep.scala:101`` + column spec
``recommendation/Utils.scala``).

TPU re-design of the sparse "wide" path: the reference feeds a giant sparse
one-hot vector into ``SparseDense``; here the wide features stay as *bucket
indices* and the wide linear layer is an embedding-sum over a
``[total_wide_dim, num_classes]`` table — mathematically identical
(one_hot(x) @ W == W[x].sum), but it becomes an on-device gather + scatter-add
gradient, the allreduce-stress case SURVEY.md §7 hard part (b) calls out.
Indicator columns are one-hot'ed on device (cheap, fuses into the first
matmul); embedding columns get per-column tables; continuous pass through.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from ..common import Recommender, register_zoo_model
from ...keras import Input, Model
from ...keras.engine import Layer
from ...keras.layers import Dense, Embedding, Flatten, Lambda, merge
from ...parallel import embedding as _embed


@dataclass
class ColumnFeatureInfo:
    """Column spec (reference ``ColumnFeatureInfo``, recommendation/Utils.scala).

    All dims are per-column cardinalities; wide-cross columns are pre-hashed
    bucket ids produced by :func:`cross_columns`.
    """
    wide_base_cols: Sequence[str] = field(default_factory=list)
    wide_base_dims: Sequence[int] = field(default_factory=list)
    wide_cross_cols: Sequence[str] = field(default_factory=list)
    wide_cross_dims: Sequence[int] = field(default_factory=list)
    indicator_cols: Sequence[str] = field(default_factory=list)
    indicator_dims: Sequence[int] = field(default_factory=list)
    embed_cols: Sequence[str] = field(default_factory=list)
    embed_in_dims: Sequence[int] = field(default_factory=list)
    embed_out_dims: Sequence[int] = field(default_factory=list)
    continuous_cols: Sequence[str] = field(default_factory=list)
    label: str = "label"

    @property
    def wide_dims(self) -> List[int]:
        return list(self.wide_base_dims) + list(self.wide_cross_dims)

    @property
    def wide_cols(self) -> List[str]:
        return list(self.wide_base_cols) + list(self.wide_cross_cols)


def _crc32_codes(col) -> np.ndarray:
    """Per-value ``crc32(str(v))`` vectorized through the column's uniques:
    categorical columns repeat values heavily, so hashing each UNIQUE once
    and gathering by inverse index does ~cardinality hashes instead of ~rows
    (50-500x on Criteo-scale columns) while producing bit-identical buckets
    to the per-value loop."""
    import zlib
    try:
        import pandas as pd
        # hash-based factorize: O(rows), no sort — np.unique on a string
        # column sorts and ends up slower than the loop it replaces.
        # use_na_sentinel=False keeps NaN IN the uniques (code >= 0) so it
        # hashes as crc32("nan") like every other value; the default -1
        # sentinel would silently gather the LAST unique's hash instead
        inv, uniq = pd.factorize(np.asarray(col), use_na_sentinel=False)
        uniq = np.asarray(uniq)
    except ImportError:
        uniq, inv = np.unique(np.asarray(col), return_inverse=True)
    table = np.fromiter((zlib.crc32(str(v).encode()) for v in uniq),
                        dtype=np.int64, count=len(uniq))
    return table[inv]


def cross_columns(df, cols: Sequence[str], bucket_size: int) -> np.ndarray:
    """Hash-cross of categorical columns into ``bucket_size`` buckets
    (reference ``Utils.buckBucket``). Uses crc32, stable across processes —
    train-time and serve-time features must land in the same bucket."""
    acc = np.zeros(len(df), dtype=np.int64)
    for c in cols:
        acc = acc * 1000003 + _crc32_codes(df[c])
    return np.abs(acc) % bucket_size


def features_from_dataframe(df, column_info: ColumnFeatureInfo
                            ) -> Tuple[List[np.ndarray], Optional[np.ndarray]]:
    """pandas DataFrame → the 4 model input arrays + labels (the reference's
    ``row2Sample``, Utils.scala:108). Categorical columns must already be
    integer-indexed (0-based per column)."""
    n = len(df)
    offsets = np.cumsum([0] + list(column_info.wide_dims))[:-1]
    # categorical ids travel as int32 — float32 transport would corrupt ids
    # above 2^24 (hashed crosses / large vocabularies)
    wide = np.stack([
        np.clip(df[c].to_numpy().astype(np.int64), 0, d - 1) + off
        for c, d, off in zip(column_info.wide_cols, column_info.wide_dims,
                             offsets)], axis=1).astype(np.int32) \
        if column_info.wide_cols else np.zeros((n, 0), np.int32)
    ind = np.stack([
        np.clip(df[c].to_numpy().astype(np.int64), 0, d - 1)
        for c, d in zip(column_info.indicator_cols, column_info.indicator_dims)],
        axis=1).astype(np.int32) \
        if column_info.indicator_cols else np.zeros((n, 0), np.int32)
    emb = np.stack([
        np.clip(df[c].to_numpy().astype(np.int64), 0, d - 1)
        for c, d in zip(column_info.embed_cols, column_info.embed_in_dims)],
        axis=1).astype(np.int32) \
        if column_info.embed_cols else np.zeros((n, 0), np.int32)
    cont = np.stack([df[c].to_numpy().astype(np.float32)
                     for c in column_info.continuous_cols], axis=1) \
        if column_info.continuous_cols else np.zeros((n, 0), np.float32)
    labels = (df[column_info.label].to_numpy().astype(np.float32)
              if column_info.label in df.columns else None)
    return [wide, ind, emb, cont], labels


class _WideLinear(Layer):
    """Embedding-sum sparse linear layer: the TPU ``SparseDense``.

    With ``shard`` set, the ``[total_wide_dim, num_classes]`` table vocab-
    shards over the mesh through ``parallel/embedding.py`` — the hashed-
    cross vocabulary (easily 100M buckets) stops being replicated per
    device and its gradient stops being a dense-table allreduce."""

    def __init__(self, total_dim: int, num_classes: int, name=None,
                 shard=None, fused=None):
        super().__init__(name)
        self.total_dim = total_dim
        self.num_classes = num_classes
        self.shard = shard
        #: per-layer override of ``kernels.fused_embedding`` (None follows
        #: the config; False pins the unfused take+sum reference path)
        self.fused = fused
        self._shard_spec = None

    def _make_spec(self):
        if not self.shard:
            return None
        axis = self.shard if isinstance(self.shard, str) else None
        return _embed.make_shard_spec(self.total_dim, self.num_classes,
                                      axis=axis)

    def sharded_tables(self):
        spec = self._shard_spec or self._make_spec()
        return {"table": spec} if spec is not None else {}

    def build(self, rng, input_shape):
        import jax
        table = jax.random.uniform(
            rng, (self.total_dim, self.num_classes), minval=-0.05, maxval=0.05)
        self._shard_spec = spec = self._make_spec()
        if spec is not None:
            pad = spec.padded - self.total_dim
            if pad:
                table = jnp.concatenate(
                    [table, jnp.zeros((pad, self.num_classes), table.dtype)])
            _embed.note_table_bytes(self.name, spec.table_bytes)
        return {"table": table, "bias": jnp.zeros((self.num_classes,))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        idx = inputs.astype(jnp.int32)  # [b, n_wide] offset bucket ids
        idx = _embed.validate_ids(idx, self.total_dim)
        spec = self._shard_spec
        flat = idx.reshape(-1)
        if spec is not None and _embed.can_run(spec, flat.shape[0]):
            rows, blob = _embed.sharded_lookup(params["table"], flat, spec)
            out = rows.reshape(idx.shape + (self.num_classes,)).sum(1) \
                + params["bias"]
            new_state = dict(state)
            new_state[_embed.ROWS_PREFIX + "table"] = blob
            return out, new_state
        ek = None if self.fused is False else _embed.fused_kernels()
        if ek is not None:
            # fused gather+sum over the pre-validated bucket ids (pallas
            # on TPU; the identical take+sum chain elsewhere)
            out = ek.gather_pool(params["table"], idx, "sum",
                                 mask_negative=False) + params["bias"]
            return out, state
        out = jnp.take(params["table"], idx, axis=0).sum(1) + params["bias"]
        return out, state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.num_classes)


class _OneHotConcat(Layer):
    """Indicator indices → concatenated one-hot block (device-side)."""

    def __init__(self, dims: Sequence[int], name=None):
        super().__init__(name)
        self.dims = list(dims)

    def call(self, params, state, inputs, *, training=False, rng=None):
        import jax
        idx = inputs.astype(jnp.int32)
        parts = [jax.nn.one_hot(idx[:, i], d) for i, d in enumerate(self.dims)]
        return jnp.concatenate(parts, axis=-1), state

    def compute_output_shape(self, input_shape):
        return (input_shape[0], sum(self.dims))


@register_zoo_model
class WideAndDeep(Recommender):
    """Inputs (all [batch, n] float arrays, see ``features_from_dataframe``):
    [wide offset-indices, indicator indices, embed indices, continuous]."""

    def __init__(self, model_type: str = "wide_n_deep", num_classes: int = 2,
                 column_info: Optional[ColumnFeatureInfo] = None,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 shard_embeddings=None, fused_embeddings=None,
                 **column_kwargs):
        super().__init__()
        if model_type not in ("wide", "deep", "wide_n_deep"):
            raise ValueError(f"unknown model_type {model_type}")
        if column_info is None:
            column_info = ColumnFeatureInfo(**column_kwargs)
        elif isinstance(column_info, dict):
            column_info = ColumnFeatureInfo(**column_info)
        self.model_type = model_type
        self.num_classes = num_classes
        self.column_info = column_info
        self.hidden_layers = list(hidden_layers)
        #: None/False = replicated tables; True/axis-name = vocab-shard the
        #: wide table and per-column embed tables (parallel/embedding.py)
        self.shard_embeddings = shard_embeddings
        #: per-model override of the ``kernels.fused_embedding`` knob
        #: (ops/embedding_kernels.py): None follows the config, False pins
        #: the wide table and embed columns to the unfused reference path.
        self.fused_embeddings = fused_embeddings

    def get_config(self) -> Dict[str, Any]:
        ci = self.column_info
        return {
            "model_type": self.model_type, "num_classes": self.num_classes,
            "hidden_layers": self.hidden_layers,
            "shard_embeddings": self.shard_embeddings,
            "fused_embeddings": self.fused_embeddings,
            "column_info": {
                "wide_base_cols": list(ci.wide_base_cols),
                "wide_base_dims": list(ci.wide_base_dims),
                "wide_cross_cols": list(ci.wide_cross_cols),
                "wide_cross_dims": list(ci.wide_cross_dims),
                "indicator_cols": list(ci.indicator_cols),
                "indicator_dims": list(ci.indicator_dims),
                "embed_cols": list(ci.embed_cols),
                "embed_in_dims": list(ci.embed_in_dims),
                "embed_out_dims": list(ci.embed_out_dims),
                "continuous_cols": list(ci.continuous_cols),
                "label": ci.label,
            },
        }

    def build_model(self) -> Model:
        ci = self.column_info
        in_wide = Input((len(ci.wide_cols),), name="wide_input")
        in_ind = Input((len(ci.indicator_cols),), name="indicator_input")
        in_emb = Input((len(ci.embed_cols),), name="embed_input")
        in_cont = Input((len(ci.continuous_cols),), name="continuous_input")
        inputs = [in_wide, in_ind, in_emb, in_cont]

        wide_out = None
        if ci.wide_cols:
            wide_out = _WideLinear(sum(ci.wide_dims), self.num_classes,
                                   name="wide_linear",
                                   shard=self.shard_embeddings,
                                   fused=self.fused_embeddings)(in_wide)

        deep_out = None
        deep_parts = []
        if ci.indicator_cols:
            deep_parts.append(
                _OneHotConcat(ci.indicator_dims, name="indicator_onehot")(in_ind))
        for i, (c, din, dout) in enumerate(zip(
                ci.embed_cols, ci.embed_in_dims, ci.embed_out_dims)):
            col = Lambda(lambda x, i=i: x[:, i:i + 1], name=f"embed_col_{i}")(in_emb)
            e = Embedding(din, dout, init="normal", name=f"embed_table_{c}",
                          shard=self.shard_embeddings,
                          fused=self.fused_embeddings)(col)
            deep_parts.append(Flatten(name=f"embed_flat_{c}")(e))
        if ci.continuous_cols:
            deep_parts.append(in_cont)
        if deep_parts:
            h = (merge(deep_parts, mode="concat") if len(deep_parts) > 1
                 else deep_parts[0])
            for i, units in enumerate(self.hidden_layers):
                h = Dense(units, activation="relu", name=f"deep_dense_{i}")(h)
            deep_out = Dense(self.num_classes, name="deep_linear")(h)

        from ...keras.layers import Activation
        if self.model_type == "wide":
            if wide_out is None:
                raise ValueError("model_type 'wide' needs wide columns")
            out = Activation("softmax", name="prediction")(wide_out)
        elif self.model_type == "deep":
            if deep_out is None:
                raise ValueError("model_type 'deep' needs deep columns")
            out = Activation("softmax", name="prediction")(deep_out)
        else:
            if wide_out is None or deep_out is None:
                raise ValueError("wide_n_deep needs both wide and deep columns")
            out = Activation("softmax", name="prediction")(
                merge([wide_out, deep_out], mode="sum"))
        return Model(inputs, out, name="wide_and_deep")

    def default_compile(self):
        self.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                     metrics=["accuracy"])
