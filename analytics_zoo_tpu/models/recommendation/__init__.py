from .ncf import NeuralCF  # noqa: F401
