from .ncf import NeuralCF  # noqa: F401
from .wide_and_deep import (  # noqa: F401
    ColumnFeatureInfo, WideAndDeep, cross_columns, features_from_dataframe)
from .session_recommender import SessionRecommender  # noqa: F401
