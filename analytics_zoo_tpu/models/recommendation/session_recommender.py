"""Session-based recommender (reference
``models/recommendation/SessionRecommender.scala``: GRU stack over the
session click sequence, optional MLP over summed purchase-history embeddings,
summed logits → softmax over the item vocabulary)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import Recommender, register_zoo_model
from ...keras import Input, Model
from ...keras.engine import SymbolicTensor
from ...keras.layers import (
    Activation, Dense, Embedding, Flatten, GRU, Lambda, merge)


@register_zoo_model
class SessionRecommender(Recommender):
    def __init__(self, item_count: int, item_embed: int = 100,
                 rnn_hidden_layers: Sequence[int] = (40, 20),
                 session_length: int = 0, include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20),
                 history_length: int = 0):
        super().__init__()
        if session_length <= 0:
            raise ValueError("session_length must be positive")
        if include_history and history_length <= 0:
            raise ValueError("history_length must be positive with history")
        self.item_count = item_count
        self.item_embed = item_embed
        self.rnn_hidden_layers = list(rnn_hidden_layers)
        self.session_length = session_length
        self.include_history = include_history
        self.mlp_hidden_layers = list(mlp_hidden_layers)
        self.history_length = history_length

    def get_config(self) -> Dict[str, Any]:
        return {
            "item_count": self.item_count, "item_embed": self.item_embed,
            "rnn_hidden_layers": self.rnn_hidden_layers,
            "session_length": self.session_length,
            "include_history": self.include_history,
            "mlp_hidden_layers": self.mlp_hidden_layers,
            "history_length": self.history_length,
        }

    def build_model(self) -> Model:
        in_session = Input((self.session_length,), name="session_input")
        x = Embedding(self.item_count + 1, self.item_embed, init="normal",
                      name="session_table")(in_session)
        for units in self.rnn_hidden_layers[:-1]:
            x = GRU(units, return_sequences=True)(x)
        x = GRU(self.rnn_hidden_layers[-1], return_sequences=False)(x)
        rnn_logits = Dense(self.item_count, name="rnn_linear")(x)

        if not self.include_history:
            out = Activation("softmax", name="prediction")(rnn_logits)
            return Model(in_session, out, name="session_recommender")

        in_history = Input((self.history_length,), name="history_input")
        h = Embedding(self.item_count + 1, self.item_embed, init="normal",
                      name="history_table")(in_history)
        h = Lambda(lambda t: t.sum(axis=1), name="history_sum")(h)
        for i, units in enumerate(self.mlp_hidden_layers):
            h = Dense(units, activation="relu", name=f"mlp_dense_{i}")(h)
        mlp_logits = Dense(self.item_count, name="mlp_linear")(h)
        out = Activation("softmax", name="prediction")(
            merge([rnn_logits, mlp_logits], mode="sum"))
        return Model([in_session, in_history], out,
                     name="session_recommender")

    def default_compile(self):
        self.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                     metrics=["accuracy"])

    # -- session ranking (reference recommendForSession) ----------------------

    def recommend_for_session(self, sessions, max_items: int = 5,
                              zero_based_label: bool = True,
                              batch_size: int = 1024
                              ) -> List[List[Tuple[int, float]]]:
        """Top-N (item, probability) per session row. Items are 1-based when
        ``zero_based_label`` is False (the reference's BigDL convention)."""
        probs = np.asarray(self.predict(sessions, batch_size=batch_size))
        top = np.argsort(-probs, axis=1)[:, :max_items]
        offset = 0 if zero_based_label else 1
        return [[(int(i) + offset, float(p[i])) for i in row]
                for row, p in zip(top, probs)]
