"""Neural Collaborative Filtering (capability parity with reference
``models/recommendation/NeuralCF.scala:45``: GMF + MLP twin towers over
user/item embeddings, softmax head; same constructor surface).

TPU design notes: the four embedding tables are plain param arrays whose
lookup gradients XLA turns into on-device scatter-adds; for huge vocabularies
pass ``shard_embeddings=True`` so the vocab axis shards over the mesh through
the sparse engine (``parallel/embedding.py``: all-to-all lookup, segment-sum
grads into only the touched rows, sparse row-subset optimizer updates).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from ..common import Recommender, register_zoo_model
from ...keras import Input, Model
from ...keras.layers import Dense, Embedding, Flatten, Lambda, merge


@register_zoo_model
class NeuralCF(Recommender):
    def __init__(self, user_count: int, item_count: int, num_classes: int,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20,
                 shard_embeddings=None, fused_embeddings=None):
        super().__init__()
        self.user_count = user_count
        self.item_count = item_count
        self.num_classes = num_classes
        self.user_embed = user_embed
        self.item_embed = item_embed
        self.hidden_layers = list(hidden_layers)
        self.include_mf = include_mf
        self.mf_embed = mf_embed
        #: None/False = replicated tables; True/axis-name = vocab-shard all
        #: four tables over the mesh (parallel/embedding.py)
        self.shard_embeddings = shard_embeddings
        #: per-model override of the ``kernels.fused_embedding`` knob
        #: (ops/embedding_kernels.py): None follows the config, False pins
        #: all four tables to the unfused bit-parity reference path.
        self.fused_embeddings = fused_embeddings

    def get_config(self):
        return {
            "user_count": self.user_count, "item_count": self.item_count,
            "num_classes": self.num_classes, "user_embed": self.user_embed,
            "item_embed": self.item_embed, "hidden_layers": self.hidden_layers,
            "include_mf": self.include_mf, "mf_embed": self.mf_embed,
            "shard_embeddings": self.shard_embeddings,
            "fused_embeddings": self.fused_embeddings,
        }

    def build_model(self) -> Model:
        pairs = Input((2,), name="user_item_pairs")
        user = Lambda(lambda x: x[:, 0:1], name="user_select")(pairs)
        item = Lambda(lambda x: x[:, 1:2], name="item_select")(pairs)

        shard = self.shard_embeddings
        fused = self.fused_embeddings
        mlp_user = Flatten(name="mlp_user_flat")(
            Embedding(self.user_count + 1, self.user_embed, init="normal",
                      name="mlp_user_table", shard=shard,
                      fused=fused)(user))
        mlp_item = Flatten(name="mlp_item_flat")(
            Embedding(self.item_count + 1, self.item_embed, init="normal",
                      name="mlp_item_table", shard=shard,
                      fused=fused)(item))
        h = merge([mlp_user, mlp_item], mode="concat")
        for i, units in enumerate(self.hidden_layers):
            h = Dense(units, activation="relu", name=f"mlp_dense_{i}")(h)

        if self.include_mf:
            if self.mf_embed <= 0:
                raise ValueError("mf_embed must be positive when include_mf")
            mf_user = Flatten(name="mf_user_flat")(
                Embedding(self.user_count + 1, self.mf_embed, init="normal",
                          name="mf_user_table", shard=shard,
                          fused=fused)(user))
            mf_item = Flatten(name="mf_item_flat")(
                Embedding(self.item_count + 1, self.mf_embed, init="normal",
                          name="mf_item_table", shard=shard,
                          fused=fused)(item))
            gmf = merge([mf_user, mf_item], mode="mul")
            h = merge([h, gmf], mode="concat")
        out = Dense(self.num_classes, activation="softmax", name="prediction")(h)
        return Model(pairs, out, name="neural_cf")

    def default_compile(self):
        self.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                     metrics=["accuracy"])
