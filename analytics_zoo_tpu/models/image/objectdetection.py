"""SSD object detection (reference
``models/image/objectdetection/ObjectDetector.scala:37``, ``ssd/SSD.scala:79``,
``ssd/SSDGraph.scala``, ``common/BboxUtil.scala``, ``Postprocessor.scala:1``,
``common/loss/MultiBoxLoss.scala``).

TPU-first redesign:

- The SSD graph is a native Keras-engine ``Model`` with two static-shape
  outputs: box-regression ``[B, A, 4]`` and class logits ``[B, A, C]`` over
  all ``A`` anchors — all feature-map heads are fused into one concat, so a
  forward pass is one XLA program with MXU-tiled NHWC convs.
- Anchor (prior-box) generation is host-side numpy, computed once per config
  and closed over as a constant (the reference recomputes priors in-graph
  per forward, ``ssd/SSD.scala:111-180``).
- Target matching/encoding (``BboxUtil.matchBboxes/encodeBboxes``) happens in
  the input pipeline (numpy, per record); the device loss consumes
  pre-encoded static-shape targets — no dynamic shapes under jit.
- MultiBox loss runs fully vectorized on device, with hard-negative mining
  as a masked top-k (the reference sorts indices per image in Scala,
  ``MultiBoxLoss.scala``).
- Decode + NMS (``Postprocessor.scala``) is a jitted, static-shape greedy NMS
  over the top-``max_detections`` candidates per class.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..common import ZooModel, register_zoo_model
from ...keras import Input, Model
from ...keras.engine import Layer
from ...keras.layers import (
    Activation, BatchNormalization, Convolution2D, MaxPooling2D, Merge,
    Reshape, ZeroPadding2D, merge)


# ---------------------------------------------------------------------------
# Anchor (PriorBox) generation — host-side, once per config
# ---------------------------------------------------------------------------


def generate_anchors(fmap_sizes: Sequence[int],
                     image_size: int,
                     min_sizes: Sequence[float],
                     max_sizes: Sequence[Optional[float]],
                     aspect_ratios: Sequence[Sequence[float]],
                     clip: bool = True) -> np.ndarray:
    """Prior boxes for every feature map, concatenated: [A, 4] as
    (cx, cy, w, h), normalized to [0, 1] (reference ``PriorBox`` layers
    instantiated in ``ssd/SSD.scala:131-180``).

    Per cell: 1 box at min_size, 1 at sqrt(min*max) (if max), plus 2 per
    extra aspect ratio (r and 1/r) — the standard SSD prior family.

    Ordering is CELL-MAJOR (all k anchors of cell 0, then cell 1, ...) to
    match the head convention: ``Reshape((fsize*fsize*k, 4))`` over an
    NHWC conv output puts the k per-cell predictions contiguously.
    """
    all_priors = []
    for fsize, mn, mx, ratios in zip(fmap_sizes, min_sizes, max_sizes,
                                     aspect_ratios):
        step = image_size / fsize
        sizes = [(mn, mn)]
        if mx:
            s = float(np.sqrt(mn * mx))
            sizes.append((s, s))
        for r in ratios:
            if r == 1.0:
                continue
            sr = float(np.sqrt(r))
            sizes.append((mn * sr, mn / sr))
            sizes.append((mn / sr, mn * sr))
        ys, xs = np.meshgrid(np.arange(fsize), np.arange(fsize), indexing="ij")
        cx = ((xs + 0.5) * step / image_size).reshape(-1)  # [cells]
        cy = ((ys + 0.5) * step / image_size).reshape(-1)
        wh = np.asarray([(w / image_size, h / image_size) for w, h in sizes],
                        np.float32)  # [k, 2]
        k = len(sizes)
        cells = np.stack([cx, cy], axis=1)  # [cells, 2]
        per_cell = np.concatenate([
            np.broadcast_to(cells[:, None, :], (len(cx), k, 2)),
            np.broadcast_to(wh[None, :, :], (len(cx), k, 2)),
        ], axis=-1)  # [cells, k, 4] cell-major
        all_priors.append(per_cell.reshape(-1, 4))
    priors = np.concatenate(all_priors, axis=0).astype(np.float32)
    if clip:
        priors = np.clip(priors, 0.0, 1.0)
    return priors


def _corner_form(cchw: np.ndarray) -> np.ndarray:
    """(cx, cy, w, h) -> (xmin, ymin, xmax, ymax)."""
    cx, cy, w, h = np.split(np.asarray(cchw), 4, axis=-1)
    return np.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          axis=-1)


def iou_matrix(boxes_a: np.ndarray, boxes_b: np.ndarray) -> np.ndarray:
    """Pairwise IoU over corner-form boxes: [Na, Nb]
    (reference ``BboxUtil.jaccardOverlap``)."""
    a = np.asarray(boxes_a)[:, None, :]
    b = np.asarray(boxes_b)[None, :, :]
    lt = np.maximum(a[..., :2], b[..., :2])
    rb = np.minimum(a[..., 2:], b[..., 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = ((a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1]))
    area_b = ((b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1]))
    return inter / np.clip(area_a + area_b - inter, 1e-10, None)


_VARIANCES = (0.1, 0.1, 0.2, 0.2)


def encode_targets(gt_boxes: np.ndarray, gt_labels: np.ndarray,
                   anchors: np.ndarray, iou_threshold: float = 0.5
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Match ground-truth to anchors and encode regression targets
    (reference ``BboxUtil.matchBboxes`` + ``encodeBboxes``).

    gt_boxes: [G, 4] corner form normalized; gt_labels: [G] in 1..C-1
    (0 = background). Returns (loc_targets [A, 4], cls_targets [A]).
    Runs in the input pipeline — numpy, per record.
    """
    A = anchors.shape[0]
    loc_t = np.zeros((A, 4), np.float32)
    cls_t = np.zeros((A,), np.int32)
    if len(gt_boxes) == 0:
        return loc_t, cls_t
    anchors_corner = _corner_form(anchors)
    ious = iou_matrix(anchors_corner, gt_boxes)  # [A, G]
    best_gt = ious.argmax(axis=1)
    best_gt_iou = ious.max(axis=1)
    # force-match: every gt owns its best anchor regardless of threshold
    best_anchor = ious.argmax(axis=0)
    best_gt[best_anchor] = np.arange(len(gt_boxes))
    best_gt_iou[best_anchor] = 1.0
    pos = best_gt_iou >= iou_threshold
    matched = gt_boxes[best_gt]
    # corner -> center form of matched gt
    mw = matched[:, 2] - matched[:, 0]
    mh = matched[:, 3] - matched[:, 1]
    mcx = matched[:, 0] + mw / 2
    mcy = matched[:, 1] + mh / 2
    vx, vy, vw, vh = _VARIANCES
    loc = np.stack([
        (mcx - anchors[:, 0]) / anchors[:, 2] / vx,
        (mcy - anchors[:, 1]) / anchors[:, 3] / vy,
        np.log(np.clip(mw, 1e-8, None) / anchors[:, 2]) / vw,
        np.log(np.clip(mh, 1e-8, None) / anchors[:, 3]) / vh,
    ], axis=1).astype(np.float32)
    loc_t[pos] = loc[pos]
    cls_t[pos] = gt_labels[best_gt[pos]].astype(np.int32)
    return loc_t, cls_t


def decode_boxes(loc: jnp.ndarray, anchors: jnp.ndarray) -> jnp.ndarray:
    """Inverse of ``encode_targets``: loc [.., A, 4] -> corner boxes
    (reference ``BboxUtil.decodeBboxes``). jnp, jit-safe."""
    vx, vy, vw, vh = _VARIANCES
    cx = loc[..., 0] * vx * anchors[:, 2] + anchors[:, 0]
    cy = loc[..., 1] * vy * anchors[:, 3] + anchors[:, 1]
    w = jnp.exp(loc[..., 2] * vw) * anchors[:, 2]
    h = jnp.exp(loc[..., 3] * vh) * anchors[:, 3]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


# ---------------------------------------------------------------------------
# MultiBox loss (reference common/loss/MultiBoxLoss.scala) — on-device
# ---------------------------------------------------------------------------


def multibox_loss(neg_pos_ratio: float = 3.0):
    """Returns ``loss_fn(y, y_pred)`` over pre-encoded targets.

    y = (loc_targets [B, A, 4], cls_targets [B, A]); y_pred = [loc, logits].
    Smooth-L1 on positives + softmax CE with hard-negative mining at
    ``neg_pos_ratio`` negatives per positive, fully vectorized (the mining
    top-k is a sort over the anchor axis — no host sync).
    """
    def loss_fn(y, y_pred):
        loc_t, cls_t = y
        loc_p, logits = y_pred
        cls_t = cls_t.astype(jnp.int32)
        pos = (cls_t > 0).astype(jnp.float32)  # [B, A]
        n_pos = jnp.maximum(pos.sum(axis=1), 1.0)  # [B]

        # smooth L1 over positive anchors
        diff = jnp.abs(loc_p - loc_t)
        sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5).sum(-1)
        loc_loss = (sl1 * pos).sum(axis=1) / n_pos

        # per-anchor CE
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, cls_t[..., None], axis=-1)[..., 0]
        # hard-negative mining: rank background anchors by CE, keep top
        # neg_pos_ratio * n_pos per image
        neg_ce = jnp.where(pos > 0, -jnp.inf, ce)
        order = jnp.argsort(-neg_ce, axis=1)
        ranks = jnp.argsort(order, axis=1).astype(jnp.float32)  # rank per anchor
        n_neg = jnp.minimum(neg_pos_ratio * n_pos,
                            (1 - pos).sum(axis=1))  # [B]
        neg = ((ranks < n_neg[:, None]) & (pos == 0)).astype(jnp.float32)
        cls_loss = (ce * (pos + neg)).sum(axis=1) / n_pos
        return jnp.mean(loc_loss + cls_loss)

    return loss_fn


# ---------------------------------------------------------------------------
# Decode + NMS postprocessor (reference Postprocessor.scala) — jitted
# ---------------------------------------------------------------------------


def _nms_mask(boxes, scores, iou_threshold, max_out):
    """Greedy NMS over top-``max_out`` candidates; returns (boxes, scores)
    padded to max_out with score 0 — static shapes throughout."""
    k = min(max_out, scores.shape[0])
    top_scores, top_idx = jax.lax.top_k(scores, k)
    top_boxes = boxes[top_idx]

    lt = jnp.maximum(top_boxes[:, None, :2], top_boxes[None, :, :2])
    rb = jnp.minimum(top_boxes[:, None, 2:], top_boxes[None, :, 2:])
    wh = jnp.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area = ((top_boxes[:, 2] - top_boxes[:, 0])
            * (top_boxes[:, 3] - top_boxes[:, 1]))
    iou = inter / jnp.clip(area[:, None] + area[None, :] - inter, 1e-10, None)

    def body(i, keep):
        # suppress i if any kept higher-scored j overlaps it
        overlap = (iou[i] > iou_threshold) & keep & (jnp.arange(k) < i)
        return keep.at[i].set(~jnp.any(overlap) & keep[i])

    keep = jax.lax.fori_loop(0, k, body, jnp.ones((k,), bool))
    return top_boxes, jnp.where(keep, top_scores, 0.0)


def decode_detections(loc, logits, anchors, num_classes: int,
                      score_threshold: float = 0.05,
                      iou_threshold: float = 0.45,
                      max_detections: int = 100):
    """[B, A, 4] loc + [B, A, C] logits -> per-image padded detections
    (boxes [B, N, 4], scores [B, N], classes [B, N]) — the reference's
    ``Postprocessor`` topN/NMS pipeline as one jitted program."""
    probs = jax.nn.softmax(logits, axis=-1)
    boxes = decode_boxes(loc, jnp.asarray(anchors))  # [B, A, 4]

    def per_image(bx, pr):
        cls_boxes, cls_scores, cls_ids = [], [], []
        for c in range(1, num_classes):  # 0 = background
            s = jnp.where(pr[:, c] >= score_threshold, pr[:, c], 0.0)
            nb, ns = _nms_mask(bx, s, iou_threshold, max_detections)
            cls_boxes.append(nb)
            cls_scores.append(ns)
            cls_ids.append(jnp.full(ns.shape, c, jnp.int32))
        all_boxes = jnp.concatenate(cls_boxes)
        all_scores = jnp.concatenate(cls_scores)
        all_ids = jnp.concatenate(cls_ids)
        top_s, top_i = jax.lax.top_k(all_scores, max_detections)
        return all_boxes[top_i], top_s, all_ids[top_i]

    return jax.vmap(per_image)(boxes, probs)


# ---------------------------------------------------------------------------
# SSD graph (reference ssd/SSD.scala + SSDGraph.scala)
# ---------------------------------------------------------------------------


class _L2Normalize(Layer):
    """Channel L2-norm with learned per-channel scale — the conv4_3
    normalization (reference ``NormalizeScale`` in SSDGraph)."""

    def __init__(self, scale_init: float = 20.0, name=None):
        super().__init__(name)
        self.scale_init = scale_init

    def build(self, rng, input_shape):
        return {"scale": jnp.full((input_shape[-1],), self.scale_init)}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        norm = jnp.sqrt(jnp.sum(inputs * inputs, axis=-1, keepdims=True) + 1e-10)
        return inputs / norm * params["scale"].astype(inputs.dtype), state


# SSD300 config (reference SSD.scala:131-156): per-map (fsize, n_anchor)
_SSD300 = dict(
    fmap_sizes=[38, 19, 10, 5, 3, 1],
    min_sizes=[30, 60, 111, 162, 213, 264],
    max_sizes=[60, 111, 162, 213, 264, 315],
    aspect_ratios=[[2], [2, 3], [2, 3], [2, 3], [2], [2]],
)

# canonical SSD512 scales (reference ssd/SSD.scala 512 variant): one more
# pyramid level than SSD300, anchors at 64..1 cell grids (24,564 total)
_SSD512 = dict(
    fmap_sizes=[64, 32, 16, 8, 4, 2, 1],
    min_sizes=[36, 77, 154, 230, 307, 384, 461],
    max_sizes=[77, 154, 230, 307, 384, 461, 538],
    aspect_ratios=[[2], [2, 3], [2, 3], [2, 3], [2, 3], [2], [2]],
)


def _anchors_per_cell(ratios: Sequence[float], has_max: bool) -> int:
    return 1 + (1 if has_max else 0) + 2 * len([r for r in ratios if r != 1.0])


def _vgg_block(x, n, filters, name, pool=True, pool_stride=2):
    for i in range(n):
        x = Convolution2D(filters, 3, 3, border_mode="same",
                          activation="relu", name=f"{name}_conv{i + 1}")(x)
    if pool:
        x = MaxPooling2D((2, 2), strides=(pool_stride, pool_stride),
                         border_mode="same", name=f"{name}_pool")(x)
    return x


def ssd_vgg16(num_classes: int, resolution: int = 300) -> Tuple[Model, np.ndarray]:
    """SSD-VGG16 at 300 or 512 resolution: returns (model, anchors). Model
    outputs [loc [B, A, 4], logits [B, A, C]] (reference ``SSD.vgg16`` +
    ``SSDGraph``; 300 and 512 variants as in ssd/SSD.scala)."""
    if resolution == 300:
        cfg = _SSD300
    elif resolution == 512:
        cfg = _SSD512
    else:
        raise ValueError(f"SSD-VGG16 supports resolution 300 or 512, "
                         f"got {resolution}")
    inp = Input((resolution, resolution, 3), name="image")
    # VGG16 trunk
    x = _vgg_block(inp, 2, 64, "block1")
    x = _vgg_block(x, 2, 128, "block2")
    x = _vgg_block(x, 3, 256, "block3")
    x = _vgg_block(x, 3, 512, "block4", pool=False)
    conv4_3 = x  # 38x38
    x = MaxPooling2D((2, 2), border_mode="same", name="block4_pool")(x)
    x = _vgg_block(x, 3, 512, "block5", pool=False)
    x = MaxPooling2D((3, 3), strides=(1, 1), border_mode="same",
                     name="block5_pool")(x)
    # fc6/fc7 as atrous + 1x1 convs
    from ...keras.layers import AtrousConvolution2D
    x = AtrousConvolution2D(1024, 3, 3, atrous_rate=(6, 6), border_mode="same",
                            activation="relu", name="fc6")(x)
    fc7 = Convolution2D(1024, 1, 1, activation="relu", name="fc7")(x)  # 19x19

    def extra(x, c1, c2, stride, pad, name):
        x = Convolution2D(c1, 1, 1, activation="relu", name=f"{name}_1")(x)
        if pad:
            x = ZeroPadding2D((1, 1), name=f"{name}_pad")(x)
            x = Convolution2D(c2, 3, 3, subsample=(stride, stride),
                              activation="relu", name=f"{name}_2")(x)
        else:
            x = Convolution2D(c2, 3, 3, subsample=(stride, stride),
                              activation="relu", border_mode="valid",
                              name=f"{name}_2")(x)
        return x

    if resolution == 300:
        conv6_2 = extra(fc7, 256, 512, 2, True, "conv6")      # 10x10
        conv7_2 = extra(conv6_2, 128, 256, 2, True, "conv7")  # 5x5
        conv8_2 = extra(conv7_2, 128, 256, 1, False, "conv8")  # 3x3
        conv9_2 = extra(conv8_2, 128, 256, 1, False, "conv9")  # 1x1
        fmaps = [_L2Normalize(name="conv4_3_norm")(conv4_3), fc7, conv6_2,
                 conv7_2, conv8_2, conv9_2]
    else:  # 512: five stride-2 extras, one more pyramid level than 300
        conv6_2 = extra(fc7, 256, 512, 2, True, "conv6")       # 16x16
        conv7_2 = extra(conv6_2, 128, 256, 2, True, "conv7")   # 8x8
        conv8_2 = extra(conv7_2, 128, 256, 2, True, "conv8")   # 4x4
        conv9_2 = extra(conv8_2, 128, 256, 2, True, "conv9")   # 2x2
        conv10_2 = extra(conv9_2, 128, 256, 2, True, "conv10")  # 1x1
        fmaps = [_L2Normalize(name="conv4_3_norm")(conv4_3), fc7, conv6_2,
                 conv7_2, conv8_2, conv9_2, conv10_2]
    locs, confs = [], []
    for i, (fmap, fsize, ratios, mx) in enumerate(zip(
            fmaps, cfg["fmap_sizes"], cfg["aspect_ratios"], cfg["max_sizes"])):
        k = _anchors_per_cell(ratios, mx is not None)
        loc = Convolution2D(k * 4, 3, 3, border_mode="same",
                            name=f"head{i}_loc")(fmap)
        conf = Convolution2D(k * num_classes, 3, 3, border_mode="same",
                             name=f"head{i}_conf")(fmap)
        locs.append(Reshape((fsize * fsize * k, 4),
                            name=f"head{i}_loc_flat")(loc))
        confs.append(Reshape((fsize * fsize * k, num_classes),
                             name=f"head{i}_conf_flat")(conf))
    all_loc = merge(locs, mode="concat", concat_axis=1, name="loc_concat")
    all_conf = merge(confs, mode="concat", concat_axis=1, name="conf_concat")
    model = Model(inp, [all_loc, all_conf], name=f"ssd{resolution}_vgg16")
    anchors = generate_anchors(cfg["fmap_sizes"], resolution,
                               cfg["min_sizes"], cfg["max_sizes"],
                               cfg["aspect_ratios"])
    return model, anchors


def ssd_mobilenet(num_classes: int, resolution: int = 300,
                  alpha: float = 1.0) -> Tuple[Model, np.ndarray]:
    """SSD300-MobileNet (reference mobilenet SSD variant): lighter trunk,
    same head/anchor machinery."""
    cfg = _SSD300
    inp = Input((resolution, resolution, 3), name="image")

    def c(f):
        return max(8, int(f * alpha))

    def dw(x, filters, stride, name):
        cin = x.shape[-1]
        x = Convolution2D(cin, 3, 3, subsample=(stride, stride),
                          border_mode="same", bias=False, groups=cin,
                          name=f"{name}_dw")(x)
        x = BatchNormalization(name=f"{name}_dw_bn")(x)
        x = Activation("relu", name=f"{name}_dw_act")(x)
        x = Convolution2D(filters, 1, 1, bias=False, name=f"{name}_pw")(x)
        x = BatchNormalization(name=f"{name}_pw_bn")(x)
        return Activation("relu", name=f"{name}_pw_act")(x)

    x = Convolution2D(c(32), 3, 3, subsample=(2, 2), border_mode="same",
                      bias=False, name="stem")(inp)  # 150
    x = BatchNormalization(name="stem_bn")(x)
    x = Activation("relu", name="stem_act")(x)
    x = dw(x, c(64), 1, "b1")
    x = dw(x, c(128), 2, "b2")   # 75
    x = dw(x, c(128), 1, "b3")
    x = dw(x, c(256), 2, "b4")   # 38
    x = dw(x, c(256), 1, "b5")
    f38 = x
    x = dw(x, c(512), 2, "b6")   # 19
    for i in range(5):
        x = dw(x, c(512), 1, f"b{7 + i}")
    f19 = x
    x = dw(x, c(1024), 2, "b12")  # 10
    f10 = dw(x, c(1024), 1, "b13")
    f5 = dw(f10, c(512), 2, "b14")
    f3 = dw(f5, c(256), 2, "b15")
    f1 = dw(f3, c(256), 3, "b16")

    fmaps = [f38, f19, f10, f5, f3, f1]
    locs, confs = [], []
    for i, (fmap, fsize, ratios, mx) in enumerate(zip(
            fmaps, cfg["fmap_sizes"], cfg["aspect_ratios"], cfg["max_sizes"])):
        k = _anchors_per_cell(ratios, mx is not None)
        loc = Convolution2D(k * 4, 3, 3, border_mode="same",
                            name=f"head{i}_loc")(fmap)
        conf = Convolution2D(k * num_classes, 3, 3, border_mode="same",
                             name=f"head{i}_conf")(fmap)
        locs.append(Reshape((fsize * fsize * k, 4),
                            name=f"head{i}_loc_flat")(loc))
        confs.append(Reshape((fsize * fsize * k, num_classes),
                             name=f"head{i}_conf_flat")(conf))
    all_loc = merge(locs, mode="concat", concat_axis=1, name="loc_concat")
    all_conf = merge(confs, mode="concat", concat_axis=1, name="conf_concat")
    model = Model(inp, [all_loc, all_conf], name="ssd300_mobilenet")
    anchors = generate_anchors(cfg["fmap_sizes"], resolution,
                               cfg["min_sizes"], cfg["max_sizes"],
                               cfg["aspect_ratios"])
    return model, anchors


class SSD:
    """SSD builder facade (reference ``SSD.apply``, ssd/SSD.scala:79)."""

    BACKBONES = {"vgg16": ssd_vgg16, "mobilenet": ssd_mobilenet}
    RESOLUTIONS = {"vgg16": (300, 512), "mobilenet": (300,)}

    def __new__(cls, class_num: int, resolution: int = 300,
                backbone: str = "vgg16"):
        if backbone not in cls.BACKBONES:
            raise ValueError(f"unknown backbone {backbone}; "
                             f"have {sorted(cls.BACKBONES)}")
        if resolution not in cls.RESOLUTIONS[backbone]:
            raise ValueError(
                f"SSD-{backbone} supports resolution "
                f"{' or '.join(map(str, cls.RESOLUTIONS[backbone]))}, "
                f"got {resolution}")
        return cls.BACKBONES[backbone](class_num, resolution)


# ---------------------------------------------------------------------------
# Detection config registry (reference ObjectDetectionConfig.scala:1 —
# per-variant preprocessing + postprocessing parameters keyed by the
# published model names)
# ---------------------------------------------------------------------------

# SSD Caffe-lineage preprocessing: BGR mean subtraction, no std scaling
_SSD_MEAN = [123.0, 117.0, 104.0]
_SSD_STD = [1.0, 1.0, 1.0]

DETECTION_CONFIGS: Dict[str, Dict[str, Any]] = {
    "ssd-vgg16-300x300": {
        "backbone": "vgg16", "resolution": 300,
        "preprocess": {"mean": _SSD_MEAN, "std": _SSD_STD},
        "postprocess": {"score_threshold": 0.05, "iou_threshold": 0.45,
                        "max_detections": 100},
    },
    "ssd-vgg16-512x512": {
        "backbone": "vgg16", "resolution": 512,
        "preprocess": {"mean": _SSD_MEAN, "std": _SSD_STD},
        "postprocess": {"score_threshold": 0.05, "iou_threshold": 0.45,
                        "max_detections": 200},
    },
    "ssd-mobilenet-300x300": {
        "backbone": "mobilenet", "resolution": 300,
        "preprocess": {"mean": [127.5, 127.5, 127.5],
                       "std": [127.5, 127.5, 127.5]},
        "postprocess": {"score_threshold": 0.05, "iou_threshold": 0.45,
                        "max_detections": 100},
    },
}


def detection_config(name: str) -> Dict[str, Any]:
    """Variant config by published name (``ObjectDetectionConfig.scala``
    role). Names follow the reference's ``ssd-<backbone>-<res>`` scheme."""
    if name not in DETECTION_CONFIGS:
        raise ValueError(f"unknown detection config {name!r}; have "
                         f"{sorted(DETECTION_CONFIGS)}")
    return DETECTION_CONFIGS[name]


# ---------------------------------------------------------------------------
# ObjectDetector ZooModel (reference ObjectDetector.scala:37 + config)
# ---------------------------------------------------------------------------


@register_zoo_model
class ObjectDetector(ZooModel):
    """SSD detector with train/predict/postprocess wiring.

    ``fit`` consumes (images, (loc_targets, cls_targets)) — use
    :meth:`encode_batch` to build targets from raw boxes. ``detect`` returns
    per-image (boxes, scores, classes) after NMS.
    """

    def __init__(self, class_num: int, backbone: str = "vgg16",
                 resolution: int = 300, labels: Optional[List[str]] = None):
        super().__init__()
        self.class_num = class_num
        self.backbone = backbone
        self.resolution = resolution
        self.labels = labels
        self.anchors: Optional[np.ndarray] = None
        self._decode_cache: Dict[Tuple, Any] = {}

    @classmethod
    def from_detection_config(cls, name: str, class_num: int,
                              labels: Optional[List[str]] = None
                              ) -> "ObjectDetector":
        """Build a detector from the published variant registry (the
        reference's ``ObjectDetector(model, config)`` load path)."""
        cfg = detection_config(name)
        det = cls(class_num, backbone=cfg["backbone"],
                  resolution=cfg["resolution"], labels=labels)
        det._config_name = name
        return det

    @property
    def _variant_cfg(self) -> Dict[str, Any]:
        name = getattr(self, "_config_name",
                       f"ssd-{self.backbone}-{self.resolution}x"
                       f"{self.resolution}")
        # every SSD.BACKBONES x RESOLUTIONS combo must have a registry
        # entry; a silent fallback would serve another variant's
        # normalization and produce garbage detections
        return detection_config(name)

    def get_config(self) -> Dict[str, Any]:
        return {"class_num": self.class_num, "backbone": self.backbone,
                "resolution": self.resolution, "labels": self.labels}

    def preprocessing_spec(self):
        pre = self._variant_cfg["preprocess"]
        return [{"op": "resize", "height": self.resolution,
                 "width": self.resolution},
                {"op": "channel_normalize", "mean": pre["mean"],
                 "std": pre["std"]},
                {"op": "to_sample"}]

    def build_model(self) -> Model:
        model, anchors = SSD(self.class_num, self.resolution, self.backbone)
        self.anchors = anchors
        return model

    def default_compile(self):
        self._ensure_built()
        self.compile(optimizer="adam", loss=multibox_loss())

    def encode_batch(self, gt_boxes: Sequence[np.ndarray],
                     gt_labels: Sequence[np.ndarray],
                     iou_threshold: float = 0.5
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Per-image gt lists -> stacked (loc_targets, cls_targets)."""
        self._ensure_built()
        pairs = [encode_targets(np.asarray(b, np.float32),
                                np.asarray(l), self.anchors, iou_threshold)
                 for b, l in zip(gt_boxes, gt_labels)]
        return (np.stack([p[0] for p in pairs]),
                np.stack([p[1] for p in pairs]))

    def detect(self, images: np.ndarray, batch_size: int = 16,
               score_threshold: float = 0.05, iou_threshold: float = 0.45,
               max_detections: int = 100):
        """Forward + decode + NMS; returns (boxes, scores, classes) arrays
        ([B, N, 4], [B, N], [B, N]; zero-score rows are padding)."""
        self._ensure_built()
        loc, logits = self.predict(images, batch_size=batch_size)
        key = (score_threshold, iou_threshold, max_detections)
        if key not in self._decode_cache:  # one jit cache entry per config
            self._decode_cache[key] = jax.jit(
                lambda l, g: decode_detections(
                    l, g, self.anchors, self.class_num,
                    score_threshold, iou_threshold, max_detections))
        boxes, scores, classes = self._decode_cache[key](
            jnp.asarray(loc), jnp.asarray(logits))
        return np.asarray(boxes), np.asarray(scores), np.asarray(classes)

    def predict_image_set(self, image_set, batch_size: int = 16, **kwargs):
        """Detections over an ImageSet (reference
        ``ImageModel.predictImageSet`` path). Preprocessing and NMS
        defaults come from the variant's detection config."""
        chain = self.bundled_preprocessing()
        post = dict(self._variant_cfg["postprocess"])
        post.update(kwargs)
        fs = image_set.transform(chain).to_featureset(shuffle=False, shard=False)
        return self.detect(np.asarray(fs.features), batch_size=batch_size,
                           **post)


class Visualizer:
    """Draw detections onto images (reference ``Visualizer.scala``) —
    pure-numpy box painting, no cv2 dependency."""

    def __init__(self, labels: Optional[List[str]] = None,
                 score_threshold: float = 0.3, thickness: int = 2,
                 color=(255, 0, 0)):
        self.labels = labels
        self.score_threshold = score_threshold
        self.thickness = thickness
        self.color = np.asarray(color, np.float32)

    def draw(self, image: np.ndarray, boxes: np.ndarray, scores: np.ndarray,
             classes: np.ndarray) -> np.ndarray:
        img = np.array(image, np.float32, copy=True)
        h, w = img.shape[:2]
        t = self.thickness
        for box, score in zip(boxes, scores):
            if score < self.score_threshold:
                continue
            x0 = int(np.clip(box[0] * w, 0, w - 1))
            y0 = int(np.clip(box[1] * h, 0, h - 1))
            x1 = int(np.clip(box[2] * w, 0, w - 1))
            y1 = int(np.clip(box[3] * h, 0, h - 1))
            img[y0:y0 + t, x0:x1] = self.color
            img[max(0, y1 - t):y1, x0:x1] = self.color
            img[y0:y1, x0:x0 + t] = self.color
            img[y0:y1, max(0, x1 - t):x1] = self.color
        return img
