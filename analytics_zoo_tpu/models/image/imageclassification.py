"""Image classification zoo (reference
``models/image/imageclassification/ImageClassifier.scala`` + per-model
configs): ResNet / MobileNet-v1 builders in the native Keras layer system,
an ``ImageClassifier`` ZooModel wrapping any backbone with its preprocessing
config, top-k labeled predictions over ImageSets.

TPU notes: NHWC convs (MXU-friendly), BatchNorm state in the model-state
pytree, bf16-ready. ResNet-50 here is the north-star training benchmark
(BASELINE.json config #2)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import ZooModel, register_zoo_model
from ...keras import Input, Layer, Model
from ...keras.layers import (
    Activation, AveragePooling2D, BatchNormalization, Convolution2D, Dense,
    Dropout, Flatten, GlobalAveragePooling2D, Lambda, MaxPooling2D, merge)

# the ONE stage table both the bf16 builder and the int8-dataflow backbone
# plan from (ops/int8_dataflow imports it lazily; they must agree on
# architecture per depth)
RESNET_BLOCKS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3),
                 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
_RESNET_BLOCKS = RESNET_BLOCKS

# canonical ImageNet statistics in pixel units — the ONE definition used by
# on-device preprocess, the host ChannelNormalize chain, and bench.py
IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32) * 255.0
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32) * 255.0
_IMAGENET_MEAN, _IMAGENET_STD = IMAGENET_MEAN, IMAGENET_STD


def _input_preprocess(x, mode: Optional[str]):
    """Optional on-device input normalization. ``"imagenet_uint8"`` lets the
    host pipeline ship raw uint8 (4x less host→HBM traffic — see bench.py
    input_pipeline) and XLA fuses the normalize into the first conv."""
    if mode is None:
        return x
    if mode == "imagenet_uint8":
        import jax.numpy as jnp
        return Lambda(
            lambda t: (t.astype(jnp.float32) - _IMAGENET_MEAN) / _IMAGENET_STD,
            name="preprocess")(x)
    raise ValueError(f"unknown preprocess mode {mode!r}")


def _conv_bn(x, filters, k, stride=1, activation="relu", name="",
             border_mode="same", int8=False):
    x = Convolution2D(filters, k, k, subsample=(stride, stride),
                      border_mode=border_mode, bias=False,
                      int8_training=int8,
                      name=f"{name}_conv")(x)
    x = BatchNormalization(name=f"{name}_bn")(x)
    if activation:
        x = Activation(activation, name=f"{name}_act")(x)
    return x


def _basic_block(x, filters, stride, name, pad3="same", int8=False):
    shortcut = x
    y = _conv_bn(x, filters, 3, stride, "relu", f"{name}_a", pad3, int8)
    y = _conv_bn(y, filters, 3, 1, None, f"{name}_b", pad3, int8)
    if stride != 1 or x.shape[-1] != filters:
        shortcut = _conv_bn(x, filters, 1, stride, None, f"{name}_sc",
                            int8=int8)
    return Activation("relu", name=f"{name}_out")(
        merge([y, shortcut], mode="sum"))


def _bottleneck_block(x, filters, stride, name, pad3="same", int8=False):
    shortcut = x
    y = _conv_bn(x, filters, 1, 1, "relu", f"{name}_a", int8=int8)
    y = _conv_bn(y, filters, 3, stride, "relu", f"{name}_b", pad3, int8)
    y = _conv_bn(y, filters * 4, 1, 1, None, f"{name}_c", int8=int8)
    if stride != 1 or x.shape[-1] != filters * 4:
        shortcut = _conv_bn(x, filters * 4, 1, stride, None, f"{name}_sc",
                            int8=int8)
    return Activation("relu", name=f"{name}_out")(
        merge([y, shortcut], mode="sum"))


class Int8DataflowBackbone(Layer):
    """Whole ResNet backbone with int8 tensors BETWEEN layers (delayed
    scaling, custom whole-backbone vjp) — see ``ops/int8_dataflow.py``.
    A single Layer because int8 graph edges carry (int8, scale) pairs the
    generic layer graph doesn't thread."""

    def __init__(self, depth: int, input_shape: Tuple[int, int, int],
                 name: Optional[str] = None):
        super().__init__(name)
        from ...ops.int8_dataflow import Int8ResNetDataflow
        self._flow = Int8ResNetDataflow(depth, input_shape)

    def build(self, rng, input_shape):
        return self._flow.init(rng)

    def call(self, params, state, inputs, *, training=False, rng=None):
        return self._flow.apply(params, state, inputs, training)

    def compute_output_shape(self, input_shape):
        h, w = input_shape[1], input_shape[2]
        return (input_shape[0], -(-h // 32), -(-w // 32),
                self._flow.out_channels)


def resnet(depth: int = 50, num_classes: int = 1000,
           input_shape: Tuple[int, int, int] = (224, 224, 3),
           include_top: bool = True,
           preprocess: Optional[str] = None,
           padding_mode: str = "same",
           int8_training: bool = False,
           dataflow: Optional[str] = None) -> Model:
    """ResNet-v1 (18/34/50/101/152).

    ``padding_mode="torch"`` reproduces torch geometry exactly (symmetric
    explicit pads on the stride-2 convs and the stem pool, where SAME pads
    asymmetrically) so imported torchvision weights are bit-faithful — the
    golden-import test depends on it.

    ``dataflow="int8"`` swaps the backbone for the quantized-dataflow int8
    implementation (int8 inter-layer tensors, delayed scales, int8 MXU
    convs) — the byte-cut lever past the bf16 HBM roofline; see
    ``ops/int8_dataflow.py``.
    """
    if depth not in _RESNET_BLOCKS:
        raise ValueError(f"unsupported depth {depth}; have "
                         f"{sorted(_RESNET_BLOCKS)}")
    if dataflow == "int8":
        if padding_mode != "same" or int8_training:
            raise ValueError(
                "dataflow='int8' uses its own backbone (SAME padding, int8 "
                "convs throughout); it composes with neither "
                "padding_mode='torch' nor the per-layer int8_training flag")
        inp = Input(input_shape, name="image")
        x = _input_preprocess(inp, preprocess)
        x = Int8DataflowBackbone(depth, input_shape,
                                 name="int8_backbone")(x)
        if not include_top:
            return Model(inp, x, name=f"resnet{depth}_int8_features")
        x = GlobalAveragePooling2D(name="avg_pool")(x)
        out = Dense(num_classes, activation="softmax", name="logits")(x)
        return Model(inp, out, name=f"resnet{depth}_int8")
    elif dataflow is not None:
        raise ValueError(f"unknown dataflow mode {dataflow!r}")
    torch_geo = padding_mode == "torch"
    blocks = _RESNET_BLOCKS[depth]
    block_fn = _basic_block if depth < 50 else _bottleneck_block
    pad3 = 1 if torch_geo else "same"
    inp = Input(input_shape, name="image")
    x = _input_preprocess(inp, preprocess)
    x = _conv_bn(x, 64, 7, 2, "relu", "stem", 3 if torch_geo else "same",
                 int8=int8_training)
    x = MaxPooling2D((3, 3), strides=(2, 2),
                     border_mode=1 if torch_geo else "same",
                     name="stem_pool")(x)
    filters = 64
    for stage, n in enumerate(blocks):
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            x = block_fn(x, filters, stride,
                         f"stage{stage + 1}_block{i + 1}", pad3,
                         int8=int8_training)
        filters *= 2
    if not include_top:
        return Model(inp, x, name=f"resnet{depth}_features")
    x = GlobalAveragePooling2D(name="avg_pool")(x)
    out = Dense(num_classes, activation="softmax", name="logits")(x)
    return Model(inp, out, name=f"resnet{depth}")


def mobilenet(num_classes: int = 1000,
              input_shape: Tuple[int, int, int] = (224, 224, 3),
              alpha: float = 1.0, include_top: bool = True) -> Model:
    """MobileNet-v1: depthwise-separable conv stack (depthwise = grouped
    conv with groups == channels; XLA lowers it onto the VPU/MXU)."""
    def dw_sep(x, filters, stride, name):
        cin = x.shape[-1]
        x = Convolution2D(cin, 3, 3, subsample=(stride, stride),
                          border_mode="same", bias=False, groups=cin,
                          name=f"{name}_dw")(x)
        x = BatchNormalization(name=f"{name}_dw_bn")(x)
        x = Activation("relu", name=f"{name}_dw_act")(x)
        return _conv_bn(x, filters, 1, 1, "relu", f"{name}_pw")

    def c(f):
        return max(8, int(f * alpha))

    inp = Input(input_shape, name="image")
    x = _conv_bn(inp, c(32), 3, 2, "relu", "stem")
    cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
           (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
           (1024, 1)]
    for i, (f, s) in enumerate(cfg):
        x = dw_sep(x, c(f), s, f"block{i + 1}")
    if not include_top:
        return Model(inp, x, name="mobilenet_features")
    x = GlobalAveragePooling2D(name="avg_pool")(x)
    out = Dense(num_classes, activation="softmax", name="logits")(x)
    return Model(inp, out, name="mobilenet")


def inception_v1(num_classes: int = 1000,
                 input_shape: Tuple[int, int, int] = (224, 224, 3),
                 include_top: bool = True) -> Model:
    """GoogLeNet / Inception-v1 (reference examples/inception +
    ImageClassifier ``inception-v1`` config). Plain conv+relu as in the
    original (no BN); the four parallel branches of every inception module
    are independent convs XLA schedules back-to-back on the MXU."""
    def conv(x, filters, k, stride=1, name=""):
        x = Convolution2D(filters, k, k, subsample=(stride, stride),
                          border_mode="same", name=f"{name}_conv")(x)
        return Activation("relu", name=f"{name}_act")(x)

    def module(x, f1, f3r, f3, f5r, f5, fp, name):
        b1 = conv(x, f1, 1, 1, f"{name}_b1")
        b3 = conv(conv(x, f3r, 1, 1, f"{name}_b3r"), f3, 3, 1, f"{name}_b3")
        b5 = conv(conv(x, f5r, 1, 1, f"{name}_b5r"), f5, 5, 1, f"{name}_b5")
        bp = MaxPooling2D((3, 3), strides=(1, 1), border_mode="same",
                          name=f"{name}_pool")(x)
        bp = conv(bp, fp, 1, 1, f"{name}_bp")
        return merge([b1, b3, b5, bp], mode="concat", name=f"{name}_out")

    inp = Input(input_shape, name="image")
    x = conv(inp, 64, 7, 2, "stem1")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="stem1_pool")(x)
    x = conv(x, 64, 1, 1, "stem2a")
    x = conv(x, 192, 3, 1, "stem2b")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="stem2_pool")(x)
    x = module(x, 64, 96, 128, 16, 32, 32, "inc3a")
    x = module(x, 128, 128, 192, 32, 96, 64, "inc3b")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="inc3_pool")(x)
    x = module(x, 192, 96, 208, 16, 48, 64, "inc4a")
    x = module(x, 160, 112, 224, 24, 64, 64, "inc4b")
    x = module(x, 128, 128, 256, 24, 64, 64, "inc4c")
    x = module(x, 112, 144, 288, 32, 64, 64, "inc4d")
    x = module(x, 256, 160, 320, 32, 128, 128, "inc4e")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="inc4_pool")(x)
    x = module(x, 256, 160, 320, 32, 128, 128, "inc5a")
    x = module(x, 384, 192, 384, 48, 128, 128, "inc5b")
    if not include_top:
        return Model(inp, x, name="inception_v1_features")
    x = GlobalAveragePooling2D(name="avg_pool")(x)
    x = Dropout(0.4, name="drop")(x)
    out = Dense(num_classes, activation="softmax", name="logits")(x)
    return Model(inp, out, name="inception_v1")


def vgg(depth: int = 16, num_classes: int = 1000,
        input_shape: Tuple[int, int, int] = (224, 224, 3),
        include_top: bool = True, fc_dim: int = 4096) -> Model:
    """VGG-16/19 (reference ImageClassifier ``vgg-16``/``vgg-19`` configs;
    also the SSD backbone family). ``fc_dim`` is parameterized so small
    deployments can shrink the two giant FC layers."""
    cfg = {16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}
    if depth not in cfg:
        raise ValueError(f"unsupported VGG depth {depth}; have {sorted(cfg)}")
    inp = Input(input_shape, name="image")
    x, filters = inp, 64
    for stage, n in enumerate(cfg[depth]):
        for i in range(n):
            x = Convolution2D(min(filters, 512), 3, 3, border_mode="same",
                              activation="relu",
                              name=f"block{stage + 1}_conv{i + 1}")(x)
        x = MaxPooling2D((2, 2), name=f"block{stage + 1}_pool")(x)
        filters *= 2
    if not include_top:
        return Model(inp, x, name=f"vgg{depth}_features")
    x = Flatten(name="flatten")(x)
    x = Dense(fc_dim, activation="relu", name="fc1")(x)
    x = Dropout(0.5, name="fc1_drop")(x)
    x = Dense(fc_dim, activation="relu", name="fc2")(x)
    x = Dropout(0.5, name="fc2_drop")(x)
    out = Dense(num_classes, activation="softmax", name="logits")(x)
    return Model(inp, out, name=f"vgg{depth}")


def squeezenet(num_classes: int = 1000,
               input_shape: Tuple[int, int, int] = (224, 224, 3),
               include_top: bool = True) -> Model:
    """SqueezeNet v1.1 (reference ImageClassifier ``squeezenet`` config):
    fire modules = 1x1 squeeze then parallel 1x1/3x3 expand concat."""
    def fire(x, squeeze, expand, name):
        s = Convolution2D(squeeze, 1, 1, activation="relu",
                          name=f"{name}_sq")(x)
        e1 = Convolution2D(expand, 1, 1, activation="relu",
                           name=f"{name}_e1")(s)
        e3 = Convolution2D(expand, 3, 3, border_mode="same",
                           activation="relu", name=f"{name}_e3")(s)
        return merge([e1, e3], mode="concat", name=f"{name}_out")

    inp = Input(input_shape, name="image")
    x = Convolution2D(64, 3, 3, subsample=(2, 2), activation="relu",
                      name="stem")(inp)
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool1")(x)
    x = fire(x, 16, 64, "fire2")
    x = fire(x, 16, 64, "fire3")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool3")(x)
    x = fire(x, 32, 128, "fire4")
    x = fire(x, 32, 128, "fire5")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool5")(x)
    x = fire(x, 48, 192, "fire6")
    x = fire(x, 48, 192, "fire7")
    x = fire(x, 64, 256, "fire8")
    x = fire(x, 64, 256, "fire9")
    if not include_top:
        return Model(inp, x, name="squeezenet_features")
    x = Dropout(0.5, name="drop")(x)
    x = Convolution2D(num_classes, 1, 1, activation="relu", name="conv10")(x)
    x = GlobalAveragePooling2D(name="avg_pool")(x)
    out = Activation("softmax", name="probs")(x)
    return Model(inp, out, name="squeezenet")


def densenet(depth: int = 121, num_classes: int = 1000,
             input_shape: Tuple[int, int, int] = (224, 224, 3),
             include_top: bool = True, growth_rate: int = 32) -> Model:
    """DenseNet-121/169 (reference ImageClassifier ``densenet-161`` role).
    BN→relu→conv pre-activation ordering; each dense layer's output is
    concatenated onto the running feature map."""
    cfg = {121: (6, 12, 24, 16), 169: (6, 12, 32, 32)}
    if depth not in cfg:
        raise ValueError(f"unsupported DenseNet depth {depth}; "
                         f"have {sorted(cfg)}")

    def bn_relu_conv(x, filters, k, name):
        x = BatchNormalization(name=f"{name}_bn")(x)
        x = Activation("relu", name=f"{name}_act")(x)
        return Convolution2D(filters, k, k, border_mode="same", bias=False,
                             name=f"{name}_conv")(x)

    inp = Input(input_shape, name="image")
    x = _conv_bn(inp, 64, 7, 2, "relu", "stem")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="stem_pool")(x)
    channels = 64
    for stage, n in enumerate(cfg[depth]):
        for i in range(n):
            name = f"dense{stage + 1}_{i + 1}"
            y = bn_relu_conv(x, 4 * growth_rate, 1, f"{name}_a")
            y = bn_relu_conv(y, growth_rate, 3, f"{name}_b")
            x = merge([x, y], mode="concat", name=f"{name}_cat")
            channels += growth_rate
        if stage < len(cfg[depth]) - 1:  # transition halves channels + size
            channels //= 2
            x = bn_relu_conv(x, channels, 1, f"trans{stage + 1}")
            x = AveragePooling2D((2, 2), name=f"trans{stage + 1}_pool")(x)
    x = BatchNormalization(name="final_bn")(x)
    x = Activation("relu", name="final_act")(x)
    if not include_top:
        return Model(inp, x, name=f"densenet{depth}_features")
    x = GlobalAveragePooling2D(name="avg_pool")(x)
    out = Dense(num_classes, activation="softmax", name="logits")(x)
    return Model(inp, out, name=f"densenet{depth}")


_BACKBONES: Dict[str, Callable] = {
    "resnet18": lambda n, s: resnet(18, n, s),
    "resnet34": lambda n, s: resnet(34, n, s),
    "resnet50": lambda n, s: resnet(50, n, s),
    "resnet101": lambda n, s: resnet(101, n, s),
    "resnet152": lambda n, s: resnet(152, n, s),
    "mobilenet": lambda n, s: mobilenet(n, s),
    "inception-v1": lambda n, s: inception_v1(n, s),
    "vgg-16": lambda n, s: vgg(16, n, s),
    "vgg-19": lambda n, s: vgg(19, n, s),
    "squeezenet": lambda n, s: squeezenet(n, s),
    "densenet-121": lambda n, s: densenet(121, n, s),
}


@register_zoo_model
class ImageClassifier(ZooModel):
    """Config-driven classifier (reference ``ImageClassifier`` + label maps).

    ``predict_image_set`` runs the model's preprocessing chain over an
    ImageSet and returns top-k (label, prob) per image."""

    def __init__(self, model_name: str = "resnet50", num_classes: int = 1000,
                 input_shape: Sequence[int] = (224, 224, 3),
                 labels: Optional[List[str]] = None,
                 padding_mode: str = "same"):
        super().__init__()
        if model_name not in _BACKBONES:
            raise ValueError(f"unknown model_name {model_name}; have "
                             f"{sorted(_BACKBONES)}")
        self.model_name = model_name
        self.num_classes = num_classes
        self.input_shape = tuple(input_shape)
        self.labels = labels
        self.padding_mode = padding_mode

    @staticmethod
    def load_label_map(path: str) -> List[str]:
        """Load a class-index→name map (the reference ships label maps with
        each pretrained artifact, ``ImageClassificationConfig.scala``).
        Accepts a JSON list ``["tench", ...]``, a JSON dict keyed by index
        (zero- OR one-based, both published formats exist), or plain text
        with one label per line; local path or scheme URI."""
        import json

        from ...common import file_io
        with file_io.fopen(path) as f:
            text = f.read()
        try:
            data = json.loads(text)
        except ValueError:
            return [line.strip() for line in text.splitlines() if line.strip()]
        if isinstance(data, dict):
            base = 0 if "0" in data else 1 if "1" in data else None
            if base is None or not all(
                    str(i + base) in data for i in range(len(data))):
                raise ValueError(
                    f"label map dict at {path} is not contiguously indexed "
                    f"from 0 or 1 (got keys like {sorted(data)[:3]}...)")
            return [data[str(i + base)] for i in range(len(data))]
        return list(data)

    def with_label_map(self, path: str) -> "ImageClassifier":
        self.labels = self.load_label_map(path)
        return self

    def load_pretrained_torch(self, module_or_path,
                              padding_mode: str = "torch"
                              ) -> "ImageClassifier":
        """Import pretrained torch weights (e.g. a torchvision state_dict)
        into this classifier's backbone — golden-validated by
        ``tests/test_torch_golden.py`` (logits match torch within 1e-4)."""
        from ...net.torch_import import load_torch
        if self.model_name.startswith("resnet") and padding_mode == "torch":
            # record the geometry so save_model/load_model round-trips
            # rebuild the SAME network (bit-faithfulness survives reload)
            self.padding_mode = "torch"
            depth = int(self.model_name[len("resnet"):])
            self.model = resnet(depth, self.num_classes, self.input_shape,
                                padding_mode="torch")
        model = self._ensure_built()
        params, state = load_torch(model, module_or_path)
        if not hasattr(model, "loss_fn"):
            self.default_compile()
        est = model.get_estimator()
        est.set_params(params)
        est.set_model_state(state)
        return self

    def get_config(self) -> Dict[str, Any]:
        return {"model_name": self.model_name,
                "num_classes": self.num_classes,
                "input_shape": list(self.input_shape),
                "labels": self.labels,
                "padding_mode": self.padding_mode}

    def build_model(self) -> Model:
        if self.model_name.startswith("resnet"):
            # padding geometry is part of the persisted config so a
            # torch-imported model round-trips save_model/load_model
            # without silently changing its stride-2 pads
            return resnet(int(self.model_name[len("resnet"):]),
                          self.num_classes, self.input_shape,
                          padding_mode=self.padding_mode)
        return _BACKBONES[self.model_name](self.num_classes, self.input_shape)

    def default_compile(self):
        self.compile(optimizer="adam",
                     loss="sparse_categorical_crossentropy",
                     metrics=["accuracy"])

    def preprocessing_spec(self):
        """Serializable input chain — persisted in pretrained bundles."""
        from ...feature.image.spec import classification_spec
        h, w, _ = self.input_shape
        return classification_spec(h, w, IMAGENET_MEAN.tolist(),
                                   IMAGENET_STD.tolist())

    def preprocessing(self):
        """The model's input chain (reference per-model configs). A
        bundle-loaded classifier uses the chain it shipped with."""
        return self.bundled_preprocessing()

    def predict_image_set(self, image_set, top_k: int = 5,
                          batch_size: int = 32):
        """Top-k labeled predictions per image (reference
        ``ImageClassifier.predictImageSet`` + label map output)."""
        fs = image_set.transform(self.preprocessing()).to_featureset(
            shuffle=False, shard=False)
        probs = np.asarray(self._ensure_built().get_estimator().predict(
            fs, batch_size=batch_size))
        top = np.argsort(-probs, axis=1)[:, :top_k]
        out = []
        for row, p in zip(top, probs):
            labeled = [((self.labels[i] if self.labels else int(i)),
                        float(p[i])) for i in row]
            out.append(labeled)
        return out
