"""Detection evaluation — mean average precision (reference
``models/image/objectdetection/common/evaluation/MeanAveragePrecision.scala:1``
+ ``EvalUtil.scala`` / ``PascalVocEvaluator.scala``).

Pascal-VOC protocol: detections matched to ground truth greedily by score at
an IoU threshold; AP per class from the precision/recall curve (VOC-2007
11-point interpolation or the continuous area under the interpolated curve);
mAP = mean over classes with ground truth. Host-side numpy — evaluation
aggregates tiny per-image lists, not a device-bound workload.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .objectdetection import iou_matrix


class MeanAveragePrecision:
    """Streaming mAP accumulator.

    ``add(boxes, scores, classes, gt_boxes, gt_labels)`` per image (corner
    boxes, classes in 1..C-1, zero-score detection rows ignored), then
    ``compute()`` -> {"mAP": float, "ap_per_class": {cls: ap}}.
    """

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 use_voc2007: bool = False):
        self.num_classes = num_classes
        self.iou_threshold = iou_threshold
        self.use_voc2007 = use_voc2007
        # per class: list of (score, is_tp); gt counts
        self._dets: Dict[int, List] = {c: [] for c in range(1, num_classes)}
        self._n_gt = np.zeros(num_classes, np.int64)

    def add(self, boxes: np.ndarray, scores: np.ndarray, classes: np.ndarray,
            gt_boxes: np.ndarray, gt_labels: np.ndarray) -> None:
        boxes = np.asarray(boxes, np.float32)
        scores = np.asarray(scores, np.float32)
        classes = np.asarray(classes)
        gt_boxes = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
        gt_labels = np.asarray(gt_labels).reshape(-1)
        for c in np.unique(gt_labels):
            self._n_gt[int(c)] += int((gt_labels == c).sum())
        for c in range(1, self.num_classes):
            sel = (classes == c) & (scores > 0)
            if not sel.any():
                continue
            det_b = boxes[sel]
            det_s = scores[sel]
            order = np.argsort(-det_s)
            det_b, det_s = det_b[order], det_s[order]
            gsel = gt_labels == c
            gts = gt_boxes[gsel]
            matched = np.zeros(len(gts), bool)
            for b, s in zip(det_b, det_s):
                if len(gts) == 0:
                    self._dets[c].append((float(s), 0))
                    continue
                ious = iou_matrix(b[None, :], gts)[0]
                j = int(ious.argmax())
                if ious[j] >= self.iou_threshold and not matched[j]:
                    matched[j] = True
                    self._dets[c].append((float(s), 1))
                else:
                    self._dets[c].append((float(s), 0))

    def _ap(self, recalls: np.ndarray, precisions: np.ndarray) -> float:
        if self.use_voc2007:
            # 11-point interpolation (EvalUtil.computeAP voc2007 branch)
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                mask = recalls >= t
                ap += (precisions[mask].max() if mask.any() else 0.0) / 11
            return float(ap)
        # continuous: area under the monotone precision envelope
        mrec = np.concatenate([[0.0], recalls, [1.0]])
        mpre = np.concatenate([[0.0], precisions, [0.0]])
        for i in range(len(mpre) - 2, -1, -1):
            mpre[i] = max(mpre[i], mpre[i + 1])
        idx = np.where(mrec[1:] != mrec[:-1])[0]
        return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))

    def compute(self) -> Dict[str, object]:
        aps = {}
        for c in range(1, self.num_classes):
            n_gt = self._n_gt[c]
            if n_gt == 0:
                continue
            dets = sorted(self._dets[c], key=lambda t: -t[0])
            if not dets:
                aps[c] = 0.0
                continue
            tp = np.cumsum([d[1] for d in dets]).astype(np.float64)
            fp = np.cumsum([1 - d[1] for d in dets]).astype(np.float64)
            recalls = tp / n_gt
            precisions = tp / np.maximum(tp + fp, 1e-10)
            aps[c] = self._ap(recalls, precisions)
        mAP = float(np.mean(list(aps.values()))) if aps else 0.0
        return {"mAP": mAP, "ap_per_class": aps}
