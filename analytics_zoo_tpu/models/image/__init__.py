from .imageclassification import ImageClassifier, mobilenet, resnet  # noqa: F401
from .objectdetection import (  # noqa: F401
    ObjectDetector, SSD, Visualizer, decode_detections)
from .evaluation import MeanAveragePrecision  # noqa: F401
