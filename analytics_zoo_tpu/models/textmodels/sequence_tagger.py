"""Sequence-labeling text models (reference
``pyzoo/zoo/tfpark/text/keras/{ner.py,pos_tagging.py,intent_extraction.py}``
which wrap nlp-architect's word+char Bi-LSTM taggers).

Re-designed natively: a shared word+character encoder — word embeddings
concatenated with a per-word character Bi-LSTM summary (a nested Model
folded over the sequence axis via ``TimeDistributed``, so the whole char
pass is ONE fused batch matmul stream on the MXU, no Python loop) — feeding
a tagger Bi-LSTM. Heads:

- :class:`SequenceTagger` / :class:`POSTagger` / :class:`NER` — per-token
  softmax tag distribution ``[B, S, num_tags]``, or with ``crf=True`` a
  linear-chain CRF head (the reference's NERCRF): ``predict`` then returns
  transition log-potentials and :meth:`SequenceTagger.decode` runs Viterbi.
- :class:`IntentEntity` — joint multi-task head: intent ``[B, num_intents]``
  from pad-masked mean-pooled tagger states plus slot tags
  ``[B, S, num_entities]``, trained with a weighted joint loss.

Inputs follow the reference contract: word indices ``[B, S]`` and char
indices ``[B, S, W]``, with index 0 reserved for padding. For padded
batches pass ``pad_tag`` (the label value used at pad positions, e.g. 0 or
-1): the tag loss then excludes pad positions (the reference's CRF 'pad'
mode role); with ``pad_tag=None`` every position counts.
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from ..common import ZooModel, register_zoo_model
from ...keras import Input, Model
from ...keras.layers import (
    Bidirectional, Dense, Dropout, Embedding, Lambda, LSTM, merge,
    TimeDistributed)
from ...keras import objectives


def _char_word_encoder(seq_len: int, word_len: int, word_vocab: int,
                       char_vocab: int, word_emb: int, char_emb: int,
                       char_lstm_dim: int, tagger_lstm_dim: int,
                       dropout: float):
    """Shared encoder: returns (inputs, per-token states [B, S, 2*tagger])."""
    word_in = Input((seq_len,), name="words")
    char_in = Input((seq_len, word_len), name="chars")

    w = Embedding(word_vocab, word_emb, name="word_embedding")(word_in)

    per_word = Input((word_len,), name="word_chars")
    ce = Embedding(char_vocab, char_emb, name="char_embedding")(per_word)
    csum = Bidirectional(LSTM(char_lstm_dim), name="char_bilstm")(ce)
    char_model = Model(per_word, csum, name="char_encoder")
    c = TimeDistributed(char_model, name="char_per_token")(char_in)

    x = merge([w, c], mode="concat", name="word_char_concat")
    x = Dropout(dropout, name="encoder_dropout")(x)
    x = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True),
                      name="tagger_bilstm")(x)
    return [word_in, char_in], x


@register_zoo_model
class SequenceTagger(ZooModel):
    """Word+char Bi-LSTM sequence tagger (reference ``pos_tagging.py``
    SequenceTagger role): softmax tag distribution per token."""

    def __init__(self, num_tags: int, word_vocab_size: int,
                 char_vocab_size: int, sequence_length: int = 64,
                 word_length: int = 12, word_emb_dim: int = 100,
                 char_emb_dim: int = 30, char_lstm_dim: int = 30,
                 tagger_lstm_dim: int = 100, dropout: float = 0.5,
                 pad_tag: Any = None, crf: bool = False):
        super().__init__()
        self.crf = crf
        self.num_tags = num_tags
        self.word_vocab_size = word_vocab_size
        self.char_vocab_size = char_vocab_size
        self.sequence_length = sequence_length
        self.word_length = word_length
        self.word_emb_dim = word_emb_dim
        self.char_emb_dim = char_emb_dim
        self.char_lstm_dim = char_lstm_dim
        self.tagger_lstm_dim = tagger_lstm_dim
        self.dropout = dropout
        self.pad_tag = pad_tag

    def get_config(self) -> Dict[str, Any]:
        return {"num_tags": self.num_tags,
                "word_vocab_size": self.word_vocab_size,
                "char_vocab_size": self.char_vocab_size,
                "sequence_length": self.sequence_length,
                "word_length": self.word_length,
                "word_emb_dim": self.word_emb_dim,
                "char_emb_dim": self.char_emb_dim,
                "char_lstm_dim": self.char_lstm_dim,
                "tagger_lstm_dim": self.tagger_lstm_dim,
                "dropout": self.dropout,
                "pad_tag": self.pad_tag,
                "crf": self.crf}

    def tag_loss(self):
        """Sparse CE over tokens; with ``pad_tag`` set, pad positions are
        excluded from the mean (reference CRF 'pad' mode role)."""
        if self.pad_tag is None:
            return objectives.get("sparse_categorical_crossentropy")
        pad = self.pad_tag

        def loss_fn(y_true, y_pred):
            idx = y_true.astype(jnp.int32)
            logp = jnp.log(jnp.clip(y_pred, 1e-7, 1.0))
            tok = -jnp.take_along_axis(
                logp, jnp.clip(idx, 0, None)[..., None], axis=-1)[..., 0]
            mask = (idx != pad).astype(tok.dtype)
            return jnp.sum(tok * mask) / jnp.clip(jnp.sum(mask), 1.0, None)
        return loss_fn

    def build_model(self) -> Model:
        inputs, states = _char_word_encoder(
            self.sequence_length, self.word_length, self.word_vocab_size,
            self.char_vocab_size, self.word_emb_dim, self.char_emb_dim,
            self.char_lstm_dim, self.tagger_lstm_dim, self.dropout)
        if self.crf:
            from ...keras.layers.crf import CRF
            emis = Dense(self.num_tags, name="emissions")(states)
            pot = CRF(self.num_tags, name="crf")(emis)
            return Model(inputs, pot, name=type(self).__name__.lower())
        tags = Dense(self.num_tags, activation="softmax", name="tags")(states)
        return Model(inputs, tags, name=type(self).__name__.lower())

    def decode(self, x, batch_size: int = 32):
        """Hard tag path per sequence ``[B, S]``: Viterbi for the CRF head,
        per-token argmax for the softmax head. With ``pad_tag`` set, pad
        positions (word index 0) are masked out of the Viterbi recursion and
        emitted as ``pad_tag``."""
        import numpy as np
        pred = self.predict(x, batch_size=batch_size)
        if self.crf:
            from ...keras.layers.crf import crf_decode
            if self.pad_tag is not None:
                words = np.asarray(x[0] if isinstance(x, (list, tuple))
                                   else x)
                # synthesize a tags-shaped array whose pad positions carry
                # pad_tag so crf_decode's mask derivation applies
                y_like = jnp.where(jnp.asarray(words) != 0,
                                   self.pad_tag + 1, self.pad_tag)
                return np.asarray(crf_decode(pred, pad_tag=self.pad_tag,
                                             y_like=y_like))
            return np.asarray(crf_decode(pred))
        return np.asarray(jnp.argmax(jnp.asarray(pred), axis=-1))

    def default_compile(self):
        if self.crf:
            from ...keras.layers.crf import crf_nll
            self.compile(optimizer="adam", loss=crf_nll(self.pad_tag))
            return
        self.compile(optimizer="adam", loss=self.tag_loss(),
                     metrics=[] if self.pad_tag is not None else ["accuracy"])


@register_zoo_model
class POSTagger(SequenceTagger):
    """Part-of-speech tagger (reference ``pos_tagging.py``)."""


@register_zoo_model
class NER(SequenceTagger):
    """Named-entity tagger (reference ``ner.py`` NERCRF): softmax head by
    default, or the full linear-chain CRF head with ``crf=True`` (train
    with ``crf_nll`` via ``default_compile``, decode with Viterbi)."""


@register_zoo_model
class IntentEntity(ZooModel):
    """Joint intent classification + slot filling (reference
    ``intent_extraction.py`` MultiTaskIntentModel): one shared encoder, two
    heads, trained with ``joint_loss``."""

    def __init__(self, num_intents: int, num_entities: int,
                 word_vocab_size: int, char_vocab_size: int,
                 sequence_length: int = 64, word_length: int = 12,
                 word_emb_dim: int = 100, char_emb_dim: int = 30,
                 char_lstm_dim: int = 30, tagger_lstm_dim: int = 100,
                 dropout: float = 0.2, intent_loss_weight: float = 1.0,
                 pad_tag: Any = None):
        super().__init__()
        self.num_intents = num_intents
        self.num_entities = num_entities
        self.word_vocab_size = word_vocab_size
        self.char_vocab_size = char_vocab_size
        self.sequence_length = sequence_length
        self.word_length = word_length
        self.word_emb_dim = word_emb_dim
        self.char_emb_dim = char_emb_dim
        self.char_lstm_dim = char_lstm_dim
        self.tagger_lstm_dim = tagger_lstm_dim
        self.dropout = dropout
        self.intent_loss_weight = intent_loss_weight
        self.pad_tag = pad_tag

    def get_config(self) -> Dict[str, Any]:
        return {"num_intents": self.num_intents,
                "num_entities": self.num_entities,
                "word_vocab_size": self.word_vocab_size,
                "char_vocab_size": self.char_vocab_size,
                "sequence_length": self.sequence_length,
                "word_length": self.word_length,
                "word_emb_dim": self.word_emb_dim,
                "char_emb_dim": self.char_emb_dim,
                "char_lstm_dim": self.char_lstm_dim,
                "tagger_lstm_dim": self.tagger_lstm_dim,
                "dropout": self.dropout,
                "intent_loss_weight": self.intent_loss_weight,
                "pad_tag": self.pad_tag}

    def build_model(self) -> Model:
        inputs, states = _char_word_encoder(
            self.sequence_length, self.word_length, self.word_vocab_size,
            self.char_vocab_size, self.word_emb_dim, self.char_emb_dim,
            self.char_lstm_dim, self.tagger_lstm_dim, self.dropout)
        # intent vector = mean over REAL tokens only (word index 0 = pad),
        # so short sentences aren't diluted by pad-position LSTM states
        def masked_mean(ts):
            states_t, words_t = ts
            mask = (words_t != 0).astype(states_t.dtype)[..., None]
            return (jnp.sum(states_t * mask, axis=1)
                    / jnp.clip(jnp.sum(mask, axis=1), 1.0, None))

        pooled = Lambda(masked_mean, name="masked_mean_pool")(
            [states, inputs[0]])
        intent = Dense(self.num_intents, activation="softmax",
                       name="intent")(pooled)
        slots = Dense(self.num_entities, activation="softmax",
                      name="slots")(states)
        return Model(inputs, [intent, slots], name="intent_entity")

    def joint_loss(self):
        """``y = (intent_labels [B], slot_labels [B, S])``; weighted sum of
        the intent CE and the (pad-masked, when ``pad_tag`` is set) slot
        CE."""
        sce = objectives.get("sparse_categorical_crossentropy")
        slot_loss = SequenceTagger.tag_loss(self)  # shares pad_tag handling
        w = self.intent_loss_weight

        def loss_fn(y_true, y_pred):
            intent_t, slots_t = y_true
            intent_p, slots_p = y_pred
            return w * sce(intent_t, intent_p) + slot_loss(slots_t, slots_p)
        return loss_fn

    def default_compile(self):
        self.compile(optimizer="adam", loss=self.joint_loss())
