from .sequence_tagger import (  # noqa: F401
    IntentEntity, NER, POSTagger, SequenceTagger)
