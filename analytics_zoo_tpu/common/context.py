"""TPU context initialization — the ``init_nncontext`` equivalent.

The reference boots a SparkContext + BigDL engine (``NNContext.initNNContext``,
``zoo/.../common/NNContext.scala:133``; Python ``pyzoo/zoo/common/nncontext.py:109``).
On TPU there is no JVM and no Spark: "context" means the JAX runtime, the device
mesh (ICI topology within a slice, DCN across slices), process/host identity, and
a deterministic RNG root. ``init_tpu_context()`` discovers all of that once and
caches it process-wide, exactly as ``init_nncontext`` memoizes the SparkContext.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from .config import global_config

logger = logging.getLogger("analytics_zoo_tpu")


@dataclass
class ZooTpuContext:
    """Process-wide runtime context (the NNContext equivalent)."""

    mesh: Mesh
    devices: Sequence[jax.Device]
    process_index: int
    process_count: int
    platform: str
    config: Dict[str, object] = field(default_factory=dict)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def data_axis(self) -> str:
        return self.mesh.axis_names[0]

    @property
    def model_axis(self) -> Optional[str]:
        return self.mesh.axis_names[1] if len(self.mesh.axis_names) > 1 else None

    def local_batch(self, global_batch: int) -> int:
        """Per-process share of a global batch (reference: global batch =
        nodes x cores x per-core batch, ``Topology.scala:1110-1119``)."""
        if global_batch % self.process_count != 0:
            raise ValueError(
                f"global batch {global_batch} not divisible by process count "
                f"{self.process_count}")
        return global_batch // self.process_count


_context_lock = threading.Lock()
_context: Optional[ZooTpuContext] = None
_cache_wired: bool = False


def wire_compilation_cache() -> bool:
    """Point JAX's persistent compilation cache at ``compile.cache_dir``.

    Idempotent; returns whether a cache dir is active. Called from context
    init (training) and ``InferenceModel`` construction (serving — which
    may never init a mesh context): a process restart then deserializes
    yesterday's XLA programs from disk instead of recompiling, which turns
    a multi-second serving cold-start into a file read. The min-size/
    min-compile-time thresholds drop to zero so small serving programs are
    cached too (JAX's defaults only persist big, slow compiles)."""
    global _cache_wired
    cache_dir = global_config().get("compile.cache_dir")
    if not cache_dir:
        return False
    if _cache_wired:
        return True
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    for flag, val in (("jax_persistent_cache_min_entry_size_bytes", 0),
                      ("jax_persistent_cache_min_compile_time_secs", 0.0)):
        try:
            jax.config.update(flag, val)
        except AttributeError:  # older jax: threshold flags absent
            pass
    _cache_wired = True
    logger.info("persistent compilation cache: %s", cache_dir)
    return True


def _version_check() -> None:
    """Warn on jax/jaxlib version skew (the ``spark.analytics.zoo.
    versionCheck`` analogue): a mismatched pair is the classic source of
    silent miscompiles and ABI crashes on TPU hosts. Opt-in via the
    ``version.check`` config key."""
    if not global_config().get("version.check"):
        return
    try:
        import jaxlib
        jaxlib_version = getattr(jaxlib, "__version__", "unknown")
    except ImportError:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = "unavailable"
    if jaxlib_version != jax.__version__:
        logger.warning(
            "version.check: jax %s != jaxlib %s — upgrade the pair in "
            "lockstep (see the JAX compatibility table)",
            jax.__version__, jaxlib_version)


def _build_mesh(devices: Sequence[jax.Device],
                mesh_shape: Optional[Tuple[int, ...]] = None,
                axis_names: Optional[Tuple[str, ...]] = None) -> Mesh:
    cfg = global_config()
    if axis_names is None:
        if mesh_shape is None or len(mesh_shape) == 1:
            axis_names = (cfg.get("mesh.data_axis"),)
        else:
            axis_names = tuple(
                [cfg.get("mesh.data_axis"), cfg.get("mesh.model_axis")]
                + [f"axis{i}" for i in range(2, len(mesh_shape))])
    if mesh_shape is None:
        mesh_shape = (len(devices),)
    n = int(np.prod(mesh_shape))
    if n != len(devices):
        raise ValueError(f"mesh shape {mesh_shape} needs {n} devices, "
                         f"have {len(devices)}")
    dev_array = np.asarray(devices).reshape(mesh_shape)
    return Mesh(dev_array, axis_names)


def init_tpu_context(mesh_shape: Optional[Tuple[int, ...]] = None,
                     axis_names: Optional[Tuple[str, ...]] = None,
                     conf: Optional[Dict[str, object]] = None,
                     force_reinit: bool = False) -> ZooTpuContext:
    """Initialize (or fetch the cached) runtime context.

    Args:
      mesh_shape: optional logical mesh shape over all addressable devices,
        e.g. ``(8,)`` for pure DP or ``(4, 2)`` for DP x MP. Defaults to a 1-D
        data-parallel mesh over every device.
      axis_names: names for the mesh axes; default ``("data",)`` /
        ``("data", "model", ...)``.
      conf: programmatic config overrides applied to the global registry
        (the ``init_spark_conf`` analogue).
      force_reinit: rebuild even if a context exists (tests only).
    """
    global _context
    with _context_lock:
        if _context is not None and not force_reinit:
            if mesh_shape is not None and tuple(_context.mesh.devices.shape) != tuple(mesh_shape):
                raise ValueError(
                    f"context already initialized with mesh shape "
                    f"{tuple(_context.mesh.devices.shape)}; requested {tuple(mesh_shape)}. "
                    f"Pass force_reinit=True to rebuild.")
            if conf:
                cfg = global_config()
                for k, v in conf.items():
                    cfg.set(k, v)
                _context.config = cfg.as_dict()
            return _context
        cfg = global_config()
        if conf:
            for k, v in conf.items():
                cfg.set(k, v)
        _version_check()
        wire_compilation_cache()
        devices = jax.devices()
        mesh = _build_mesh(devices, mesh_shape, axis_names)
        ctx = ZooTpuContext(
            mesh=mesh,
            devices=devices,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            platform=devices[0].platform,
            config=cfg.as_dict(),
        )
        logger.info(
            "init_tpu_context: platform=%s devices=%d mesh=%s process=%d/%d",
            ctx.platform, ctx.num_devices, dict(zip(mesh.axis_names, mesh.devices.shape)),
            ctx.process_index, ctx.process_count)
        _context = ctx
        return ctx


def get_context() -> ZooTpuContext:
    if _context is None:
        return init_tpu_context()
    return _context


def reset_context() -> None:
    """Drop the cached context (tests only)."""
    global _context
    with _context_lock:
        _context = None
