"""Composable training triggers — the ``ZooTrigger`` algebra.

Mirrors the semantics of the reference's trigger system
(``zoo/.../common/ZooTrigger.scala:43-154``): a trigger is a predicate over the
training state, fired by the training loop to decide when to validate,
checkpoint, or stop. Triggers compose with ``And``/``Or``. The "zoo state"
extension (sub-epoch slice counters for huge epochs, ``numOfSlice``) is carried
in :class:`TrainingState`.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class TrainingState:
    """Loop state visible to triggers (the BigDL ``Table`` state equivalent)."""

    epoch: int = 1                 # 1-based current epoch
    iteration: int = 0             # global step counter
    loss: Optional[float] = None   # last train loss
    score: Optional[float] = None  # last validation score
    record_count: int = 0          # samples consumed in current epoch
    epoch_finished: bool = False   # set by the loop at epoch boundary
    #: steps the loop advanced since the previous trigger check (K under
    #: ``steps_per_dispatch=K``); interval triggers fire on BOUNDARY
    #: CROSSINGS within that window rather than exact multiples, so
    #: non-aligned intervals quantize to the group boundary instead of
    #: being skipped
    dispatch_width: int = 1
    # Zoo-state extras (sub-epoch slicing, ZooTrigger.setZooState equivalent):
    num_slices: int = 1
    slice_index: int = 0           # current sub-epoch slice
    extras: Dict[str, float] = field(default_factory=dict)


class Trigger:
    #: True when the trigger reads per-step loss — the loop only syncs the
    #: device loss back to host when some consumer needs it. Defaults to True
    #: so custom triggers are safe; built-ins that ignore loss opt out.
    requires_loss: bool = True

    def __call__(self, state: TrainingState) -> bool:
        raise NotImplementedError

    def and_(self, other: "Trigger") -> "Trigger":
        return And(self, other)

    def or_(self, other: "Trigger") -> "Trigger":
        return Or(self, other)


class EveryEpoch(Trigger):
    """Fires once per full epoch.

    Under sub-epoch slicing the loop marks ``epoch_finished`` at every slice
    boundary; like the reference (``ZooTrigger.scala:43-68``, fires when
    ``currentSlice % numSlice == 0``) this only fires when the finished slice
    closes a full epoch.
    """

    requires_loss = False

    def __call__(self, state: TrainingState) -> bool:
        if not state.epoch_finished:
            return False
        if state.num_slices <= 1:
            return True
        return state.slice_index % state.num_slices == 0


class SeveralIteration(Trigger):
    """Fires every ``interval`` iterations (``ZooTrigger.scala`` severalIteration).

    Under multi-step dispatch the counter advances ``dispatch_width`` steps
    between checks; this fires whenever an interval boundary was crossed
    inside that window (e.g. interval=100, width=8 fires at iteration 104),
    which reduces to exact ``iteration % interval == 0`` at width 1.
    """

    requires_loss = False

    def __init__(self, interval: int):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.interval = interval

    def __call__(self, state: TrainingState) -> bool:
        if state.iteration <= 0:
            return False
        width = max(1, state.dispatch_width)
        prev = max(0, state.iteration - width)
        return state.iteration // self.interval > prev // self.interval


class MaxEpoch(Trigger):
    requires_loss = False
    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, state: TrainingState) -> bool:
        return state.epoch > self.max_epoch


class MaxIteration(Trigger):
    requires_loss = False
    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, state: TrainingState) -> bool:
        return state.iteration >= self.max_iteration


class MaxScore(Trigger):
    """Stop once validation score exceeds a bar."""

    requires_loss = False

    def __init__(self, max_score: float):
        self.max_score = max_score

    def __call__(self, state: TrainingState) -> bool:
        return state.score is not None and state.score > self.max_score


class MinLoss(Trigger):
    """Stop once training loss drops below a bar."""

    requires_loss = True

    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, state: TrainingState) -> bool:
        return state.loss is not None and state.loss < self.min_loss


class TimeInterval(Trigger):
    """Fires when ``interval_s`` of wall time has elapsed since the last
    fire (monotonic clock, immune to clock steps).  The online fine-tune
    mode's snapshot cadence: unbounded streams have no meaningful epoch
    boundary, so checkpoints are paced by time, not progress.  The timer
    arms at the first check, so the first fire comes one full interval
    into training."""

    requires_loss = False

    def __init__(self, interval_s: float):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = float(interval_s)
        self._last: Optional[float] = None

    def __call__(self, state: TrainingState) -> bool:
        import time
        now = time.monotonic()
        if self._last is None:
            self._last = now
            return False
        if now - self._last >= self.interval_s:
            self._last = now
            return True
        return False


class Never(Trigger):
    """Never fires — the end trigger for unbounded online training, which
    runs until preempted (SIGTERM snapshot-and-exit) or killed."""

    requires_loss = False

    def __call__(self, state: TrainingState) -> bool:
        return False


class And(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    @property
    def requires_loss(self):
        return any(t.requires_loss for t in self.triggers)

    def __call__(self, state: TrainingState) -> bool:
        return all(t(state) for t in self.triggers)


class Or(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    @property
    def requires_loss(self):
        return any(t.requires_loss for t in self.triggers)

    def __call__(self, state: TrainingState) -> bool:
        return any(t(state) for t in self.triggers)
