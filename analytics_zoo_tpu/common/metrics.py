"""Process-global, thread- and fork-safe metrics registry.

The reference platform stops at aggregate wall-time logs (``Utils.timeIt``,
BigDL ``Metrics`` phase totals); every subsystem this reproduction has grown
since (async data plane, chaos framework, serving SLO layer) kept its own
ad-hoc counters with no shared registry and no scrapable exposition. This
module is the one telemetry plane they all report into:

- :class:`Counter`, :class:`Gauge` and :class:`Histogram` with label
  support, registered once per process under ``subsystem.noun_unit`` names
  (``scripts/check_metric_names.py`` lints the naming and uniqueness);
- every value lives in a ``multiprocessing.shared_memory`` slab of float64
  slots created BEFORE any fork (the same MAP_SHARED trick as
  ``feature/worker_pool.py``), so a counter incremented inside a forked
  transform worker is immediately visible to the parent's exposition;
- all histograms share ONE fixed log-spaced bucket layout
  (:data:`BUCKET_BOUNDS`), so p50/p99 come from the same code everywhere;
- two exposition paths: :func:`expose_text` (Prometheus text format, written
  to ``metrics.prom`` next to ``health.json`` by the serving health loop)
  and :func:`metrics_snapshot` (a structured dict —
  ``ClusterServing.health_snapshot()`` is a view of it).

Cost model: with the registry disabled (``metrics.enabled`` config flag or
:func:`set_enabled`), every record call is an attribute load and a boolean
check — well under a microsecond, safe on per-span hot paths. Enabled,
each record takes one cross-process lock round-trip (~1-2µs), which is
noise next to the ms-scale steps/batches being measured; per-record inner
loops stay uninstrumented on purpose.

Fork caveats (documented, not hidden): slot allocations and label combos
created in a forked CHILD write to the shared slab correctly, but the
parent's name→slot map only knows combos that existed before the fork —
pre-create (``.labels(...)``) any combo a child will touch, as the worker
pool instrumentation does, if the parent must expose it. ``set_enabled``
after a fork only affects the calling process.
"""
from __future__ import annotations

import atexit
import logging
import math
import os
import threading
import warnings
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = [
    "BUCKET_BOUNDS", "Counter", "Gauge", "Histogram", "Registry",
    "default_registry", "counter", "gauge", "histogram", "expose_text",
    "metrics_snapshot", "set_enabled", "enabled", "zero_all",
]

#: shared histogram bucket layout: log-spaced upper bounds, 10 per decade
#: over 1e-5..1e2 (10µs..100s when observing seconds) + one overflow bucket.
#: Every histogram uses THIS layout, so percentile math is identical
#: everywhere and cross-metric comparisons are apples to apples.
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    10.0 ** (-5 + i / 10.0) for i in range(1, 71))
_N_BUCKETS = len(BUCKET_BOUNDS) + 1  # + overflow
_HIST_SLOTS = _N_BUCKETS + 2         # buckets + sum + count

#: relative half-width of one bucket (geometric): the worst-case error of
#: a histogram percentile vs an exact one — tests assert against this
BUCKET_REL_ERROR = 10.0 ** 0.05 - 1.0


def _fmt(v: float) -> str:
    """Prometheus number formatting (compact, round-trippable)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Slab:
    """Fixed-capacity float64 value store in shared memory.

    Created before any fork so parent and children address the same
    physical pages. Slot 0 holds the allocation cursor (lock-guarded, so
    a post-fork child allocating a label combo draws slots disjoint from
    the parent's). Falls back to a process-local buffer when POSIX shared
    memory is unavailable — everything still works, minus fork visibility.
    """

    def __init__(self, capacity: int):
        import numpy as np
        self.capacity = capacity
        self._shm = None
        try:
            from multiprocessing import shared_memory
            self._shm = shared_memory.SharedMemory(
                create=True, size=capacity * 8)
            self.arr = np.ndarray((capacity,), dtype=np.float64,
                                  buffer=self._shm.buf)
        except Exception:
            warnings.warn(
                "analytics_zoo_tpu.common.metrics: shared memory "
                "unavailable; metrics are process-local (no fork "
                "visibility)", RuntimeWarning)
            self.arr = np.zeros((capacity,), dtype=np.float64)
        self.arr[:] = 0.0
        self.arr[0] = 1.0  # next free slot (slot 0 is the cursor itself)

    def alloc(self, n: int) -> int:
        """Reserve ``n`` slots; caller holds the registry lock."""
        base = int(self.arr[0])
        if base + n > self.capacity:
            raise MemoryError(
                f"metrics slab exhausted ({self.capacity} slots); raise "
                f"Registry(capacity=...)")
        self.arr[0] = float(base + n)
        return base

    def close(self) -> None:
        self.arr = None
        if self._shm is not None:
            try:
                self._shm.close()
            except Exception:
                pass
            try:
                self._shm.unlink()
            except Exception:
                pass
            self._shm = None


class _Metric:
    """Base for one exposition family: a name, a help string, optional
    label names, and one slot block per label combo (or one block total
    when unlabeled)."""

    kind = "untyped"
    slots_per_series = 1

    def __init__(self, registry: "Registry", name: str, help: str,
                 labelnames: Tuple[str, ...]):
        self._reg = registry
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._series: Dict[Tuple[str, ...], int] = {}
        if not labelnames:
            self._base = registry._alloc(self.slots_per_series)
            self._series[()] = self._base
        else:
            self._base = -1

    def labels(self, **kw: Any) -> "_Metric":
        """Bound child for one label combo (allocated on first use)."""
        if tuple(sorted(kw)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(kw))}")
        key = tuple(str(kw[k]) for k in self.labelnames)
        base = self._series.get(key)
        if base is None:
            with self._reg._plock:
                base = self._series.get(key)
                if base is None:
                    base = self._reg._alloc(self.slots_per_series)
                    self._series[key] = base
        child = object.__new__(type(self))
        child._reg = self._reg
        child.name = self.name
        child.help = self.help
        child.labelnames = ()
        child._series = {(): base}
        child._base = base
        return child

    def _require_base(self) -> int:
        if self._base < 0:
            raise ValueError(
                f"metric {self.name!r} is labeled by {self.labelnames}; "
                f"call .labels(...) first")
        return self._base

    def _values(self, base: int) -> List[float]:
        arr = self._reg._slab.arr
        return [float(x) for x in
                arr[base:base + self.slots_per_series]]


class Counter(_Metric):
    """Monotonically increasing value (float increments allowed, e.g.
    accumulated stall seconds)."""

    kind = "counter"

    def inc(self, v: float = 1.0) -> None:
        reg = self._reg
        if not reg._enabled:
            return
        base = self._require_base()
        if reg._acquire():
            try:
                reg._slab.arr[base] += v
            finally:
                reg._plock.release()

    def value(self) -> float:
        return float(self._reg._slab.arr[self._require_base()])


class Gauge(_Metric):
    """Point-in-time value (queue depth, in-flight count, claim age)."""

    kind = "gauge"

    def set(self, v: float) -> None:
        reg = self._reg
        if not reg._enabled:
            return
        # a plain 8-byte store is atomic enough for a gauge (last writer
        # wins is the semantics anyway) — no lock round-trip
        reg._slab.arr[self._require_base()] = float(v)

    def inc(self, v: float = 1.0) -> None:
        reg = self._reg
        if not reg._enabled:
            return
        base = self._require_base()
        if reg._acquire():
            try:
                reg._slab.arr[base] += v
            finally:
                reg._plock.release()

    def value(self) -> float:
        return float(self._reg._slab.arr[self._require_base()])


class Histogram(_Metric):
    """Fixed log-spaced-bucket histogram (layout :data:`BUCKET_BOUNDS`).

    Slot block layout: ``[bucket_0 .. bucket_69, overflow, sum, count]``
    (non-cumulative per-bucket counts; exposition cumulates)."""

    kind = "histogram"
    slots_per_series = _HIST_SLOTS

    def observe(self, v: float) -> None:
        reg = self._reg
        if not reg._enabled:
            return
        base = self._require_base()
        idx = bisect_left(BUCKET_BOUNDS, v) if v > 0 else 0
        arr = reg._slab.arr
        if reg._acquire():
            try:
                arr[base + idx] += 1.0
                arr[base + _N_BUCKETS] += v
                arr[base + _N_BUCKETS + 1] += 1.0
            finally:
                reg._plock.release()

    def count(self) -> int:
        return int(self._reg._slab.arr[self._require_base()
                                       + _N_BUCKETS + 1])

    def sum(self) -> float:
        return float(self._reg._slab.arr[self._require_base() + _N_BUCKETS])

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (``q`` in [0, 1]) from the bucket counts:
        the geometric midpoint of the bucket holding the target rank.
        Worst-case relative error is :data:`BUCKET_REL_ERROR`. Returns
        ``None`` on an empty histogram — callers surface ``null``, never
        a fake ``0.0`` (see docs/observability.md)."""
        base = self._require_base()
        vals = self._values(base)
        buckets, total = vals[:_N_BUCKETS], vals[_N_BUCKETS + 1]
        if total <= 0:
            return None
        target = max(1.0, math.ceil(q * total))
        cum = 0.0
        for i, c in enumerate(buckets):
            cum += c
            if cum >= target:
                if i == 0:
                    return BUCKET_BOUNDS[0] * 10 ** -0.05
                if i >= len(BUCKET_BOUNDS):
                    return BUCKET_BOUNDS[-1] * 10 ** 0.05
                return math.sqrt(BUCKET_BOUNDS[i - 1] * BUCKET_BOUNDS[i])
        return BUCKET_BOUNDS[-1] * 10 ** 0.05  # pragma: no cover


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """One slab + one family table. Use :func:`default_registry` for the
    process-global instance; fresh instances are for tests (close() them —
    each owns a shared-memory segment)."""

    _live: "Dict[int, Registry]" = {}

    def __init__(self, capacity: int = 1 << 16,
                 enabled: Optional[bool] = None):
        self._slab = _Slab(capacity)
        self._families: Dict[str, _Metric] = {}
        self._flock = threading.Lock()  # family-table registration
        self._plock = self._make_plock()  # cross-process value lock
        self._lock_warned = False
        if enabled is None:
            try:
                from .config import global_config
                enabled = bool(global_config().get("metrics.enabled", True))
            except Exception:
                enabled = True
        self._enabled = bool(enabled)
        Registry._live[id(self)] = self

    @staticmethod
    def _make_plock():
        import multiprocessing as mp
        try:
            if "fork" in mp.get_all_start_methods():
                return mp.get_context("fork").Lock()
        except Exception:
            pass
        return threading.Lock()

    def _acquire(self) -> bool:
        """Take the value lock; a lock stranded by a SIGKILLed child must
        degrade to a skipped update, never deadlock the data plane."""
        try:
            got = self._plock.acquire(timeout=0.5)
        except TypeError:  # a lock type without timeout support
            got = self._plock.acquire()
        if not got and not self._lock_warned:
            self._lock_warned = True
            logger.warning(
                "metrics value lock unavailable for 0.5s (stranded by a "
                "killed process?); dropping updates rather than blocking")
        return got

    def _alloc(self, n: int) -> int:
        return self._slab.alloc(n)

    # -- registration ---------------------------------------------------------

    def _register(self, kind: str, name: str, help: str,
                  labels: Iterable[str]) -> _Metric:
        labelnames = tuple(labels)
        with self._flock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}"
                        f"{fam.labelnames}; cannot re-register as {kind}"
                        f"{labelnames}")
                return fam
            with self._plock:
                fam = _KINDS[kind](self, name, help, labelnames)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._register("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._register("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = ()) -> Histogram:
        return self._register("histogram", name, help, labels)

    # -- toggles --------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, v: bool) -> None:
        self._enabled = bool(v)

    def zero(self) -> None:
        """Zero every allocated value slot (bench A/B resets; allocations
        and label combos survive so bound children stay valid)."""
        if self._acquire():
            try:
                cursor = self._slab.arr[0]
                self._slab.arr[1:int(cursor)] = 0.0
            finally:
                self._plock.release()

    # -- exposition -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Structured dict of every family: the machine-readable twin of
        :meth:`expose_text`. ``health_snapshot()`` is a view of this."""
        out: Dict[str, Any] = {}
        with self._flock:
            families = sorted(self._families.items())
        for name, fam in families:
            entry: Dict[str, Any] = {"type": fam.kind}
            series: Dict[str, Any] = {}
            for key, base in sorted(fam._series.items()):
                label = ",".join(f"{k}={v}" for k, v
                                 in zip(fam.labelnames, key))
                if fam.kind == "histogram":
                    vals = fam._values(base)
                    total = vals[_N_BUCKETS + 1]
                    h = Histogram.__new__(Histogram)
                    h._reg, h._base = self, base
                    h.name, h.labelnames, h._series = name, (), {(): base}
                    series[label] = {
                        "count": int(total),
                        "sum": round(vals[_N_BUCKETS], 6),
                        "p50": h.percentile(0.50),
                        "p90": h.percentile(0.90),
                        "p99": h.percentile(0.99),
                    }
                else:
                    v = float(self._slab.arr[base])
                    series[label] = int(v) if v == int(v) else v
            if fam.labelnames:
                entry["series"] = series
            else:
                entry["value" if fam.kind != "histogram"
                      else "summary"] = series.get("")
            out[name] = entry
        return out

    def expose_text(self) -> str:
        """Prometheus text exposition format, one family per block.
        ``subsystem.noun_unit`` names become ``zoo_subsystem_noun_unit``."""
        lines: List[str] = []
        with self._flock:
            families = sorted(self._families.items())
        for name, fam in families:
            pname = "zoo_" + name.replace(".", "_").replace("-", "_")
            if fam.help:
                esc = fam.help.replace("\\", "\\\\").replace("\n", "\\n")
                lines.append(f"# HELP {pname} {esc}")
            lines.append(f"# TYPE {pname} {fam.kind}")
            for key, base in sorted(fam._series.items()):
                pairs = [f'{k}="{v}"' for k, v in zip(fam.labelnames, key)]
                lbl = "{" + ",".join(pairs) + "}" if pairs else ""
                if fam.kind == "histogram":
                    vals = fam._values(base)
                    cum = 0.0
                    for i, bound in enumerate(BUCKET_BOUNDS):
                        cum += vals[i]
                        lp = pairs + [f'le="{_fmt(bound)}"']
                        lines.append(
                            f"{pname}_bucket{{{','.join(lp)}}} {_fmt(cum)}")
                    cum += vals[len(BUCKET_BOUNDS)]
                    lp = pairs + ['le="+Inf"']
                    lines.append(
                        f"{pname}_bucket{{{','.join(lp)}}} {_fmt(cum)}")
                    lines.append(f"{pname}_sum{lbl} "
                                 f"{_fmt(vals[_N_BUCKETS])}")
                    lines.append(f"{pname}_count{lbl} "
                                 f"{_fmt(vals[_N_BUCKETS + 1])}")
                else:
                    lines.append(
                        f"{pname}{lbl} "
                        f"{_fmt(float(self._slab.arr[base]))}")
        return "\n".join(lines) + ("\n" if lines else "")

    def close(self) -> None:
        Registry._live.pop(id(self), None)
        self._slab.close()


@atexit.register
def _close_live_registries() -> None:
    # interpreter exit must not leak /dev/shm segments (worker_pool pattern)
    for reg in list(Registry._live.values()):
        try:
            reg.close()
        except Exception:
            pass


# -- process-global default registry ------------------------------------------

_default: Optional[Registry] = None
_default_lock = threading.Lock()


def default_registry() -> Registry:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Registry()
    return _default


def counter(name: str, help: str = "", labels: Iterable[str] = ()) -> Counter:
    return default_registry().counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Iterable[str] = ()) -> Gauge:
    return default_registry().gauge(name, help, labels)


def histogram(name: str, help: str = "",
              labels: Iterable[str] = ()) -> Histogram:
    return default_registry().histogram(name, help, labels)


def metrics_snapshot() -> Dict[str, Any]:
    return default_registry().snapshot()


def expose_text() -> str:
    return default_registry().expose_text()


def set_enabled(v: bool) -> None:
    default_registry().set_enabled(v)


def enabled() -> bool:
    return default_registry().enabled


def zero_all() -> None:
    default_registry().zero()


def write_prom(path: str) -> None:
    """Write :func:`expose_text` to ``path`` atomically (tmp + rename) —
    the file the serving health loop drops next to ``health.json`` for a
    node-exporter textfile collector or a sidecar scraper to pick up."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(expose_text())
    os.replace(tmp, path)
