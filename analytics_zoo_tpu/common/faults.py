"""Deterministic, config-driven fault injection.

The reference platform's headline robustness feature — retry the failed
epoch from the newest checkpoint within a ``failure.retryTimes`` budget
(``Topology.scala:1180-1262``) — is only worth reproducing if something
*exercises* it. This module is the chaos layer: named injection sites are
threaded through every component that claims fault tolerance (the estimator
train step and snapshot writer, remote ``file_io`` operations, transform
worker children, the device-feed producer, the serving decode/writeback
loops), and tests arm them with deterministic schedules to prove recovery
actually recovers.

Design constraints that shaped the API:

- **Deterministic.** A chaos test must fail the same way twice. ``at=N``
  rules fire on exactly the N-th call of a site in a process; probabilistic
  rules (``p=0.2``) draw from a per-site ``random.Random`` seeded from
  ``faults.seed`` xor a stable site hash — same seed, same firing pattern.
- **Budgeted.** Every rule carries a budget (default 1) after which the
  site goes quiet, so an injected fault cannot starve a retry loop forever.
  Budgets (and fire counts) live in ``multiprocessing.Value`` shared
  memory: a site armed before a ``fork`` is shared with worker children, so
  "kill ONE worker" means one — the first child to fire consumes the
  budget and its respawned replacement finds the site exhausted.
- **Registry-complete.** ``inject()`` refuses unknown site names; the
  REGISTRY below is the single list of every site in the codebase, and
  ``scripts/check_fault_sites.py`` lints that call sites and registry
  entries stay in bijection (and that every site is exercised by a test).
- **Free when idle.** With no rules armed, ``inject()`` is a dict lookup
  and a couple of ``is None`` checks — safe on per-step and per-batch hot
  paths (it is deliberately NOT placed on per-record hot loops except in
  worker children, which are already process-parallel).

Two site kinds:

- ``raise`` sites: a firing ``inject()`` raises :class:`FaultInjected`
  (an ``OSError`` subclass, so transient-IO retry layers treat it as
  retryable) — models a step failure, a flaky RPC, a torn write.
- ``flag`` sites: a firing ``inject()`` returns ``True`` and the call
  site performs the action itself (SIGKILL a worker, tear a published
  snapshot, request preemption) — models faults that are not exceptions.

Config: ``faults.plan`` is a comma-separated schedule string, e.g.
``"train.step:3,ckpt.write:1,io.remote:0.1@4"`` — ``site:N`` fires on the
N-th call, ``site:0.1`` fires with probability 0.1 per call, ``@B`` sets
the budget (default 1). ``faults.seed`` seeds the probabilistic draws.
Tests usually use the programmatic API (:func:`arm` / :func:`reset`)
instead.
"""
from __future__ import annotations

import multiprocessing
import random
import threading
import zlib
from dataclasses import dataclass
from typing import Dict, Optional

from . import metrics as _metrics

__all__ = ["FaultInjected", "Site", "REGISTRY", "inject", "arm", "reset",
           "fire_count", "armed", "describe", "tear_snapshot"]


class FaultInjected(OSError):
    """Raised by a firing ``raise``-kind injection site. Subclasses
    ``OSError`` on purpose: layers that retry transient IO must treat an
    injected fault exactly like a real flaky backend."""

    def __init__(self, site: str, call: int):
        super().__init__(f"injected fault at site {site!r} (call #{call})")
        self.site = site
        self.call = call


@dataclass(frozen=True)
class Site:
    description: str
    kind: str = "raise"  # "raise" | "flag"


#: Every injection site in the codebase. Adding a ``faults.inject("x")``
#: call without a row here fails at the call site (unknown site) AND in
#: ``scripts/check_fault_sites.py``; a stale row with no call site fails
#: the lint too.
REGISTRY: Dict[str, Site] = {
    "train.step": Site(
        "estimator train loop, once per dispatched step — models a chip/"
        "tunnel failure surfacing as a step exception (elastic retry)"),
    "train.preempt": Site(
        "estimator train loop — simulates SIGTERM preemption notice "
        "(fence writer, final snapshot, resumable marker)", kind="flag"),
    "ckpt.write": Site(
        "snapshot writer, before serialize+publish — models a write "
        "failure/crash before the atomic publish"),
    "ckpt.corrupt": Site(
        "snapshot writer, after publish — tears the just-published "
        "snapshot (checksum-manifest fallback must skip it)", kind="flag"),
    "io.remote": Site(
        "every remote file_io operation, before dispatch — models a "
        "flaky object store (retry-with-backoff absorbs it)"),
    "worker.task": Site(
        "transform worker child, before applying the chain to a task — "
        "models a transient per-task failure (task retry budget)"),
    "worker.kill": Site(
        "transform worker child — SIGKILLs itself mid-batch (pool "
        "self-healing respawns and resubmits)", kind="flag"),
    "xshard.task": Site(
        "xshard ETL worker child, before running a task body — models a "
        "transient per-task failure (task retry budget)"),
    "xshard.kill": Site(
        "xshard ETL worker child — SIGKILLs itself mid-task (pool "
        "self-healing respawns and resubmits)", kind="flag"),
    "feed.produce": Site(
        "device-feed producer thread, once per host batch — models a "
        "data-plane crash mid-epoch (surfaces in the consumer)"),
    "serving.decode": Site(
        "serving record decode, once per record — an undecodable/faulty "
        "record must become an error result, not kill the loop"),
    "serving.writeback": Site(
        "serving result writeback, once per batch — a failed writeback "
        "must error its batch and keep the server draining"),
    "serving.claim": Site(
        "serving claim stage, once per claim attempt — a flaky queue "
        "backend must be retried and absorbed, never kill the serve loop"),
    "serving.predict": Site(
        "serving batch dispatch, once per batch — a failed predict must "
        "post error results for ITS batch and keep the server serving"),
    "serving.reload": Site(
        "hot model reload, once per reload attempt — a failed reload "
        "must roll back to the serving model with zero dropped requests"),
    "serving.decode_step": Site(
        "generative scheduler, once per fused decode step — a failed step "
        "must error every active stream (their one terminal result) and "
        "keep the scheduler serving new requests"),
    "serving.page_alloc": Site(
        "paged KV allocator, at stream join — simulates pool exhaustion; "
        "the request must be SHED with a terminal page-shed error while "
        "every resident stream keeps decoding (no crash, no stall)",
        kind="flag"),
    "fleet.route": Site(
        "fleet router placement, once per routed request — a failed "
        "placement pass must park the request in the router backlog and "
        "retry it next pass (never lost, never double-enqueued)"),
    "fleet.breaker": Site(
        "fleet router health refresh, once per instance — a firing "
        "force-opens that instance's circuit breaker (arm with budget=N "
        "to trip the first N instances refreshed); the router must stop "
        "placing on it, half-open probe it after the cooldown, and close "
        "the breaker on a clean probe", kind="flag"),
    "cluster.heartbeat": Site(
        "worker lease heartbeat thread, once per beat — a firing makes "
        "the worker STOP heartbeating (a hung host: process alive, lease "
        "frozen); the supervisor's monotonic lease-age detector must "
        "declare it dead and restart the pod generation", kind="flag"),
    "cluster.worker_restart": Site(
        "elastic supervisor, before respawning a pod generation — models "
        "a respawn that itself fails (scheduler refusal, image pull); "
        "the supervisor must back off and retry within its budget"),
    "fleet.scale_actuate": Site(
        "fleet supervisor actuation step, once per spawn/drain decision "
        "— a failed actuation must leave the fleet consistent and be "
        "retried on the next cadence tick, never half-spawn"),
    "online.promote": Site(
        "trainer→server promotion, once per instance before its reload "
        "(canary is the 1st) — a rollout that dies at any instance must "
        "roll every already-promoted instance back to the prior "
        "model_version with zero dropped requests"),
}


#: per-site chaos telemetry (fork-safe shared-memory slots: a site fired
#: inside a worker child shows up in the parent's exposition)
_M_ARMED = _metrics.counter(
    "fault.armed_total", "Fault-injection rules armed, by site.",
    labels=("site",))
_M_FIRED = _metrics.counter(
    "fault.fired_total", "Fault-injection firings, by site.",
    labels=("site",))


def _emit_fired_event(site: str) -> None:
    """Mirror a fault firing into the ops-plane event log so chaos
    injections interleave with the transitions they caused on incident
    timelines. Lazy import: ``..ops`` pulls the jax-heavy kernel package,
    and faults must stay importable everywhere."""
    try:
        from ..ops import events as ops_events
        ops_events.event_type(
            "fault.fired",
            "A fault-injection site fired (site).").emit(site=site)
    except Exception:
        pass  # chaos telemetry must never break the injected path


class _Rule:
    """One armed schedule for one site. Budget and fire counters live in
    shared memory so fork-inherited copies (worker children) coordinate
    with the parent."""

    def __init__(self, site: str, at: Optional[int], p: Optional[float],
                 budget: int, seed: int):
        if (at is None) == (p is None):
            raise ValueError(
                f"faults.arm({site!r}): exactly one of at=/p= is required")
        if at is not None and at < 1:
            raise ValueError(f"faults.arm({site!r}): at= is 1-based")
        if p is not None and not 0.0 < p <= 1.0:
            raise ValueError(f"faults.arm({site!r}): p must be in (0, 1]")
        self.site = site
        self.at = at
        self.p = p
        # per-site deterministic stream independent of arm() order
        self.rng = random.Random(seed ^ zlib.crc32(site.encode()))
        self.budget = multiprocessing.Value("i", int(budget))
        self.fired = multiprocessing.Value("i", 0)
        self.calls = 0  # per-process (fork children count independently)

    def should_fire(self) -> bool:
        self.calls += 1
        if self.at is not None:
            if self.calls != self.at:
                return False
        elif self.rng.random() >= self.p:
            return False
        with self.budget.get_lock():
            if self.budget.value <= 0:
                return False
            self.budget.value -= 1
        with self.fired.get_lock():
            self.fired.value += 1
        _M_FIRED.labels(site=self.site).inc()
        _emit_fired_event(self.site)
        return True


_lock = threading.Lock()
_rules: Dict[str, _Rule] = {}
_plan_cache: Optional[str] = None  # last faults.plan string applied


def _parse_spec(site: str, spec: str, seed: int) -> _Rule:
    budget = 1
    if "@" in spec:
        spec, b = spec.split("@", 1)
        budget = int(b)
    if "." in spec or "e" in spec.lower():
        return _Rule(site, at=None, p=float(spec), budget=budget, seed=seed)
    return _Rule(site, at=int(spec), p=None, budget=budget, seed=seed)


def _sync_plan() -> None:
    """Apply the ``faults.plan`` config string if it changed. Programmatic
    ``arm()`` calls layer on top (and ``reset()`` clears both)."""
    global _plan_cache
    try:
        from .config import global_config
        cfg = global_config()
        plan = str(cfg.get("faults.plan") or "")
        seed = int(cfg.get("faults.seed") or 0)
    except Exception:
        return  # config layer unavailable (early import): nothing to apply
    if plan == _plan_cache:
        return
    with _lock:
        if plan == _plan_cache:
            return
        for entry in filter(None, (e.strip() for e in plan.split(","))):
            site, _, spec = entry.partition(":")
            if site not in REGISTRY:
                raise ValueError(
                    f"faults.plan names unknown site {site!r}; registered "
                    f"sites: {sorted(REGISTRY)}")
            if not spec:
                raise ValueError(f"faults.plan entry {entry!r} needs a "
                                 f"'site:spec' form")
            if site not in _rules:
                _rules[site] = _parse_spec(site, spec, seed)
                _M_ARMED.labels(site=site).inc()
                _M_FIRED.labels(site=site)  # pre-fork slot for children
        _plan_cache = plan


def arm(site: str, at: Optional[int] = None, p: Optional[float] = None,
        budget: int = 1, seed: int = 0) -> None:
    """Programmatically arm ``site``: fire on call ``at`` (1-based) or with
    per-call probability ``p``, at most ``budget`` times (shared across
    forked children)."""
    if site not in REGISTRY:
        raise ValueError(f"unknown fault site {site!r}; registered sites: "
                         f"{sorted(REGISTRY)}")
    with _lock:
        _rules[site] = _Rule(site, at=at, p=p, budget=budget, seed=seed)
    _M_ARMED.labels(site=site).inc()
    # allocate the fired-counter slot NOW, before any fork: a child firing
    # this site writes to a slot the parent's exposition already knows
    _M_FIRED.labels(site=site)


def reset() -> None:
    """Disarm every site and forget the applied plan (test teardown)."""
    global _plan_cache
    with _lock:
        _rules.clear()
        _plan_cache = None


def inject(site: str) -> bool:
    """The injection point. Returns ``False`` when the site does not fire.
    When it fires: ``raise``-kind sites raise :class:`FaultInjected`;
    ``flag``-kind sites return ``True`` and the caller performs the fault
    action itself."""
    reg = REGISTRY.get(site)
    if reg is None:
        raise ValueError(f"unknown fault site {site!r}; register it in "
                         f"analytics_zoo_tpu/common/faults.py")
    _sync_plan()
    rule = _rules.get(site)
    if rule is None or not rule.should_fire():
        return False
    if reg.kind == "flag":
        return True
    raise FaultInjected(site, rule.calls)


def fire_count(site: str) -> int:
    """How many times ``site`` fired (shared across forked children)."""
    rule = _rules.get(site)
    return int(rule.fired.value) if rule is not None else 0


def armed(site: str) -> bool:
    return site in _rules


def describe() -> Dict[str, str]:
    """Site registry as ``{name: 'kind: description'}`` (docs/CLI)."""
    return {name: f"{s.kind}: {s.description}"
            for name, s in sorted(REGISTRY.items())}


def tear_snapshot(path: str) -> None:
    """Chaos helper for the ``ckpt.corrupt`` flag site: corrupt the
    published snapshot at ``path`` by bit-flipping the largest data file
    (metadata/manifest files are left alone so the tear is only caught by
    checksum verification, not by a trivial parse error)."""
    from . import file_io  # lazy: file_io imports this module

    def walk(p):
        for name in file_io.listdir(p):
            child = file_io.join(p, name)
            if file_io.isdir(child):
                yield from walk(child)
            else:
                yield child
    candidates = []
    for f in walk(path):
        base = f.rsplit("/", 1)[-1]
        if base.endswith((".json", ".txt")) or base.startswith("manifest"):
            continue
        with file_io.fopen(f, "rb") as fh:
            candidates.append((len(fh.read()), f))
    if not candidates:
        raise RuntimeError(f"no data file to tear in snapshot {path!r}")
    _, victim = max(candidates)
    with file_io.fopen(victim, "rb") as fh:
        data = bytearray(fh.read())
    mid = len(data) // 2
    data[mid] ^= 0xFF
    with file_io.fopen(victim, "wb") as fh:
        fh.write(bytes(data))
