"""Serialization policy for pod spools (search trials, xshard jobs).

cloudpickle serializes ``__main__``-defined functions and closures — the
ergonomics Ray gives remote functions — and writes standard pickle wire,
so workers deserialize with stdlib ``pickle``. Plain pickle is the
fallback (module-level functions only; ``HAVE_CLOUDPICKLE`` tells error
messages which contract is active). cloudpickle is a declared dependency
in pyproject.toml; the fallback covers exotic minimal installs.
"""
try:
    import cloudpickle as pickler  # noqa: F401
    HAVE_CLOUDPICKLE = True
except ImportError:  # pragma: no cover - declared dependency
    import pickle as pickler  # noqa: F401
    HAVE_CLOUDPICKLE = False


def capability_note() -> str:
    return ("cloudpickle covers __main__ functions and closures"
            if HAVE_CLOUDPICKLE else
            "plain-pickle fallback active (cloudpickle not installed): "
            "only module-level functions serialize")
