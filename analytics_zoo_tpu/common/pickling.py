"""Serialization policy for pod spools (search trials, xshard jobs).

cloudpickle serializes ``__main__``-defined functions and closures — the
ergonomics Ray gives remote functions — and writes standard pickle wire,
so workers deserialize with stdlib ``pickle``. Plain pickle is the
fallback (module-level functions only). Declared as a real dependency in
pyproject.toml; the fallback covers exotic minimal installs.
"""
try:
    import cloudpickle as pickler  # noqa: F401
except ImportError:  # pragma: no cover - declared dependency
    import pickle as pickler  # noqa: F401
