from .config import Config, global_config  # noqa: F401
from .context import ZooTpuContext, get_context, init_tpu_context, reset_context  # noqa: F401
from . import triggers  # noqa: F401
from .utils import time_it, timers, tree_num_params, tree_size_bytes  # noqa: F401
