"""Layered configuration registry.

The reference scatters configuration across six mechanisms (packaged
``spark-analytics-zoo.conf`` defaults, SparkConf keys, MKL env vars, Java system
properties, per-service YAML, build-info properties — see
``pyzoo/zoo/common/nncontext.py:148-200`` and ``zoo/.../common/NNContext.scala:35-78``
in the reference). This module centralizes the same capability into a single
layered registry: registered defaults < config file < environment variables <
programmatic overrides.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional
from . import file_io

_ENV_PREFIX = "ZOO_TPU_"


@dataclass
class _Flag:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str = ""


def _parse_bool(s: str) -> bool:
    return str(s).strip().lower() in ("1", "true", "yes", "on")


class Config:
    """A single process-wide layered flag registry.

    Precedence (lowest to highest):
      1. registered defaults (``register``)
      2. values loaded from a JSON config file (``load_file``)
      3. environment variables ``ZOO_TPU_<UPPER_NAME>``
      4. programmatic ``set`` overrides
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._flags: Dict[str, _Flag] = {}
        self._file_values: Dict[str, Any] = {}
        self._overrides: Dict[str, Any] = {}

    def register(self, name: str, default: Any, help: str = "",
                 parser: Optional[Callable[[str], Any]] = None) -> None:
        with self._lock:
            if parser is None:
                if isinstance(default, bool):
                    parser = _parse_bool
                elif isinstance(default, int):
                    parser = int
                elif isinstance(default, float):
                    parser = float
                else:
                    parser = str
            self._flags[name] = _Flag(name, default, parser, help)

    def load_file(self, path: str) -> None:
        with file_io.fopen(path) as f:
            values = json.load(f)
        with self._lock:
            self._file_values.update(values)

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            self._overrides[name] = value

    def unset(self, name: str) -> None:
        with self._lock:
            self._overrides.pop(name, None)

    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            flag = self._flags.get(name)
            if name in self._overrides:
                return self._overrides[name]
            env_key = _ENV_PREFIX + name.upper().replace(".", "_").replace("-", "_")
            if env_key in os.environ:
                raw = os.environ[env_key]
                return flag.parser(raw) if flag else raw
            if name in self._file_values:
                return self._file_values[name]
            if flag is not None:
                return flag.default
            return default

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            out = {name: self.get(name) for name in self._flags}
            for name in self._file_values:
                out.setdefault(name, self.get(name))
            for name in self._overrides:
                out[name] = self._overrides[name]
            return out


_global_config = Config()


def global_config() -> Config:
    return _global_config


# Core defaults (mirrors the knobs the reference exposes via SparkConf / sysprops).
_global_config.register("failure.retry_times", 5,
                        "Max training retries from checkpoint within a retry window "
                        "(reference: bigdl.failure.retryTimes).")
_global_config.register("failure.retry_interval_s", 120.0,
                        "Window seconds for retry budget reset "
                        "(reference: bigdl.failure.retryTimeInterval).")
_global_config.register("failure.io_retries", 3,
                        "Retries for transient remote file_io failures "
                        "(exponential backoff; local paths never retry).")
_global_config.register("failure.io_backoff_s", 0.05,
                        "Base backoff seconds for remote IO retries "
                        "(doubles per attempt).")
_global_config.register("checkpoint.keep", 5,
                        "Snapshots retained per checkpoint dir (older ones "
                        "pruned after each successful write; >= 2 keeps a "
                        "fallback candidate for torn-newest recovery; "
                        "0 = unlimited).")
_global_config.register("checkpoint.verify", True,
                        "Verify the per-snapshot checksum manifest on "
                        "restore; a mismatch raises CheckpointCorruptError "
                        "and elastic restores fall back to the next-older "
                        "valid snapshot.")
_global_config.register("faults.plan", "",
                        "Fault-injection schedule: 'site:N' fires on the "
                        "N-th call, 'site:0.1' with probability 0.1, "
                        "'@B' suffix sets the budget (default 1); "
                        "comma-separated. '' = injection disabled.")
_global_config.register("faults.seed", 0,
                        "Seed for probabilistic fault-injection draws "
                        "(per-site streams are derived deterministically).")
_global_config.register("data.task_retries", 0,
                        "Times a failed transform-worker task is retried "
                        "before TransformWorkerError surfaces (transient "
                        "per-task faults: flaky decode/remote reads).")
_global_config.register("data.worker_respawns", 2,
                        "Respawn budget for transform workers that die "
                        "mid-task (SIGKILL/OOM): the pool forks a "
                        "replacement and resubmits the lost task; once "
                        "exhausted the consumer gets TransformWorkerError "
                        "promptly instead of hanging.")
_global_config.register("version.check", False,
                        "Warn on jax/jaxlib version skew at context init "
                        "(reference: spark.analytics.zoo.versionCheck).")
_global_config.register("data.prefetch", 2, "Device-feed prefetch depth.")
_global_config.register("data.num_workers", 0,
                        "Default worker count for FeatureSet transforms "
                        "(0 = serial loop; >1 enables the parallel tiers).")
_global_config.register("data.transform_mode", "auto",
                        "Per-record transform engine: auto|mp|thread|loop. "
                        "'auto' picks forked shared-memory workers (mp) "
                        "when num_workers > 1 — the only tier that beats "
                        "the GIL for pure-Python transforms — falling back "
                        "to a thread pool where fork is unavailable.")
_global_config.register("data.shm_slots", 4,
                        "Shared-memory batch slabs per transform worker "
                        "pool — the mp data plane's pipeline depth. A "
                        "yielded zero-copy batch view stays valid until "
                        "shm_slots-1 further batches are drawn; keep this "
                        "above data.prefetch + 2.")
_global_config.register("data.cache_dir", "",
                        "Directory for one-shot lazy-transform memmap "
                        "replay caches ('' = a fresh temp dir per set).")
_global_config.register("data.staging_slots", 0,
                        "Train-iterator staging ring depth for zero-alloc "
                        "batch gathers (np.take(..., out=...) into reused "
                        "buffers). 0 = fresh arrays per batch (safe "
                        "default: a yielded batch is overwritten after "
                        "staging_slots further batches, which breaks "
                        "consumers that buffer batches or alias host "
                        "memory into device arrays).")
_global_config.register("eval.async", True,
                        "Pipeline evaluate()/predict() through the "
                        "DeviceFeed with on-device accumulation (one host "
                        "sync per pass). False falls back to the "
                        "synchronous per-batch loops (parity reference / "
                        "A-B benchmarking).")
_global_config.register("eval.predict_window", 2,
                        "Max in-flight predict dispatches before results "
                        "are fetched behind the dispatch frontier.")
_global_config.register("compile.cache_dir", "",
                        "Directory for JAX's persistent compilation cache "
                        "('' = disabled). Warm processes skip XLA "
                        "recompiles of programs compiled by ANY earlier "
                        "process pointed at the same dir.")
_global_config.register("metrics.enabled", True,
                        "Record into the process-global metrics registry "
                        "(common/metrics.py). False turns every counter/"
                        "gauge/histogram record into a sub-microsecond "
                        "no-op (the bench obs_overhead A/B baseline); "
                        "serving health counters go dark too.")
_global_config.register("mesh.data_axis", "data", "Default data-parallel mesh axis name.")
_global_config.register("mesh.model_axis", "model", "Default model-parallel mesh axis name.")
_global_config.register("rng.impl", "",
                        "JAX PRNG implementation for estimator rng streams "
                        "('' = default threefry; 'rbg'/'unsafe_rbg' use the "
                        "TPU hardware RNG — faster bit generation, streams "
                        "differ from threefry's).")
_global_config.register("profile.enabled", False,
                        "Step-phase attribution profiler (common/profiler."
                        "py): decompose train/eval/serving steps into "
                        "host_input/dispatch/execute/fetch/compile phases "
                        "with MFU and roofline gauges. Off = sub-microsecond "
                        "no-ops; on, the train loop fences each step "
                        "(block_until_ready) to separate execute from "
                        "dispatch, trading pipelining for attribution.")
_global_config.register("profile.capture_dir", "",
                        "Output directory for jax.profiler capture windows "
                        "('' disables all captures, armed or not).")
_global_config.register("profile.capture_steps", 0,
                        "Arm one jax.profiler capture for this many profiled "
                        "steps at the first step boundary (0 = not armed).")
_global_config.register("profile.capture_on_breach", False,
                        "Arm a time-bounded jax.profiler capture on the "
                        "first serving SLO breach (shed or expired) of the "
                        "process.")
_global_config.register("profile.capture_seconds", 2.0,
                        "Wall-seconds bound for breach-triggered capture "
                        "windows.")
_global_config.register("profile.peak_flops", 0.0,
                        "Override the device's peak bf16 FLOP/s for the MFU "
                        "gauge (0 = auto-detect from the device kind; "
                        "detection knows TPU v4/v5e/v5p/v6e).")
_global_config.register("data.validate_ids", "count",
                        "Embedding-id validation policy ('count' | 'raise' "
                        "| 'clamp'). 'clamp' keeps the historical silent "
                        "jnp.take clip; 'count' clamps but counts offenders "
                        "into embed.oob_ids_total; 'raise' raises on "
                        "out-of-range ids when the lookup runs eagerly "
                        "(test suites) and degrades to 'count' under jit.")
_global_config.register("embed.sparse_updates", True,
                        "Apply sparse row-subset optimizer updates to "
                        "sharded embedding tables (parallel/embedding.py): "
                        "only the rows touched this step are read/written, "
                        "and their optimizer state lives outside the dense "
                        "optax tree. False funnels embedding grads through "
                        "the dense optimizer like any other parameter.")
_global_config.register("data.handoff", "slab",
                        "XShard → FeatureSet lowering path: 'slab' has "
                        "ETL workers write partition rows straight into "
                        "one shared feature/label segment the FeatureSet "
                        "wraps zero-copy; 'gather' is the eager "
                        "concat-into-from_dataframe baseline (parity "
                        "reference / A-B benchmarking).")
_global_config.register("xshard.num_workers", 0,
                        "ETL worker fleet size for the XShard engine "
                        "(0 = the transform pool's default: min(4, "
                        "cpu_count)).")
_global_config.register("xshard.partitions", 0,
                        "Default partition count for XShard.from_pandas "
                        "(0 = one partition per ETL worker).")
_global_config.register("xshard.slab_mb", 64.0,
                        "Per-partition shared-memory slab budget (MB) for "
                        "XShard blocks; a partition output exceeding it "
                        "spills to a per-partition memmap file instead "
                        "(xshard.spill_bytes_total counts the bytes).")
_global_config.register("xshard.spill_dir", "",
                        "Directory for XShard spill files ('' = a fresh "
                        "temp dir per engine, removed at engine close).")
_global_config.register("embed.cold_lr", 0.01,
                        "SGD learning rate for host-DRAM cold-tier embedding "
                        "rows (applied eagerly on the host inside the "
                        "backward callback; independent of the device "
                        "optimizer).")
_global_config.register("fleet.stale_after_s", 5.0,
                        "Health-file age beyond which the fleet router "
                        "treats an instance as dead: its spool is "
                        "reclaimed and its in-flight streams fail over "
                        "from their last streamed prefix.")
_global_config.register("fleet.health_refresh_s", 0.25,
                        "Router cadence for re-reading per-instance "
                        "health files (placement gauges refresh at most "
                        "this often).")
_global_config.register("fleet.scale_headroom", 1.25,
                        "Multiplier on observed demand when computing the "
                        "fleet.desired_instances scale signal (>1 keeps "
                        "spare capacity for failover).")
_global_config.register("fleet.scale_interval_s", 0.25,
                        "Fleet supervisor actuation cadence: how often "
                        "the desired-instance signal is compared against "
                        "the live fleet and a spawn/drain is issued "
                        "(rate-limits scale thrash).")
_global_config.register("cluster.heartbeat_s", 0.5,
                        "Worker lease heartbeat cadence: every pod worker "
                        "bumps its lease seq this often so the elastic "
                        "supervisor can tell a live rank from a dead or "
                        "hung one.")
_global_config.register("cluster.lease_expiry_s", 0.0,
                        "Monotonic lease age (seconds since the supervisor "
                        "last SAW a worker's lease seq change) beyond "
                        "which the rank is declared dead and the elastic "
                        "restart path fires. 0 = 6 x cluster.heartbeat_s.")
_global_config.register("cluster.respawns", 3,
                        "Elastic restart budget: how many pod-generation "
                        "respawns the supervisor performs before giving "
                        "up and surfacing the failure (the reference's "
                        "failure.retryTimes, at cluster scope).")
_global_config.register("cluster.restart_backoff_s", 0.5,
                        "Base backoff between a detected worker death and "
                        "the respawned generation (grows linearly with "
                        "consecutive restarts so a crash-looping pod "
                        "does not spin).")
_global_config.register("ingest.buffer_records", 4096,
                        "Bounded-buffer capacity of the streaming ingest "
                        "tier (journaled-but-unconsumed plus claimed-but-"
                        "unreleased records); at capacity the ingest "
                        "thread stops claiming, so backpressure surfaces "
                        "as queue depth.")
_global_config.register("ingest.watermark_s", 0.0,
                        "Event-time watermark: a claimed record is "
                        "released to the journal once its timestamp is "
                        "at least this old (0 releases immediately); a "
                        "full buffer force-releases regardless.")
_global_config.register("ingest.poll_interval_s", 0.02,
                        "Sleep between ingest polls when the queue is "
                        "quiet, and between journal-growth checks on the "
                        "consumer side.")
_global_config.register("online.snapshot_interval_s", 30.0,
                        "Default wall-time snapshot cadence for "
                        "Estimator.train_online (unbounded streams "
                        "checkpoint by time, not epoch boundaries).")
_global_config.register("online.rollout_verify_timeout_s", 5.0,
                        "How long the promotion coordinator polls an "
                        "instance's health_snapshot for the new "
                        "model_version before declaring the rollout "
                        "failed and rolling back.")
_global_config.register("kernels.fused_embedding", True,
                        "Route embedding lookups through the fused "
                        "gather/pool/scatter kernels in "
                        "ops/embedding_kernels.py (pallas on TPU, "
                        "bit-identical lax elsewhere). Off = the "
                        "historical unfused layer ops, kept as the "
                        "bit-parity reference.")
_global_config.register("parallel.tensor_axis", "model",
                        "Mesh axis tensor-parallel (Megatron column/row) "
                        "rules shard over; transformer_tp_rules() reads "
                        "this when no axis is passed explicitly.")
_global_config.register("parallel.pipeline_stages", 0,
                        "Default pipeline-parallel stage count for "
                        "TransformerLM training (0 = pipelining off; "
                        "stages must divide n_block and equal the "
                        "'pipe' mesh axis size).")
_global_config.register("parallel.pipeline_microbatches", 4,
                        "Microbatches per global batch in the 1F1B "
                        "pipeline schedule; bubble fraction is "
                        "2(P-1)/(M+2(P-1)) so larger M amortizes the "
                        "pipeline fill/drain bubbles.")
_global_config.register("parallel.moe_capacity_factor", 1.25,
                        "Default MoE expert capacity factor (GShard "
                        "k*tokens*C/experts convention) when MoE(...) "
                        "is built without an explicit value; overflow "
                        "tokens ride the residual path and are counted "
                        "in parallel.moe_dropped_tokens_total.")
_global_config.register("parallel.moe_exchange", "auto",
                        "MoE expert dispatch: 'dense' = one-hot einsum "
                        "dispatch with GSPMD-inserted collectives; "
                        "'alltoall' = explicit fixed-size all-to-all "
                        "exchange (route -> local expert compute -> "
                        "reverse, the PR 7 embedding-exchange shape); "
                        "'auto' = alltoall when a mesh with an 'expert' "
                        "axis is active and shapes divide, dense "
                        "otherwise.")
_global_config.register("serving.brownout_high", 0.75,
                        "Pressure (max of queue-fill, slot-occupancy and "
                        "KV-page-scarcity ratios) above which the brownout "
                        "controller steps DOWN one degradation rung on the "
                        "next health tick (docs/serving.md"
                        "#overload-survival).")
_global_config.register("serving.brownout_low", 0.35,
                        "Pressure below which the brownout controller "
                        "steps back UP one rung after "
                        "serving.brownout_hold_ticks consecutive calm "
                        "health ticks.")
_global_config.register("serving.brownout_hold_ticks", 3,
                        "Consecutive calm health ticks required before the "
                        "brownout controller recovers one rung — "
                        "hysteresis so the fleet does not flap between "
                        "rungs at the threshold.")
_global_config.register("serving.brownout_token_frac", 0.25,
                        "Fraction of the configured max_new_tokens that "
                        "the deepest brownout rung caps generative "
                        "budgets to (rung 3; rung 2 caps at twice this).")
_global_config.register("client.retry_budget_ratio", 0.1,
                        "Retry-budget token-bucket earn rate: each first "
                        "attempt deposits this many tokens, each "
                        "retry/hedge spends one — retry amplification is "
                        "bounded at 1 + ratio by construction.")
_global_config.register("client.retry_attempts", 2,
                        "Max budgeted retries per logical request in "
                        "ResilientClient.call (only on terminal errors "
                        "with retriable: true).")
_global_config.register("client.retry_backoff_s", 0.05,
                        "Full-jitter retry backoff base: attempt N sleeps "
                        "uniform(0, base * 2^N) seconds before "
                        "re-enqueueing.")
_global_config.register("client.hedge_delay_ms", 200.0,
                        "Hedge trigger floor for ResilientClient."
                        "query_any: a second copy races the first after "
                        "this long (or the client's observed p99 once "
                        "enough history exists) without a terminal.")
_global_config.register("fleet.breaker_failures", 3,
                        "Consecutive settled error terminals from one "
                        "instance that trip its circuit breaker open "
                        "(docs/fleet.md#overload-survival).")
_global_config.register("fleet.breaker_latency_ratio", 4.0,
                        "An instance whose EWMA service time exceeds this "
                        "multiple of the fleet median for "
                        "fleet.breaker_failures consecutive health "
                        "refreshes trips its breaker (sick-but-not-dead "
                        "detection ahead of health-file staleness).")
_global_config.register("fleet.breaker_cooldown_s", 1.0,
                        "Seconds an open breaker holds before moving to "
                        "half-open and admitting one probe placement.")
_global_config.register("ops.enabled", False,
                        "Master switch for the ops plane (structured "
                        "event log, metric history sampler, SLO alert "
                        "engine). Off by default: a disabled plane costs "
                        "one boolean check per would-be event and "
                        "nothing per step (docs/observability.md"
                        "#ops-plane).")
_global_config.register("ops.dir", "",
                        "Shared event-spool directory for the structured "
                        "event log. Point every process of a fleet "
                        "(supervisor, servers, forked workers) at the "
                        "same path so the incident CLI reads one story; "
                        "empty = a private temp spool per creating "
                        "process.")
_global_config.register("ops.ring_events", 2048,
                        "Capacity of the per-process in-memory event ring "
                        "(EventLog.tail) — bounds memory regardless of "
                        "run length; the JSONL spool on disk is the "
                        "unbounded record.")
_global_config.register("ops.sample_interval_s", 0.25,
                        "Cadence of the metric history sampler thread "
                        "snapshotting the shm registry into per-series "
                        "rings.")
_global_config.register("ops.history_depth", 512,
                        "Samples retained per (metric, label) series in "
                        "the history rings — memory is bounded by "
                        "series x depth (at the default cadence, ~2 "
                        "minutes of history).")
_global_config.register("ops.eval_interval_s", 0.5,
                        "Cadence of the SLO alert engine's evaluation "
                        "pass over the metric history.")
_global_config.register("ops.incident_dir", "",
                        "Directory incident bundles are sealed into; "
                        "empty = an 'incidents/' subdirectory of the "
                        "event spool.")
_global_config.register("ops.incident_window_s", 60.0,
                        "Trailing window of events and metric history "
                        "frozen into each incident bundle.")
