"""Scheme-aware filesystem layer.

The reference routes every file touch through an HDFS-aware filesystem
resolver (``common/Utils.scala:175`` ``getFileSystem``, used by model
save/load, checkpoints and summary writers at ``:97,129,158``). On a TPU pod
the same role is played by object storage: data, checkpoints and served
models live in GCS. This module is the single place the framework resolves a
path:

- plain local paths (``/tmp/x``, relative paths) go straight to the posix
  builtins — zero overhead, identical semantics to before;
- ``file://`` URIs are stripped to local paths;
- any other ``scheme://`` URI (``gs://``, ``s3://``, ``memory://``, ...)
  dispatches to an `fsspec`_ filesystem for that scheme, or to a filesystem
  registered via :func:`register_filesystem` (how tests inject a fake remote
  backend without network access).

Remote caveats are explicit rather than hidden: :func:`replace` is atomic on
posix and a plain copy-rename on object stores (single-writer patterns only),
and mmap-based tiers (FeatureSet DISK cache) stay local by design — they are
caches, not durable artifacts.
"""
from __future__ import annotations

import contextlib
import logging
import os
import posixpath
import re
import shutil
import tempfile
import time
from typing import Dict, Iterator, List, Optional

from . import faults

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")

logger = logging.getLogger(__name__)


def _retryable(e: BaseException) -> bool:
    """Transient-failure policy for remote backends: network/backend
    OSErrors (and injected faults, which subclass OSError) retry;
    deterministic filesystem answers must surface immediately — retrying
    a FileNotFoundError just turns a clear error into a slow one."""
    if isinstance(e, (FileNotFoundError, FileExistsError, IsADirectoryError,
                      NotADirectoryError, PermissionError)):
        return False
    return isinstance(e, (OSError, TimeoutError))


def _remote_op(op: str, path: str, fn):
    """Run one remote-filesystem operation behind the ``io.remote`` fault
    site and the transient-failure retry policy (``failure.io_retries``
    attempts with ``failure.io_backoff_s`` exponential backoff). Local
    paths never come through here — posix calls keep posix semantics."""
    from .config import global_config
    cfg = global_config()
    retries = int(cfg.get("failure.io_retries") or 0)
    backoff = float(cfg.get("failure.io_backoff_s") or 0.0)
    attempt = 0
    while True:
        try:
            faults.inject("io.remote")
            return fn()
        except BaseException as e:
            if not _retryable(e) or attempt >= retries:
                raise
            delay = backoff * (2 ** attempt)
            logger.warning(
                "transient remote IO failure in %s(%r) (attempt %d/%d, "
                "retrying in %.2fs): %r", op, path, attempt + 1, retries,
                delay, e)
            time.sleep(delay)
            attempt += 1

# scheme -> filesystem object with the fsspec AbstractFileSystem surface
# (open/exists/isdir/ls/makedirs/rm/mv). Checked before fsspec so tests can
# shadow a scheme with a fake.
_REGISTRY: Dict[str, object] = {}


def register_filesystem(scheme: str, fs) -> None:
    """Register (or override) the filesystem serving ``scheme://`` paths."""
    _REGISTRY[scheme] = fs


def unregister_filesystem(scheme: str) -> None:
    _REGISTRY.pop(scheme, None)


def scheme_of(path: str) -> Optional[str]:
    m = _SCHEME_RE.match(str(path))
    return m.group(1) if m else None


def is_remote(path: str) -> bool:
    """True when the path needs a non-posix filesystem."""
    scheme = scheme_of(path)
    return scheme is not None and scheme != "file"


def local_path(path: str) -> str:
    """Strip a ``file://`` prefix; error on genuinely remote paths."""
    scheme = scheme_of(path)
    if scheme == "file":
        return str(path)[len("file://"):]
    if scheme is not None:
        raise ValueError(f"{path!r} is not a local path")
    return str(path)


def _fs(path: str):
    scheme = scheme_of(path)
    if scheme in _REGISTRY:
        return _REGISTRY[scheme]
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover - fsspec is baked in
        raise RuntimeError(
            f"path {path!r} needs fsspec for scheme {scheme!r}; install "
            f"fsspec or register_filesystem({scheme!r}, fs)") from e
    fs, _ = fsspec.core.url_to_fs(path)
    return fs


def join(path: str, *parts: str) -> str:
    """Scheme-preserving join (posix separators for URIs)."""
    if is_remote(path) or scheme_of(path) == "file":
        return posixpath.join(str(path), *parts)
    return os.path.join(str(path), *parts)


def fopen(path: str, mode: str = "r", encoding: Optional[str] = None,
          errors: Optional[str] = None):
    """Open a file. Returns a file-like usable directly or as a context
    manager, for both local paths and ``scheme://`` URIs. ``encoding`` /
    ``errors`` apply to text modes (same semantics as builtin ``open``)."""
    text_kw = {} if "b" in mode else {"encoding": encoding, "errors": errors}
    if not is_remote(path):
        return open(local_path(path), mode, **text_kw)
    fs = _fs(path)
    # Object stores can't append. A fresh file opened 'a' is just a write
    # (the TB writer's unique event files land here); appending to an
    # EXISTING remote object would silently truncate or raise depending on
    # the backend, so fail loudly instead of guessing.
    if "a" in mode:
        if _remote_op("exists", path, lambda: fs.exists(str(path))):
            raise ValueError(
                f"append mode is not supported on existing remote objects "
                f"({path!r}): object stores cannot append — write a new "
                f"object or read-modify-write explicitly")
        mode = mode.replace("a", "w")
    # NOTE durability contract: buffered remote writes commit at close(), not
    # at flush() — a crash before close loses the object. Writers that must
    # survive crashes (SummaryWriter event files) write unique per-open files.
    return _remote_op("open", path,
                      lambda: fs.open(str(path), mode, **text_kw))


_warned_non_exclusive: set = set()


def create_exclusive(path: str, data: bytes = b"") -> None:
    """Create ``path`` failing with FileExistsError if it already exists —
    the claim-marker primitive for multi-consumer queues. Atomic on posix
    (O_EXCL). On remote stores it uses the backend's exclusive-create mode
    when available, else an exists-check + write: atomic on stores with
    create-preconditions (GCS), best-effort elsewhere — a second consumer
    racing the same marker within the check-write window could both
    'win'; callers needing hard exactly-once remotely should use a real
    broker (RedisQueue)."""
    if not is_remote(path):
        fd = os.open(local_path(path), os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        return
    fs = _fs(path)
    try:
        f = _remote_op("create_exclusive", path,
                       lambda: fs.open(str(path), "xb"))
    except FileExistsError:
        raise
    except (ValueError, NotImplementedError):
        # "mode unsupported" signals only: a transient network/auth OSError
        # must NOT silently degrade the claim to the non-atomic path — it
        # propagates to the caller instead
        # scheme-only dedup key: a schemeless local path has no "://" and
        # split()[0] would return the WHOLE path, growing the warn-once set
        # by one entry per polled marker
        scheme = path.split("://")[0] if "://" in path else "local"
        if scheme not in _warned_non_exclusive:  # once per scheme, not
            _warned_non_exclusive.add(scheme)    # per claim-poll
            import logging
            logging.getLogger(__name__).warning(
                "backend for %s lacks exclusive-create; claim markers "
                "degrade to a non-atomic exists-check + write", scheme)
        if _remote_op("exists", path, lambda: fs.exists(str(path))):
            raise FileExistsError(path)
        f = _remote_op("open", path, lambda: fs.open(str(path), "wb"))
    with f:
        f.write(data)


def exists(path: str) -> bool:
    if not is_remote(path):
        return os.path.exists(local_path(path))
    return bool(_remote_op("exists", path,
                           lambda: _fs(path).exists(str(path))))


def isdir(path: str) -> bool:
    if not is_remote(path):
        return os.path.isdir(local_path(path))
    return bool(_remote_op("isdir", path,
                           lambda: _fs(path).isdir(str(path))))


def listdir(path: str, refresh: bool = False) -> List[str]:
    """Child names (basenames), like ``os.listdir``. ``refresh`` drops the
    filesystem's cached listing first — fsspec backends cache directory
    listings indefinitely, so a POLLING consumer (e.g. the serving file
    queue) would otherwise never see entries written by another process."""
    if not is_remote(path):
        return os.listdir(local_path(path))
    fs = _fs(path)
    if refresh:
        try:
            fs.invalidate_cache(str(path))
        except Exception:
            pass  # backend without a listing cache
    if refresh and _accepts_refresh(fs):
        names = _remote_op("listdir", path,
                           lambda: fs.ls(str(path), detail=False,
                                         refresh=True))
    else:
        names = _remote_op("listdir", path,
                           lambda: fs.ls(str(path), detail=False))
    return [posixpath.basename(str(n).rstrip("/")) for n in names]


def _accepts_refresh(fs) -> bool:
    try:
        import inspect
        return "refresh" in inspect.signature(fs.ls).parameters
    except (TypeError, ValueError):
        return False


def makedirs(path: str, exist_ok: bool = True) -> None:
    if not is_remote(path):
        os.makedirs(local_path(path), exist_ok=exist_ok)
        return
    # object stores have no real directories; best-effort for stores that do
    try:
        _remote_op("makedirs", path,
                   lambda: _fs(path).makedirs(str(path), exist_ok=exist_ok))
    except FileExistsError:
        if not exist_ok:
            raise


def remove(path: str) -> None:
    if not is_remote(path):
        os.remove(local_path(path))
        return
    _remote_op("remove", path, lambda: _fs(path).rm_file(str(path)))


def rmtree(path: str) -> None:
    if not is_remote(path):
        shutil.rmtree(local_path(path))
        return
    _remote_op("rmtree", path,
               lambda: _fs(path).rm(str(path), recursive=True))


def replace(src: str, dst: str) -> None:
    """Rename ``src`` over ``dst``. Atomic on posix (``os.replace``); on
    remote stores this is the store's ``mv`` — NOT atomic, so multi-consumer
    claim protocols must not rely on it remotely."""
    if not is_remote(src) and not is_remote(dst):
        os.replace(local_path(src), local_path(dst))
        return
    if scheme_of(src) != scheme_of(dst):
        raise ValueError(f"cross-scheme replace: {src!r} -> {dst!r}")
    fs = _fs(src)

    def mv():
        # fsspec mv() refuses to clobber on some backends; drop the target
        # first (re-running after a transient failure re-checks, so a
        # half-done rm+mv attempt resumes cleanly)
        if fs.exists(str(dst)):
            fs.rm_file(str(dst))
        fs.mv(str(src), str(dst))

    _remote_op("replace", src, mv)


def put_tree(local_dir: str, remote_dir: str) -> None:
    """Upload a local directory tree under ``remote_dir`` (contents, not the
    directory itself — mirrors ``shutil.copytree(src, dst)`` semantics)."""
    local_dir = local_path(local_dir)
    if not is_remote(remote_dir):
        shutil.copytree(local_dir, local_path(remote_dir), dirs_exist_ok=True)
        return
    fs = _fs(remote_dir)
    for root, _dirs, files in os.walk(local_dir):
        rel = os.path.relpath(root, local_dir)
        for name in files:
            dst = (join(remote_dir, name) if rel == "." else
                   join(remote_dir, rel.replace(os.sep, "/"), name))

            def upload(src_path=os.path.join(root, name), dst=dst):
                # whole-file op: a retry after a mid-copy failure restarts
                # the object from byte 0 (object stores have no partials)
                with open(src_path, "rb") as src, fs.open(dst, "wb") as out:
                    shutil.copyfileobj(src, out)

            _remote_op("put", dst, upload)


def get_tree(remote_dir: str, local_dir: str) -> None:
    """Download a remote directory tree into ``local_dir``."""
    if not is_remote(remote_dir):
        shutil.copytree(local_path(remote_dir), local_dir, dirs_exist_ok=True)
        return
    fs = _fs(remote_dir)
    # fs.find returns protocol-stripped paths; normalize the base the same
    # way the filesystem does so the relative part lines up
    strip = getattr(fs, "_strip_protocol", lambda p: p)
    base = str(strip(str(remote_dir))).rstrip("/")
    for src in _remote_op("find", remote_dir,
                          lambda: list(fs.find(str(remote_dir)))):
        src = str(src)
        rel = src[len(base):].lstrip("/")
        dst = os.path.join(local_dir, *rel.split("/"))
        os.makedirs(os.path.dirname(dst), exist_ok=True)

        def download(src=src, dst=dst):
            with fs.open(src, "rb") as f, open(dst, "wb") as out:
                shutil.copyfileobj(f, out)

        _remote_op("get", src, download)


@contextlib.contextmanager
def localized(path: str, mode: str = "r") -> Iterator[str]:
    """Yield a LOCAL path for ``path``.

    ``mode='r'``: downloads a remote file/tree to a temp location first.
    ``mode='w'``: yields a temp dir path and uploads it on exit.
    Local paths pass through untouched. This is the bridge for components
    that fundamentally need posix files (mmap, native readers, orbax).
    """
    if not is_remote(path):
        yield local_path(path)
        return
    tmp = tempfile.mkdtemp(prefix="zoo_fio_")
    try:
        if mode == "r":
            if isdir(path):
                get_tree(path, tmp)
                yield tmp
            else:
                dst = os.path.join(tmp, posixpath.basename(str(path)))

                def download():
                    with _fs(path).open(str(path), "rb") as f, \
                            open(dst, "wb") as out:
                        shutil.copyfileobj(f, out)

                _remote_op("get", path, download)
                yield dst
        elif mode == "w":
            yield tmp
            put_tree(tmp, path)
        else:
            raise ValueError(f"localized mode must be 'r' or 'w', got {mode!r}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
