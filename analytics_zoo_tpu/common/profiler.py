"""Step-phase attribution profiler: where a step's wall time goes.

PR 5's registry counts events; this layer says WHERE the time went. Each
profiled loop (estimator train/eval, the serving dispatch pipeline) is
decomposed into phases —

- ``host_input``: the consumer blocked on host-side data (feed stalls,
  claim+decode),
- ``dispatch``: tracing + enqueueing work onto the device (async),
- ``execute``: device compute, measured by an explicit
  ``block_until_ready`` fence (profiling the execute phase deliberately
  costs the loop its async pipelining — that is what attribution buys),
- ``fetch``: blocked pulling results back to host,
- ``compile``: XLA compiles (first dispatch of a fresh step fn,
  ``InferenceModel`` bucket compiles),
- ``other``: the unattributed remainder of the step wall (triggers,
  checkpoints, bookkeeping) — booked so phase sums always account for
  the whole wall (tested on a fake clock).

Everything exports through the existing planes: phase/wall histograms and
MFU/HBM/RSS gauges land in ``metrics.expose_text()`` / ``metrics.prom`` /
``metrics_snapshot()``, and every phase recorded with a ``start`` stamp is
also offered to ``utils.span_hooks`` so a live :func:`utils.trace.trace`
session draws the phases on the Perfetto timeline.

Disabled (the default: the ``profile.enabled`` config flag /
``ZOO_TPU_PROFILE_ENABLED``), every record call is an attribute load plus
a boolean check — well under 1µs, the same contract as
``metrics.set_enabled`` (asserted by ``tests/test_profiler.py``).

On-demand deep captures: :func:`arm_capture` opens a ``jax.profiler``
trace window (by step count or wall seconds), armed manually, by config
(``profile.capture_steps`` + ``profile.capture_dir``), or automatically on
the first serving SLO breach (``profile.capture_on_breach``). A broken or
absent ``jax.profiler`` degrades to a warning once — never an exception
on the hot path.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from . import metrics as _metrics
from . import utils as _utils
from .config import global_config

#: the closed phase vocabulary (the ``phase`` label of
#: ``profile.phase_seconds`` only ever takes these values)
PHASES = ("host_input", "dispatch", "execute", "fetch", "compile", "other")

_M_PHASE = _metrics.histogram(
    "profile.phase_seconds",
    "Wall time attributed to one phase of one profiled loop "
    "(host_input / dispatch / execute / fetch / compile / other).",
    labels=("loop", "phase"))
_M_WALL = _metrics.histogram(
    "profile.step_wall_seconds",
    "Whole-step wall time of a profiled loop; the per-loop phase sums "
    "account for this exactly (the remainder is booked as phase=other).",
    labels=("loop",))
_M_MFU = _metrics.gauge(
    "profile.mfu_ratio",
    "Model-FLOP utilization of the last profiled step: achieved matmul "
    "FLOP/s divided by the chip's bf16 peak (needs a known device kind "
    "or the profile.peak_flops override).", labels=("loop",))
_M_ROOF = _metrics.gauge(
    "profile.hbm_roofline_ratio",
    "Achieved HBM GB/s of the last profiled step divided by the chip's "
    "peak memory bandwidth.", labels=("loop",))
_M_ROOF_UTIL = _metrics.gauge(
    "profile.roofline_utilization_ratio",
    "Roofline utilization of the last profiled step: the LARGER of MFU "
    "and the HBM-bandwidth fraction, so bytes-bound steps (embedding "
    "gathers) report how close they run to the roofline instead of a "
    "misleading ~0 MFU.", labels=("loop",))
_M_HBM_USED = _metrics.gauge(
    "profile.hbm_used_bytes",
    "Device memory in use (jax memory_stats, sampled on the health "
    "cadence; absent on backends without memory_stats).")
_M_HBM_LIMIT = _metrics.gauge(
    "profile.hbm_limit_bytes",
    "Device memory limit (jax memory_stats, sampled with "
    "profile.hbm_used_bytes).")
_M_RSS = _metrics.gauge(
    "profile.host_rss_bytes",
    "Host resident-set size of this process, sampled on the health "
    "cadence.")
_M_CAPTURES = _metrics.counter(
    "profile.captures_total",
    "jax.profiler capture windows opened, by trigger "
    "(manual / config / breach).", labels=("trigger",))
_M_BUILD = _metrics.gauge(
    "build.info",
    "Environment identity (value is always 1; the labels carry the "
    "info): jax version, backend platform, device kind, git sha.",
    labels=("jax_version", "backend", "device_kind", "git_sha"))

# -- enablement ---------------------------------------------------------------


def _resolve_enabled() -> bool:
    try:
        return bool(global_config().get("profile.enabled"))
    except Exception:  # pragma: no cover - config bootstrap
        return False


_enabled: bool = _resolve_enabled()


def enabled() -> bool:
    """Cheap hot-path check; record calls are no-ops when False."""
    return _enabled


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)


# -- phase recording ----------------------------------------------------------

#: bound label children, cached — ``.labels()`` allocates a wrapper per
#: call, and record_phase sits on per-step paths
_children: Dict[Tuple[str, ...], Any] = {}


def _phase_child(loop: str, phase: str):
    child = _children.get(("p", loop, phase))
    if child is None:
        child = _M_PHASE.labels(loop=loop, phase=phase)
        _children[("p", loop, phase)] = child
    return child


def _loop_child(metric, tag: str, loop: str):
    child = _children.get((tag, loop))
    if child is None:
        child = metric.labels(loop=loop)
        _children[(tag, loop)] = child
    return child


def record_phase(loop: str, phase: str, seconds: float,
                 start: Optional[float] = None) -> None:
    """Attribute ``seconds`` of ``loop``'s time to ``phase``. With a
    ``start`` perf_counter stamp the span is also offered to any live
    trace session (``profile.<loop>.<phase>`` on the Perfetto timeline).
    <1µs no-op while the profiler is disabled."""
    if not _enabled:
        return
    _phase_child(loop, phase).observe(seconds)
    if start is not None and _utils.span_hooks:
        name = "profile.%s.%s" % (loop, phase)
        for hook in tuple(_utils.span_hooks):
            hook(name, start, seconds)


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _PhaseSpan:
    __slots__ = ("_sp", "_name", "_t0")

    def __init__(self, sp: "StepProfiler", name: str):
        self._sp = sp
        self._name = name

    def __enter__(self):
        self._t0 = self._sp._clock()
        return self

    def __exit__(self, *exc):
        self._sp.add(self._name, self._sp._clock() - self._t0,
                     start=self._t0)
        return False


class StepProfiler:
    """Per-step phase accounting for one loop (``train``, ``eval``, ...).

    Phases added between :meth:`step_start` and :meth:`step_end` land in
    ``profile.phase_seconds``; the step wall lands in
    ``profile.step_wall_seconds``; any unattributed remainder is booked
    as phase ``other`` so ``sum(phases) == wall`` holds exactly (the
    fake-clock contract in ``tests/test_profiler.py``). With a cost model
    (:meth:`set_cost`) each step also refreshes the per-loop MFU and HBM
    roofline gauges. Every method is a <1µs no-op while disabled.

    ``clock`` is injectable for tests; production uses
    ``time.perf_counter``.
    """

    def __init__(self, loop: str,
                 clock: Callable[[], float] = time.perf_counter):
        self.loop = loop
        self._clock = clock
        self._t0: Optional[float] = None
        self._acc: Dict[str, float] = {}
        self._flops: Optional[float] = None
        self._bytes: Optional[float] = None
        self._peak: Any = None        # lazily resolved; False = unknown
        self._hbm: Any = None

    def set_cost(self, flops_per_step: Optional[float] = None,
                 bytes_per_step: Optional[float] = None) -> None:
        """Install the XLA cost model (per dispatched step) used for the
        MFU / roofline gauges — see :func:`cost_flops` / :func:`cost_bytes`."""
        if flops_per_step is not None:
            self._flops = float(flops_per_step)
        if bytes_per_step is not None:
            self._bytes = float(bytes_per_step)

    def step_start(self) -> None:
        if not _enabled:
            return
        self._acc.clear()
        self._t0 = self._clock()

    def add(self, phase: str, seconds: float,
            start: Optional[float] = None) -> None:
        """Accumulate one timed window into this step's ``phase``."""
        if not _enabled:
            return
        self._acc[phase] = self._acc.get(phase, 0.0) + seconds
        if start is not None and _utils.span_hooks:
            name = "profile.%s.%s" % (self.loop, phase)
            for hook in tuple(_utils.span_hooks):
                hook(name, start, seconds)

    def phase(self, name: str):
        """``with sp.phase("fetch"): ...`` — times the block into ``name``."""
        if not _enabled:
            return _NULL_SPAN
        return _PhaseSpan(self, name)

    def step_end(self) -> None:
        """Commit this step: phase histograms + wall histogram + ``other``
        remainder + MFU/roofline gauges, then the capture-window tick."""
        if not _enabled or self._t0 is None:
            return
        wall = self._clock() - self._t0
        self._t0 = None
        attributed = 0.0
        for phase, secs in self._acc.items():
            _phase_child(self.loop, phase).observe(secs)
            attributed += secs
        if wall - attributed > 0:
            _phase_child(self.loop, "other").observe(wall - attributed)
        _loop_child(_M_WALL, "w", self.loop).observe(wall)
        if wall > 0:
            mfu = roof = None
            if self._flops is not None:
                peak = self._resolve_peak()
                if peak:
                    mfu = self._flops / wall / peak
                    _loop_child(_M_MFU, "m", self.loop).set(mfu)
            if self._bytes is not None:
                hbm = self._resolve_hbm()
                if hbm:
                    roof = self._bytes / wall / (hbm * 1e9)
                    _loop_child(_M_ROOF, "r", self.loop).set(roof)
            if mfu is not None or roof is not None:
                # the binding ceiling: a step is "fast" when it saturates
                # EITHER the matmul peak or the memory bandwidth
                _loop_child(_M_ROOF_UTIL, "u", self.loop).set(
                    max(mfu or 0.0, roof or 0.0))
        step_boundary()

    def _resolve_peak(self) -> Optional[float]:
        if self._peak is None:
            self._peak = device_peak_flops() or False
        return self._peak or None

    def _resolve_hbm(self) -> Optional[float]:
        if self._hbm is None:
            self._hbm = device_hbm_gbps() or False
        return self._hbm or None


# -- XLA cost analysis + device peaks (shared with bench.py) ------------------

#: bf16 peak matmul FLOP/s per chip by device kind (JAX's default matmul
#: precision on TPU uses bf16 multiplies, so this is the right denominator)
PEAK_BF16_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}

#: peak HBM bandwidth per chip, GB/s
PEAK_HBM_GBPS = {
    "TPU v5 lite": 820.0,
    "TPU v5e": 820.0,
    "TPU v4": 1228.0,
    "TPU v5p": 2765.0,
    "TPU v6e": 1640.0,
}


def cost_flops(compiled) -> Optional[float]:
    """FLOPs from an XLA cost analysis (``Lowered`` or ``Compiled`` both
    expose ``cost_analysis()``); None where the backend reports none."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca["flops"]) if ca and "flops" in ca else None
    except Exception:
        return None


def cost_bytes(compiled) -> Optional[float]:
    """HBM bytes accessed, from the same cost analysis as :func:`cost_flops`."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return (float(ca["bytes accessed"])
                if ca and "bytes accessed" in ca else None)
    except Exception:
        return None


def _device_kind() -> Optional[str]:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return None


def device_peak_flops() -> Optional[float]:
    """Peak bf16 matmul FLOP/s of device 0, or the ``profile.peak_flops``
    config override (>0), or None when the kind is unknown (CPU hosts)."""
    try:
        override = float(global_config().get("profile.peak_flops"))
    except Exception:
        override = 0.0
    if override > 0:
        return override
    kind = _device_kind()
    if kind is None:
        return None
    for key, peak in PEAK_BF16_FLOPS.items():
        if key.lower() in kind.lower():
            return peak
    return None


def device_hbm_gbps() -> Optional[float]:
    """Peak HBM bandwidth (GB/s) of device 0, or None when unknown."""
    kind = _device_kind()
    if kind is None:
        return None
    for key, gbps in PEAK_HBM_GBPS.items():
        if key.lower() in kind.lower():
            return gbps
    return None


# -- memory + build-info gauges (health cadence) ------------------------------


def _host_rss_bytes() -> Optional[float]:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        try:
            import resource
            return float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                         * 1024)
        except Exception:
            return None


def sample_memory() -> Dict[str, Optional[float]]:
    """Refresh the HBM and host-RSS gauges; called on the serving health
    cadence (and usable anywhere). Never raises: each source degrades to
    None where unavailable (CPU backends have no ``memory_stats``)."""
    out: Dict[str, Optional[float]] = {
        "hbm_used_bytes": None, "hbm_limit_bytes": None,
        "host_rss_bytes": None}
    stats = None
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
    except Exception:
        stats = None
    if stats:
        used = stats.get("bytes_in_use")
        limit = (stats.get("bytes_limit")
                 or stats.get("bytes_reservable_limit"))
        if used is not None:
            out["hbm_used_bytes"] = float(used)
            _M_HBM_USED.set(float(used))
        if limit is not None:
            out["hbm_limit_bytes"] = float(limit)
            _M_HBM_LIMIT.set(float(limit))
    rss = _host_rss_bytes()
    if rss is not None:
        out["host_rss_bytes"] = rss
        _M_RSS.set(rss)
    ensure_build_info()
    return out


def _git_sha() -> str:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    git = os.path.join(root, ".git")
    try:
        with open(os.path.join(git, "HEAD")) as f:
            head = f.read().strip()
        if head.startswith("ref:"):
            ref = head.split(None, 1)[1]
            try:
                with open(os.path.join(git, *ref.split("/"))) as f:
                    head = f.read().strip()
            except OSError:  # ref packed away by gc
                with open(os.path.join(git, "packed-refs")) as f:
                    for line in f:
                        parts = line.strip().split()
                        if len(parts) == 2 and parts[1] == ref:
                            head = parts[0]
                            break
        return head[:12] or "unknown"
    except Exception:
        return "unknown"


_build_info: Optional[Dict[str, str]] = None


def ensure_build_info() -> Dict[str, str]:
    """Stamp the ``zoo_build_info`` info-style gauge (value 1; the labels
    carry jax version / backend / device kind / git sha) so scraped
    dashboards can segment by environment. Idempotent; off-accelerator
    hosts degrade the device labels to ``unknown``."""
    global _build_info
    if _build_info is not None:
        return _build_info
    info = {"jax_version": "unknown", "backend": "unknown",
            "device_kind": "unknown", "git_sha": _git_sha()}
    try:
        import jax
        info["jax_version"] = jax.__version__
        dev = jax.devices()[0]
        info["backend"] = dev.platform
        info["device_kind"] = dev.device_kind
    except Exception:
        pass
    _M_BUILD.labels(**info).set(1)
    _build_info = info
    return info


# -- jax.profiler capture windows ---------------------------------------------


class _CaptureState:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.active = False
        self.steps_left = 0
        self.stop_at: Optional[float] = None
        self.broken = False          # jax.profiler failed: stop trying
        self.config_checked = False  # config arming consumed once
        self.breach_fired = False    # one breach capture per process


_cap = _CaptureState()


def _profiler_start(out_dir: str) -> None:  # monkeypatch point for tests
    import jax
    jax.profiler.start_trace(out_dir)


def _profiler_stop() -> None:
    import jax
    jax.profiler.stop_trace()


def arm_capture(steps: int = 0, seconds: float = 0.0,
                out_dir: Optional[str] = None,
                trigger: str = "manual") -> bool:
    """Open a ``jax.profiler`` trace window now, bounded by ``steps``
    profiled steps (closed by :func:`step_boundary`) and/or ``seconds`` of
    wall time (closed by the next boundary or health tick past the
    deadline). ``out_dir`` defaults to ``profile.capture_dir``. Returns
    True if a window opened; a failing ``jax.profiler`` warns once and
    permanently degrades to False."""
    if _cap.broken:
        return False
    if out_dir is None:
        try:
            out_dir = str(global_config().get("profile.capture_dir") or "")
        except Exception:
            out_dir = ""
    if not out_dir or (steps <= 0 and seconds <= 0):
        return False
    with _cap.lock:
        if _cap.active:
            return False
        try:
            _profiler_start(out_dir)
        except Exception:
            _cap.broken = True
            _utils.logger.warning(
                "profiler: jax.profiler capture unavailable; further "
                "capture requests are no-ops", exc_info=True)
            return False
        _cap.active = True
        _cap.steps_left = int(steps)
        _cap.stop_at = (time.perf_counter() + float(seconds)
                        if seconds > 0 else None)
    _M_CAPTURES.labels(trigger=trigger).inc()
    _utils.logger.info("profiler: capture window opened (trigger=%s "
                       "steps=%d seconds=%.1f dir=%s)",
                       trigger, steps, seconds, out_dir)
    return True


def _stop_locked() -> None:
    try:
        _profiler_stop()
    except Exception:
        _cap.broken = True
        _utils.logger.warning("profiler: stop_trace failed", exc_info=True)
    _cap.active = False
    _cap.steps_left = 0
    _cap.stop_at = None


def step_boundary() -> None:
    """One profiled step elapsed: consume config arming on the first
    boundary, count down a step-bounded window, close an elapsed
    time-bounded one. Cheap when no window is armed."""
    if not _enabled:
        return
    if not _cap.config_checked:
        _cap.config_checked = True
        try:
            steps = int(global_config().get("profile.capture_steps"))
        except Exception:
            steps = 0
        if steps > 0:
            arm_capture(steps=steps, trigger="config")
            return
    if not _cap.active:
        return
    with _cap.lock:
        if not _cap.active:
            return
        if _cap.steps_left > 0:
            _cap.steps_left -= 1
            if _cap.steps_left == 0:
                _stop_locked()
                return
        if _cap.stop_at is not None and time.perf_counter() >= _cap.stop_at:
            _stop_locked()


def maybe_stop_capture() -> None:
    """Health-cadence tick: close a time-bounded window whose deadline
    passed (serving sees no step boundaries on a quiet queue)."""
    if not _cap.active:
        return
    with _cap.lock:
        if (_cap.active and _cap.stop_at is not None
                and time.perf_counter() >= _cap.stop_at):
            _stop_locked()


def capture_active() -> bool:
    return _cap.active


def on_slo_breach(kind: str) -> None:
    """Serving calls this when requests shed or miss deadlines. With
    ``profile.capture_on_breach`` set, the FIRST breach in the process
    opens one time-bounded capture (``profile.capture_seconds``) so the
    trace shows the overload as it happens."""
    if _cap.breach_fired or _cap.broken:
        return
    try:
        cfg = global_config()
        if not cfg.get("profile.capture_on_breach"):
            return
        seconds = float(cfg.get("profile.capture_seconds"))
    except Exception:
        return
    _cap.breach_fired = True
    _utils.logger.warning("profiler: SLO breach (%s) — arming capture "
                          "window", kind)
    arm_capture(seconds=seconds, trigger="breach")


def _reset_capture_for_tests() -> None:
    with _cap.lock:
        if _cap.active:
            _stop_locked()
    _cap.broken = False
    _cap.config_checked = False
    _cap.breach_fired = False
