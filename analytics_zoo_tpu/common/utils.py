"""Small runtime utilities: micro-profiler, tree helpers, file IO.

``time_it`` mirrors the reference's ``Utils.timeIt`` wall-time micro-profiler
(``zoo/.../common/Utils.scala``) used around every hot call
(``tfpark/GraphRunner.scala:112,132``); here it also aggregates per-name stats so
the Estimator can report phase timings the way BigDL's ``Metrics`` does.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Tuple

import jax
import numpy as np

logger = logging.getLogger("analytics_zoo_tpu")


class _TimerRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self._totals[name] += seconds
            self._counts[name] += 1

    def stats(self) -> Dict[str, Tuple[float, int]]:
        with self._lock:
            return {k: (self._totals[k], self._counts[k]) for k in self._totals}

    def reset(self) -> None:
        with self._lock:
            self._totals.clear()
            self._counts.clear()


timers = _TimerRegistry()

# span observers (utils/trace.py chrome-trace recorder registers here);
# called as fn(name, start_perf_counter, elapsed_seconds)
span_hooks: list = []


@contextlib.contextmanager
def time_it(name: str, log: bool = False) -> Iterator[None]:
    start = time.perf_counter()
    try:
        yield
    finally:
        elapsed = time.perf_counter() - start
        timers.add(name, elapsed)
        # iterate a SNAPSHOT: a hook registered/removed concurrently from
        # another thread must not break this in-flight span exit (list
        # mutation during iteration raises / skips entries)
        for hook in tuple(span_hooks):
            hook(name, start, elapsed)
        if log:
            logger.info("%s: %.3fms", name, elapsed * 1e3)


def wall_clock() -> float:
    """Epoch seconds for stamps that CROSS process boundaries: queue lease
    stamps, request ``enqueue_t``, ``health.json``, client-supplied
    deadlines. Wall-clock is the only clock two hosts share, so these
    genuinely cannot use ``time.monotonic()`` — every other interval or
    deadline in-process must. Routing all cross-process stamps through
    this one audited call keeps the intent explicit and grep-able (the
    ``monotonic-clock`` zoolint pass bans bare ``time.time()``)."""
    return time.time()  # zoolint: disable=monotonic-clock — the one audited wall-clock read; cross-process stamps need epoch time


def tree_size_bytes(tree) -> int:
    """Total byte size of all array leaves in a pytree."""
    leaves = jax.tree_util.tree_leaves(tree)
    return int(sum(np.prod(l.shape) * l.dtype.itemsize
                   for l in leaves if hasattr(l, "shape")))


def tree_num_params(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(sum(np.prod(l.shape) for l in leaves if hasattr(l, "shape")))


