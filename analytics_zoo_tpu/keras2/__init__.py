"""Keras-2-style API (reference ``zoo/.../pipeline/api/keras2/`` +
``pyzoo/zoo/pipeline/api/keras2/``): the SAME engine and layers as
:mod:`analytics_zoo_tpu.keras`, exposed under Keras-2 argument names
(``units``/``filters``/``kernel_size``/``strides``/``padding``/
``use_bias``/``rate``...). Models built from either namespace mix freely —
these classes subclass the keras-1 layers, so params/checkpoints/graphs are
identical."""
from ..keras.engine import Input, Model, Sequential  # noqa: F401
from . import layers  # noqa: F401
