"""Keras-2 argument-name adapters over the native keras-1 layer classes
(reference keras2 layers: ``keras2/layers/Dense.scala:30``,
``pyzoo/zoo/pipeline/api/keras2/layers/core.py:55`` etc.).

Each class subclasses its keras-1 twin and only translates constructor
vocabulary (units→output_dim, strides→subsample, padding→border_mode...),
so graphs, params and checkpoints are interchangeable between the two APIs.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

from ..keras import layers as k1


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def _check_padding(cls_name: str, padding: str) -> None:
    if padding not in ("valid", "same"):
        raise ValueError(f"{cls_name}: unsupported padding {padding!r} "
                         f"(only 'valid'/'same'; 'causal' is not available)")


def _reject_unknown(cls_name: str, kwargs) -> None:
    """Unsupported Keras-2 arguments fail loudly — silently dropping e.g.
    ``dilation_rate`` or ``kernel_regularizer`` would build a DIFFERENT
    model than the user asked for."""
    if kwargs:
        raise TypeError(f"{cls_name}: unsupported keras2 argument(s) "
                        f"{sorted(kwargs)}")


class Dense(k1.Dense):
    def __init__(self, units: int, activation=None,
                 kernel_initializer="glorot_uniform", use_bias: bool = True,
                 name: Optional[str] = None, **kwargs):
        _reject_unknown("Dense", kwargs)
        super().__init__(units, activation=activation,
                         init=kernel_initializer, bias=use_bias, name=name)


class Dropout(k1.Dropout):
    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(rate, name=name)


class Activation(k1.Activation):
    pass


class Flatten(k1.Flatten):
    pass


class Softmax(k1.Softmax):
    pass


class Conv1D(k1.Convolution1D):
    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "valid", activation=None,
                 kernel_initializer="glorot_uniform", use_bias: bool = True,
                 name: Optional[str] = None, **kwargs):
        _reject_unknown("Conv1D", kwargs)
        _check_padding("Conv1D", padding)
        super().__init__(filters, kernel_size, activation=activation,
                         subsample_length=strides, border_mode=padding,
                         init=kernel_initializer, bias=use_bias, name=name)


class Conv2D(k1.Convolution2D):
    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding: str = "valid", activation=None,
                 dilation_rate=(1, 1), groups: int = 1,
                 kernel_initializer="glorot_uniform", use_bias: bool = True,
                 name: Optional[str] = None, **kwargs):
        _reject_unknown("Conv2D", kwargs)
        _check_padding("Conv2D", padding)
        kh, kw = _pair(kernel_size)
        super().__init__(filters, kh, kw, activation=activation,
                         subsample=_pair(strides), border_mode=padding,
                         init=kernel_initializer, bias=use_bias,
                         dilation=_pair(dilation_rate), groups=groups,
                         name=name)


class Conv3D(k1.Convolution3D):
    def __init__(self, filters: int, kernel_size, strides=(1, 1, 1),
                 padding: str = "valid", activation=None,
                 kernel_initializer="glorot_uniform", use_bias: bool = True,
                 name: Optional[str] = None, **kwargs):
        _reject_unknown("Conv3D", kwargs)
        _check_padding("Conv3D", padding)
        kd, kh, kw = (kernel_size if isinstance(kernel_size, (tuple, list))
                      else (kernel_size,) * 3)
        sd, sh, sw = (strides if isinstance(strides, (tuple, list))
                      else (strides,) * 3)
        super().__init__(filters, kd, kh, kw, activation=activation,
                         subsample=(sd, sh, sw), border_mode=padding,
                         init=kernel_initializer, bias=use_bias, name=name)


class MaxPooling1D(k1.MaxPooling1D):
    def __init__(self, pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", name: Optional[str] = None):
        _check_padding("MaxPooling1D", padding)
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, name=name)


class MaxPooling2D(k1.MaxPooling2D):
    def __init__(self, pool_size=(2, 2), strides=None, padding: str = "valid",
                 name: Optional[str] = None):
        _check_padding("MaxPooling2D", padding)
        super().__init__(pool_size=_pair(pool_size), strides=strides,
                         border_mode=padding, name=name)


class AveragePooling1D(k1.AveragePooling1D):
    def __init__(self, pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", name: Optional[str] = None):
        _check_padding("AveragePooling1D", padding)
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, name=name)


class AveragePooling2D(k1.AveragePooling2D):
    def __init__(self, pool_size=(2, 2), strides=None, padding: str = "valid",
                 name: Optional[str] = None):
        _check_padding("AveragePooling2D", padding)
        super().__init__(pool_size=_pair(pool_size), strides=strides,
                         border_mode=padding, name=name)


class GlobalAveragePooling1D(k1.GlobalAveragePooling1D):
    pass


class GlobalAveragePooling2D(k1.GlobalAveragePooling2D):
    pass


class GlobalAveragePooling3D(k1.GlobalAveragePooling3D):
    pass


class GlobalMaxPooling1D(k1.GlobalMaxPooling1D):
    pass


class GlobalMaxPooling2D(k1.GlobalMaxPooling2D):
    pass


class GlobalMaxPooling3D(k1.GlobalMaxPooling3D):
    pass


class Cropping1D(k1.Cropping1D):
    def __init__(self, cropping=(1, 1), name: Optional[str] = None):
        super().__init__(cropping=cropping, name=name)


class LocallyConnected1D(k1.LocallyConnected1D):
    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 activation=None, use_bias: bool = True,
                 name: Optional[str] = None, **kwargs):
        _reject_unknown("LocallyConnected1D", kwargs)
        super().__init__(filters, kernel_size, activation=activation,
                         subsample_length=strides, bias=use_bias, name=name)


class Embedding(k1.Embedding):
    def __init__(self, input_dim: int, output_dim: int,
                 embeddings_initializer="uniform",
                 name: Optional[str] = None, **kwargs):
        _reject_unknown("Embedding", kwargs)
        super().__init__(input_dim, output_dim,
                         init=embeddings_initializer, name=name)


class BatchNormalization(k1.BatchNormalization):
    def __init__(self, axis: int = -1, momentum: float = 0.99,
                 epsilon: float = 1e-3, name: Optional[str] = None,
                 **kwargs):
        _reject_unknown("BatchNormalization", kwargs)
        super().__init__(epsilon=epsilon, momentum=momentum, axis=axis,
                         name=name)


# -- merge layers (reference keras2 Maximum/Minimum/Average) ----------------


def maximum(inputs, name: Optional[str] = None):
    return k1.merge(inputs, mode="max", name=name)


def minimum(inputs, name: Optional[str] = None):
    return k1.merge(inputs, mode="min", name=name)


def average(inputs, name: Optional[str] = None):
    return k1.merge(inputs, mode="ave", name=name)


def add(inputs, name: Optional[str] = None):
    return k1.merge(inputs, mode="sum", name=name)


def multiply(inputs, name: Optional[str] = None):
    return k1.merge(inputs, mode="mul", name=name)


def concatenate(inputs, axis: int = -1, name: Optional[str] = None):
    return k1.merge(inputs, mode="concat", concat_axis=axis, name=name)
