"""XShard — distributed pandas shards (reference ``pyzoo/zoo/xshard/``:
``DataShards:20``, ``RayDataShards:42``, ``SparkDataShards:103``,
``read_file_ray/read_file_spark``).

TPU-host shape: shards are pandas partitions processed by a local process
pool (the Ray/Spark executor role); ``apply`` maps a function over every
shard in parallel, ``collect`` gathers, ``repartition`` rebalances. On a
multi-host pod each host builds its own DataShards over its slice of files
(the per-host shard_index contract)."""
from __future__ import annotations

import glob
import os
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class DataShards:
    def __init__(self, shards: List[Any], parallelism: Optional[int] = None,
                 use_processes: bool = False):
        self.shards = list(shards)
        self.parallelism = parallelism or min(8, os.cpu_count() or 1)
        self.use_processes = use_processes

    def _pool(self):
        cls = ProcessPoolExecutor if self.use_processes else ThreadPoolExecutor
        return cls(max_workers=self.parallelism)

    def apply(self, fn: Callable[[Any], Any], *args) -> "DataShards":
        """Map ``fn(shard, *args)`` over all shards in parallel (reference
        ``DataShards.apply``)."""
        if len(self.shards) == 1:
            return DataShards([fn(self.shards[0], *args)], self.parallelism,
                              self.use_processes)
        with self._pool() as pool:
            futures = [pool.submit(fn, s, *args) for s in self.shards]
            out = [f.result() for f in futures]
        return DataShards(out, self.parallelism, self.use_processes)

    def transform_shard(self, fn: Callable, *args) -> "DataShards":
        return self.apply(fn, *args)  # reference alias

    def collect(self) -> List[Any]:
        return list(self.shards)

    def concat_to_pandas(self):
        import pandas as pd
        return pd.concat(self.shards, ignore_index=True)

    def num_partitions(self) -> int:
        return len(self.shards)

    def repartition(self, n: int) -> "DataShards":
        """Rebalance pandas shards into ``n`` partitions by row-range
        offsets: each output part concatenates only the shard SLICES that
        overlap its row range (``np.array_split`` size convention), so
        the whole frame is never materialized in the driver — the seed
        did a full ``pd.concat`` + per-part ``iloc``, two dataset-sized
        copies."""
        import pandas as pd
        n = max(1, int(n))
        sizes = np.array([len(s) for s in self.shards], dtype=np.int64)
        starts = np.concatenate([[0], np.cumsum(sizes)])
        total = int(starts[-1])
        part_sizes = np.full(n, total // n, dtype=np.int64)
        part_sizes[:total % n] += 1
        bounds = np.concatenate([[0], np.cumsum(part_sizes)])
        parts = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            i = max(0, int(np.searchsorted(starts, lo, side="right")) - 1)
            pieces = []
            while i < len(self.shards) and starts[i] < hi:
                s0 = int(starts[i])
                a, b = max(int(lo) - s0, 0), min(int(hi) - s0, int(sizes[i]))
                if b > a:
                    pieces.append(self.shards[i].iloc[a:b])
                i += 1
            if not pieces:
                pieces = [self.shards[0].iloc[0:0]]
            part = (pd.concat(pieces, ignore_index=True) if len(pieces) > 1
                    else pieces[0].reset_index(drop=True))
            parts.append(part)
        return DataShards(parts, self.parallelism, self.use_processes)

    def to_xshard(self, engine=None):
        """Bridge into the partitioned ETL engine (one XShard block per
        shard): shuffle ops, disk spill and the zero-copy
        ``to_featureset`` handoff — see ``docs/xshard.md``."""
        from .engine import XShard
        return XShard.from_shards(self.shards, engine=engine)

    def to_featureset(self, feature_cols: Sequence[str],
                      label_cols: Optional[Sequence[str]] = None,
                      stack: bool = True, **kwargs):
        """Lower the shards into a FeatureSet. With ``stack`` (default) the
        feature columns are assembled into one ``[B, K]`` float matrix (the
        reference's VectorAssembler-style tabular contract, ``(B, 1)`` for a
        single column); ``stack=False`` keeps them as separate model
        inputs."""
        from ..feature.featureset import FeatureSet
        return FeatureSet.from_dataframe(self.concat_to_pandas(),
                                         feature_cols, label_cols,
                                         stack=stack, **kwargs)


def _expand(path: str, exts: Sequence[str]) -> List[str]:
    if os.path.isdir(path):
        files: List[str] = []
        for e in exts:
            files.extend(sorted(glob.glob(os.path.join(path, f"*{e}"))))
        return files
    return sorted(glob.glob(path)) or [path]


def _read(path: str, exts: Sequence[str], reader: Callable,
          num_shards: Optional[int], **pandas_kwargs) -> DataShards:
    """One shard per matched file; falls back to ``reader(path)`` when the
    dir glob matches nothing (e.g. a hive-partitioned parquet dataset dir,
    which pandas reads natively)."""
    files = _expand(path, exts)
    if not files:
        files = [path]
    if len(files) > 1:
        # fan file loads over a thread pool — a 100-file parquet dir
        # cold-starts in parallel instead of one file at a time (pandas
        # IO/decompression releases the GIL for long stretches)
        with ThreadPoolExecutor(
                max_workers=min(8, len(files), os.cpu_count() or 1)) as pool:
            dfs = list(pool.map(
                lambda f: reader(f, **pandas_kwargs), files))
    else:
        dfs = [reader(files[0], **pandas_kwargs)]
    shards = DataShards(dfs)
    if num_shards and num_shards != len(dfs):
        shards = shards.repartition(num_shards)
    return shards


def read_csv(path: str, num_shards: Optional[int] = None,
             **pandas_kwargs) -> DataShards:
    """Read csv file(s)/dir/glob into shards (reference ``read_csv``:
    one shard per file, or row-split when a single file)."""
    import pandas as pd
    return _read(path, [".csv"], pd.read_csv, num_shards, **pandas_kwargs)


def read_json(path: str, num_shards: Optional[int] = None,
              **pandas_kwargs) -> DataShards:
    import pandas as pd
    return _read(path, [".json", ".jsonl"], pd.read_json, num_shards,
                 **pandas_kwargs)


def read_parquet(path: str, num_shards: Optional[int] = None,
                 **pandas_kwargs) -> DataShards:
    """Read parquet file(s)/dir/glob into shards (reference XShards
    ``read_parquet``; columnar files are the Criteo-scale interchange
    format). A partitioned dataset directory (no top-level ``*.parquet``)
    is read whole via pandas' native dataset support."""
    import pandas as pd
    return _read(path, [".parquet", ".pq"], pd.read_parquet, num_shards,
                 **pandas_kwargs)
