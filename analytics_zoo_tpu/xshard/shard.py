"""XShard — distributed pandas shards (reference ``pyzoo/zoo/xshard/``:
``DataShards:20``, ``RayDataShards:42``, ``SparkDataShards:103``,
``read_file_ray/read_file_spark``).

TPU-host shape: shards are pandas partitions processed by a local process
pool (the Ray/Spark executor role); ``apply`` maps a function over every
shard in parallel, ``collect`` gathers, ``repartition`` rebalances. On a
multi-host pod each host builds its own DataShards over its slice of files
(the per-host shard_index contract)."""
from __future__ import annotations

import glob
import os
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class DataShards:
    def __init__(self, shards: List[Any], parallelism: Optional[int] = None,
                 use_processes: bool = False):
        self.shards = list(shards)
        self.parallelism = parallelism or min(8, os.cpu_count() or 1)
        self.use_processes = use_processes

    def _pool(self):
        cls = ProcessPoolExecutor if self.use_processes else ThreadPoolExecutor
        return cls(max_workers=self.parallelism)

    def apply(self, fn: Callable[[Any], Any], *args) -> "DataShards":
        """Map ``fn(shard, *args)`` over all shards in parallel (reference
        ``DataShards.apply``)."""
        if len(self.shards) == 1:
            return DataShards([fn(self.shards[0], *args)], self.parallelism,
                              self.use_processes)
        with self._pool() as pool:
            futures = [pool.submit(fn, s, *args) for s in self.shards]
            out = [f.result() for f in futures]
        return DataShards(out, self.parallelism, self.use_processes)

    def transform_shard(self, fn: Callable, *args) -> "DataShards":
        return self.apply(fn, *args)  # reference alias

    def collect(self) -> List[Any]:
        return list(self.shards)

    def concat_to_pandas(self):
        import pandas as pd
        return pd.concat(self.shards, ignore_index=True)

    def num_partitions(self) -> int:
        return len(self.shards)

    def repartition(self, n: int) -> "DataShards":
        """Rebalance pandas shards into ``n`` partitions."""
        import pandas as pd
        whole = pd.concat(self.shards, ignore_index=True)
        parts = np.array_split(np.arange(len(whole)), n)
        return DataShards([whole.iloc[p].reset_index(drop=True)
                           for p in parts], self.parallelism,
                          self.use_processes)

    def to_featureset(self, feature_cols: Sequence[str],
                      label_cols: Optional[Sequence[str]] = None,
                      stack: bool = True, **kwargs):
        """Lower the shards into a FeatureSet. With ``stack`` (default) the
        feature columns are assembled into one ``[B, K]`` float matrix (the
        reference's VectorAssembler-style tabular contract, ``(B, 1)`` for a
        single column); ``stack=False`` keeps them as separate model
        inputs."""
        from ..feature.featureset import FeatureSet
        return FeatureSet.from_dataframe(self.concat_to_pandas(),
                                         feature_cols, label_cols,
                                         stack=stack, **kwargs)


def _expand(path: str, exts: Sequence[str]) -> List[str]:
    if os.path.isdir(path):
        files: List[str] = []
        for e in exts:
            files.extend(sorted(glob.glob(os.path.join(path, f"*{e}"))))
        return files
    return sorted(glob.glob(path)) or [path]


def _read(path: str, exts: Sequence[str], reader: Callable,
          num_shards: Optional[int], **pandas_kwargs) -> DataShards:
    """One shard per matched file; falls back to ``reader(path)`` when the
    dir glob matches nothing (e.g. a hive-partitioned parquet dataset dir,
    which pandas reads natively)."""
    files = _expand(path, exts)
    if not files:
        files = [path]
    dfs = [reader(f, **pandas_kwargs) for f in files]
    shards = DataShards(dfs)
    if num_shards and num_shards != len(dfs):
        shards = shards.repartition(num_shards)
    return shards


def read_csv(path: str, num_shards: Optional[int] = None,
             **pandas_kwargs) -> DataShards:
    """Read csv file(s)/dir/glob into shards (reference ``read_csv``:
    one shard per file, or row-split when a single file)."""
    import pandas as pd
    return _read(path, [".csv"], pd.read_csv, num_shards, **pandas_kwargs)


def read_json(path: str, num_shards: Optional[int] = None,
              **pandas_kwargs) -> DataShards:
    import pandas as pd
    return _read(path, [".json", ".jsonl"], pd.read_json, num_shards,
                 **pandas_kwargs)


def read_parquet(path: str, num_shards: Optional[int] = None,
                 **pandas_kwargs) -> DataShards:
    """Read parquet file(s)/dir/glob into shards (reference XShards
    ``read_parquet``; columnar files are the Criteo-scale interchange
    format). A partitioned dataset directory (no top-level ``*.parquet``)
    is read whole via pandas' native dataset support."""
    import pandas as pd
    return _read(path, [".parquet", ".pq"], pd.read_parquet, num_shards,
                 **pandas_kwargs)
