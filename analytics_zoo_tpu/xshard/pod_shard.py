"""PodDataShards — distributed pandas shards over pod worker processes.

The reference's distributed XShards put pandas partitions on Ray actors /
Spark executors (``pyzoo/zoo/xshard/shard.py:42`` ``RayDataShards``, ``:103``
``SparkDataShards``) with a driver-side handle. The TPU-native equivalent
reuses the framework's pod orchestration (``cluster/launcher.py``): the
driver handle records WHAT to read and WHICH transforms to apply (a lazy op
chain, like the reference's chained ``transform_shard``); an action
(``collect``/``to_featureset``/``count``) launches workers that each process
the ``rank::num_workers`` stride of files and spool results through the
scheme-aware filesystem layer — so the spool (and the input files) can live
on gs:// for real multi-host pods.

The op chain serializes via cloudpickle when available — __main__-defined
functions and closures work, the same ergonomics Ray provides — falling
back to plain pickle (module-level functions only).
"""
from __future__ import annotations

import os
import pickle
import tempfile

from typing import Any, Callable, List, Optional, Sequence

from ..common import file_io
from ..common import pickling
from ..common.pickling import pickler as _pickler
from .shard import DataShards, _expand

_READERS = {"csv": "read_csv", "json": "read_json", "parquet": "read_parquet"}


def _xshard_worker(spool: str) -> int:
    """Worker target (under ``cluster.bootstrap``): read this rank's files,
    run the op chain, spool the resulting shards."""
    import pandas as pd
    rank = int(os.environ["ZOO_TPU_PROC_ID"])
    nprocs = int(os.environ["ZOO_TPU_NPROCS"])
    with file_io.fopen(file_io.join(spool, "job.pkl"), "rb") as f:
        job = pickle.load(f)
    reader = getattr(pd, _READERS[job["format"]])
    out: List[Any] = []
    for idx in range(rank, len(job["files"]), nprocs):
        shard = reader(job["files"][idx], **job["reader_kwargs"])
        for fn, args in job["ops"]:
            shard = fn(shard, *args)
        out.append((idx, shard))
    payload = _pickler.dumps(out)
    tmp = file_io.join(spool, f".out_{rank}.pkl")
    with file_io.fopen(tmp, "wb") as f:
        f.write(payload)
    file_io.replace(tmp, file_io.join(spool, f"out_{rank}.pkl"))
    return 0


class PodDataShards:
    """Driver-side handle to shards processed by pod workers."""

    def __init__(self, files: Sequence[str], fmt: str,
                 num_workers: int = 2,
                 reader_kwargs: Optional[dict] = None,
                 ops: Optional[List] = None,
                 timeout: Optional[float] = None,
                 spool_dir: Optional[str] = None):
        if fmt not in _READERS:
            raise ValueError(f"format must be one of {sorted(_READERS)}")
        if not files:
            raise ValueError("no input files")
        self.files = list(files)
        self.fmt = fmt
        self.num_workers = num_workers
        self.reader_kwargs = dict(reader_kwargs or {})
        self.ops = list(ops or [])
        self.timeout = timeout
        self.spool_dir = spool_dir

    # -- constructors (reference read_file_ray/read_file_spark) ---------------

    @classmethod
    def read_csv(cls, path: str, num_workers: int = 2,
                 timeout: Optional[float] = None, **pandas_kwargs):
        return cls(_expand(path, [".csv"]), "csv", num_workers,
                   reader_kwargs=pandas_kwargs, timeout=timeout)

    @classmethod
    def read_json(cls, path: str, num_workers: int = 2,
                  timeout: Optional[float] = None, **pandas_kwargs):
        return cls(_expand(path, [".json", ".jsonl"]), "json", num_workers,
                   reader_kwargs=pandas_kwargs, timeout=timeout)

    @classmethod
    def read_parquet(cls, path: str, num_workers: int = 2,
                     timeout: Optional[float] = None, **pandas_kwargs):
        return cls(_expand(path, [".parquet", ".pq"]), "parquet",
                   num_workers, reader_kwargs=pandas_kwargs, timeout=timeout)

    # -- lazy transforms ------------------------------------------------------

    def transform_shard(self, fn: Callable, *args) -> "PodDataShards":
        """Append ``fn(shard, *args)`` to the op chain (lazy — runs in the
        workers at the next action). Lambdas and closures work (cloudpickle
        serialization, see ``common.pickling``)."""
        return PodDataShards(self.files, self.fmt, self.num_workers,
                             self.reader_kwargs, self.ops + [(fn, args)],
                             self.timeout, self.spool_dir)

    apply = transform_shard

    def num_partitions(self) -> int:
        return len(self.files)

    # -- actions (launch the pod) ---------------------------------------------

    def _run(self) -> List[Any]:
        job = {"files": self.files, "format": self.fmt,
               "reader_kwargs": self.reader_kwargs, "ops": self.ops}
        try:
            blob = _pickler.dumps(job)
        except Exception as e:
            raise ValueError(
                "PodDataShards needs serializable transforms "
                f"({pickling.capability_note()}): {e!r}")
        # caller-provided spool dirs (e.g. gs:// for multi-host) are the
        # caller's to manage; auto-created temp spools are always removed
        own_spool = not self.spool_dir
        spool = self.spool_dir or tempfile.mkdtemp(prefix="zoo_xshard_")
        file_io.makedirs(spool)
        try:
            with file_io.fopen(file_io.join(spool, "job.pkl"), "wb") as f:
                f.write(blob)
            from ..cluster.launcher import run_pod
            nprocs = min(self.num_workers, len(self.files))
            run_pod("analytics_zoo_tpu.xshard.pod_shard:_xshard_worker",
                    nprocs, args=[spool], platform="cpu",
                    timeout=self.timeout)
            indexed: List[Any] = []
            for rank in range(nprocs):
                path = file_io.join(spool, f"out_{rank}.pkl")
                if not file_io.exists(path):
                    raise RuntimeError(
                        f"xshard worker {rank} wrote no output")
                with file_io.fopen(path, "rb") as f:
                    indexed.extend(pickle.loads(f.read()))
        finally:
            if own_spool:
                import shutil
                shutil.rmtree(spool, ignore_errors=True)
        indexed.sort(key=lambda t: t[0])  # stable file order
        return [shard for _, shard in indexed]

    def collect(self) -> List[Any]:
        return self._run()

    def to_local(self) -> DataShards:
        """Materialize on the driver as local :class:`DataShards`."""
        return DataShards(self._run())

    def concat_to_pandas(self):
        import pandas as pd
        return pd.concat(self._run(), ignore_index=True)

    def to_featureset(self, feature_cols: Sequence[str],
                      label_cols: Optional[Sequence[str]] = None,
                      stack: bool = True, **kwargs):
        from ..feature.featureset import FeatureSet
        return FeatureSet.from_dataframe(self.concat_to_pandas(),
                                         feature_cols, label_cols,
                                         stack=stack, **kwargs)
